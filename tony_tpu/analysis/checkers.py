"""The graft-lint checkers (docs/ANALYSIS.md has the catalogue with
bad/good examples per code).

=======  ====================  ==============================================
code     name                  what it catches
=======  ====================  ==============================================
GL001    host-sync-in-jit      ``.item()`` / ``float(tracer)`` / ``np.asarray``
                               / ``jax.device_get`` / ``print`` in functions
                               reachable from a jit entry point
GL002    recompile-hazard      ``jax.jit`` in a loop, jit-of-partial in a
                               loop (shape-keyed bucket dispatch re-jitting
                               per step), ``jit(partial(...))(...)`` built
                               and called in one expression (per-dispatch
                               rebuild — the MoE routing shape),
                               jit-of-lambda inside a function body, Python
                               branch on a traced value, mutable default
                               behind ``static_argnums``
GL003    donation-reuse        reading an argument after passing it to a
                               ``donate_argnums`` jit in the same scope
GL004    lock-discipline       blocking calls (sleep, unbounded join/wait/
                               queue-get, file I/O, RPC-ish backend/client
                               calls) while a lock is held; cross-module
                               lock-order inversions
GL005    disarmed-hook-cost    chaos/trace/hbm/health/series/profile hook call
                               sites whose arguments allocate or call before
                               the armed check
=======  ====================  ==============================================

Checkers are tuned to under-approximate (see analysis/callgraph.py): the
tier-1 zero-findings gate only works if a clean tree needs no blanket
suppressions.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from tony_tpu.analysis.callgraph import Project, dotted, unwrap_partial
from tony_tpu.analysis.core import Finding

# attribute reads that are static under tracing (never a host sync and
# never tracer-valued themselves)
_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "sharding", "device", "aval",
    "itemsize", "nbytes",
}

# array-producing namespaces: a value returned by these is tracer-typed
# inside a traced function
_TRACER_EXCLUDE = {"jax.device_get", "jax.block_until_ready"}


def _is_jnpish(resolved: str | None) -> bool:
    """Does this callee produce traced array values? Restricted to the
    array namespaces — general ``jax.*`` API calls (mesh/axis-env/sharding
    introspection) return static metadata and must not taint locals."""
    if not resolved or resolved in _TRACER_EXCLUDE:
        return False
    head = resolved.split(".", 1)[0]
    return head in ("jnp", "lax") or resolved.startswith(
        ("jax.numpy.", "jnp.", "lax.", "jax.lax.", "jax.nn.", "jax.random.",
         "jax.scipy.")
    )


def walk_own(root: ast.AST, *, skip_nested: bool = True) -> Iterator[ast.AST]:
    """Walk ``root``'s body without descending into nested function/class
    definitions (they are analyzed as their own symbols)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if skip_nested and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _Emitter:
    """Finding factory that keeps fingerprints unique when the same
    (code, path, symbol, detail) occurs more than once."""

    def __init__(self, code: str):
        self.code = code
        self._seen: dict[str, int] = {}

    def emit(self, path: str, line: int, symbol: str, message: str,
             detail: str) -> Finding:
        base = f"{self.code}|{path}|{symbol}|{detail}"
        n = self._seen[base] = self._seen.get(base, 0) + 1
        if n > 1:
            detail = f"{detail}#{n}"
        return Finding(self.code, path, line, symbol, message, detail)


def _tracerish_names(project: Project, mi, func) -> set[str]:
    """Local names (conservatively) holding traced array values: assigned
    from jnp/lax/jax.* calls or arithmetic on such names. Function
    parameters are NOT assumed traced (they are often static configs) —
    an under-approximation by design."""
    names: set[str] = set()

    def value_is_tracer(node: ast.expr) -> bool:
        if isinstance(node, ast.Call):
            return _is_jnpish(project.dotted_resolved(mi, node.func))
        if isinstance(node, ast.Name):
            return node.id in names
        if isinstance(node, ast.BinOp):
            return value_is_tracer(node.left) or value_is_tracer(node.right)
        if isinstance(node, ast.UnaryOp):
            return value_is_tracer(node.operand)
        if isinstance(node, ast.Compare):
            return value_is_tracer(node.left) or any(
                value_is_tracer(c) for c in node.comparators
            )
        if isinstance(node, ast.Subscript):
            return value_is_tracer(node.value)
        if isinstance(node, ast.IfExp):
            return value_is_tracer(node.body) or value_is_tracer(node.orelse)
        if isinstance(node, ast.Attribute):
            # x.shape / x.dtype are static; x.T / x.at results stay traced
            if node.attr in _STATIC_ATTRS:
                return False
            return value_is_tracer(node.value)
        return False

    stmts = sorted(
        (n for n in walk_own(func.node)
         if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign))),
        key=lambda n: (n.lineno, n.col_offset),
    )
    for stmt in stmts:
        value = stmt.value
        if value is None:
            continue
        if not value_is_tracer(value):
            continue
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for t in targets:
            for el in ([t] if not isinstance(t, (ast.Tuple, ast.List)) else t.elts):
                if isinstance(el, ast.Name):
                    names.add(el.id)
    return names


def _uses_tracer(project: Project, mi, expr: ast.expr, names: set[str]) -> bool:
    """Does ``expr`` read a tracer-ish value (skipping static attrs)?"""
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return False
        return _uses_tracer(project, mi, expr.value, names)
    if isinstance(expr, ast.Name):
        return expr.id in names
    if isinstance(expr, ast.Call):
        if _is_jnpish(project.dotted_resolved(mi, expr.func)):
            return True
        # a method call on a traced receiver (y.mean(), y.any()) yields a
        # traced value unless the attribute is static metadata
        if (isinstance(expr.func, ast.Attribute)
                and expr.func.attr not in _STATIC_ATTRS
                and _uses_tracer(project, mi, expr.func.value, names)):
            return True
        return any(_uses_tracer(project, mi, a, names) for a in expr.args)
    return any(
        _uses_tracer(project, mi, child, names)
        for child in ast.iter_child_nodes(expr)
        if isinstance(child, ast.expr)
    )


# --- GL001 -------------------------------------------------------------------


class HostSyncInJit:
    CODE = "GL001"
    NAME = "host-sync-in-jit"

    _SYNC_ATTRS = {"item", "tolist", "block_until_ready"}

    def run(self, project: Project) -> Iterator[Finding]:
        em = _Emitter(self.CODE)
        for qual, root in sorted(project.traced_from.items()):
            fi = project.funcs.get(qual)
            if fi is None:
                continue
            mi = project.modules[fi.module]
            path = mi.sf.path
            tracerish = _tracerish_names(project, mi, fi)
            reach = f"reachable from jitted entry `{root.split(':', 1)[1]}`"
            for node in walk_own(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                resolved = project.dotted_resolved(mi, node.func) or ""
                last = resolved.rsplit(".", 1)[-1]
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in self._SYNC_ATTRS):
                    yield em.emit(
                        path, node.lineno, fi.local,
                        f"`.{node.func.attr}()` forces a device sync inside "
                        f"traced code ({reach}); move it outside the jitted "
                        "path or return the value",
                        f".{node.func.attr}()",
                    )
                elif resolved == "jax.device_get":
                    yield em.emit(
                        path, node.lineno, fi.local,
                        f"`jax.device_get` inside traced code ({reach}) "
                        "host-syncs every trace; hoist it to the caller",
                        "jax.device_get",
                    )
                elif (resolved.split(".", 1)[0] in ("numpy", "np", "onp")
                      and last in ("asarray", "array")):
                    yield em.emit(
                        path, node.lineno, fi.local,
                        f"`{resolved}` materialises a traced value on host "
                        f"({reach}); use jnp, or move the conversion out of "
                        "the jitted path",
                        resolved,
                    )
                elif resolved in ("float", "int", "bool") and node.args and (
                    _uses_tracer(project, mi, node.args[0], tracerish)
                ):
                    yield em.emit(
                        path, node.lineno, fi.local,
                        f"`{resolved}()` on a traced value ({reach}) blocks "
                        "on the device (ConcretizationError on newer jax); "
                        "keep it an array or sync outside the jitted path",
                        f"{resolved}()",
                    )
                elif resolved == "print":
                    yield em.emit(
                        path, node.lineno, fi.local,
                        f"`print` inside traced code ({reach}) runs at trace "
                        "time only (or syncs under jit); use jax.debug.print",
                        "print",
                    )


# --- GL002 -------------------------------------------------------------------


class RecompileHazard:
    CODE = "GL002"
    NAME = "recompile-hazard"

    _MUTABLE_DEFAULTS = (ast.List, ast.Dict, ast.Set)

    def run(self, project: Project) -> Iterator[Finding]:
        em = _Emitter(self.CODE)
        yield from self._jit_in_loop(project, em)
        yield from self._jit_per_dispatch(project, em)
        yield from self._jit_call_hazards(project, em)
        yield from self._branch_on_tracer(project, em)

    def _jit_per_dispatch(self, project: Project, em: _Emitter) -> Iterator[Finding]:
        """``jax.jit(partial(...))(x)`` built and invoked in ONE expression
        inside a function body — the per-dispatch twin of the in-loop
        case (the MoE routing-path shape: re-wrapping a dispatch kernel
        around the current config on every routing call). The partial is
        a fresh callable per call, so the jit cache key never repeats and
        every dispatch recompiles — no loop needed, the caller IS the
        loop. Hoisted jit-of-partial (assigned once, dispatched later)
        and cached factories stay silent."""
        for mi in project.modules.values():
            for fi in mi.funcs.values():
                for node in walk_own(fi.node):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Call)):
                        continue
                    inner = node.func
                    if project.dotted_resolved(mi, inner.func) not in (
                            "jax.jit", "jit", "pjit", "jax.pjit"):
                        continue
                    wrapped = inner.args[0] if inner.args else None
                    if (isinstance(wrapped, ast.Call)
                            and project.dotted_resolved(mi, wrapped.func)
                            in ("functools.partial", "partial")):
                        yield em.emit(
                            mi.sf.path, node.lineno, fi.local,
                            "`jax.jit(partial(...))(...)` built and called "
                            "in one expression: the partial is a fresh "
                            "callable every dispatch, so the jit cache "
                            "never hits and every call recompiles — the "
                            "per-dispatch twin of the in-loop hazard. "
                            "Build the jitted callable once (hoist it, or "
                            "memoize keyed by the static config) and "
                            "dispatch through it",
                            "jit-per-dispatch",
                        )

    def _jit_in_loop(self, project: Project, em: _Emitter) -> Iterator[Finding]:
        for mi in project.modules.values():
            for fi in mi.funcs.values():
                loops: list[ast.AST] = []

                def visit(node: ast.AST) -> Iterator[Finding]:
                    for child in ast.iter_child_nodes(node):
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.ClassDef)):
                            continue
                        is_loop = isinstance(child, (ast.For, ast.While,
                                                     ast.AsyncFor))
                        if (loops and isinstance(child, ast.Call)
                                and project.dotted_resolved(mi, child.func)
                                in ("jax.jit", "jit", "pjit", "jax.pjit")):
                            # refine the in-loop case: jit of a fresh
                            # functools.partial is the bucketed-collective
                            # regression shape — a per-step shape-keyed
                            # dispatch that rebuilds the partial (and so
                            # the jit cache key) every iteration, so every
                            # bucket recompiles every step even when its
                            # shapes repeat
                            wrapped = child.args[0] if child.args else None
                            if (isinstance(wrapped, ast.Call)
                                    and project.dotted_resolved(
                                        mi, wrapped.func)
                                    in ("functools.partial", "partial")):
                                yield em.emit(
                                    mi.sf.path, child.lineno, fi.local,
                                    "`jax.jit(partial(...))` inside a loop: "
                                    "the partial is a fresh callable every "
                                    "iteration so the jit cache never hits "
                                    "— a shape-keyed bucket dispatch "
                                    "re-jits every bucket every step. "
                                    "Build the jitted callable once per "
                                    "distinct plan (hoist it, or memoize "
                                    "keyed by the static shapes) and "
                                    "dispatch through it",
                                    "shape-keyed-jit-in-loop",
                                )
                            else:
                                yield em.emit(
                                    mi.sf.path, child.lineno, fi.local,
                                    "`jax.jit` inside a loop builds a fresh "
                                    "jitted callable (and cache entry) every "
                                    "iteration — hoist it out of the loop",
                                    "jit-in-loop",
                                )
                        if is_loop:
                            loops.append(child)
                        yield from visit(child)
                        if is_loop:
                            loops.pop()

                yield from visit(fi.node)

    def _jit_call_hazards(self, project: Project, em: _Emitter) -> Iterator[Finding]:
        for jc in project.jit_calls:
            mi = project.modules[jc.module]
            symbol = jc.func.local if jc.func is not None else ""
            fn_node = unwrap_partial(jc.node.args[0]) if jc.node.args else None
            if jc.func is not None and isinstance(fn_node, ast.Lambda):
                yield em.emit(
                    mi.sf.path, jc.node.lineno, symbol,
                    "jit of a lambda inside a function body: the lambda is "
                    "a fresh object per call, so the jit cache never hits "
                    "and every invocation recompiles — define the function "
                    "once (module level or cached factory)",
                    "jit-of-lambda",
                )
            if jc.target is not None and (jc.static_argnums or jc.static_argnames):
                args = jc.target.node.args
                params = list(args.posonlyargs) + list(args.args)
                defaults = list(args.defaults)
                # defaults align to the tail of the positional params
                default_of = dict(
                    zip([p.arg for p in params[len(params) - len(defaults):]],
                        defaults)
                )
                static_names = set(jc.static_argnames)
                for i in jc.static_argnums:
                    if 0 <= i < len(params):
                        static_names.add(params[i].arg)
                for name in sorted(static_names):
                    d = default_of.get(name)
                    if isinstance(d, self._MUTABLE_DEFAULTS):
                        yield em.emit(
                            mi.sf.path, jc.node.lineno, symbol,
                            f"static arg `{name}` of `{jc.target.local}` has "
                            "a non-hashable (mutable) default: jit static "
                            "args must hash, and a per-call-fresh object "
                            "recompiles every call",
                            f"static-unhashable:{name}",
                        )

    def _branch_on_tracer(self, project: Project, em: _Emitter) -> Iterator[Finding]:
        for qual in sorted(project.traced_from):
            fi = project.funcs.get(qual)
            if fi is None:
                continue
            mi = project.modules[fi.module]
            tracerish = _tracerish_names(project, mi, fi)
            if not tracerish:
                continue
            for node in walk_own(fi.node):
                cond = None
                kind = ""
                if isinstance(node, (ast.If, ast.While)):
                    cond, kind = node.test, type(node).__name__.lower()
                elif isinstance(node, ast.IfExp):
                    cond, kind = node.test, "conditional expression"
                elif isinstance(node, ast.Assert):
                    cond, kind = node.test, "assert"
                if cond is None or not _uses_tracer(project, mi, cond, tracerish):
                    continue
                yield em.emit(
                    mi.sf.path, node.lineno, fi.local,
                    f"Python `{kind}` on a traced value inside traced code: "
                    "concretizes the tracer (error or silent recompile per "
                    "branch) — use jnp.where / lax.cond / lax.select",
                    f"branch-on-tracer:{kind}",
                )


# --- GL003 -------------------------------------------------------------------


class DonationReuse:
    CODE = "GL003"
    NAME = "donation-reuse"

    def run(self, project: Project) -> Iterator[Finding]:
        em = _Emitter(self.CODE)
        for mi in project.modules.values():
            module_donors = self._donors(project, mi, mi.sf.tree.body)
            # module-level straight-line use
            yield from self._check_scope(
                project, mi, None, mi.sf.tree.body, dict(module_donors), em
            )
            for fi in mi.funcs.values():
                body = list(getattr(fi.node, "body", []))
                donors = dict(module_donors)
                donors.update(self._donors(project, mi, body))
                yield from self._check_scope(project, mi, fi, body, donors, em)

    def _donors(self, project: Project, mi, body: list[ast.stmt]
                ) -> dict[str, tuple[int, ...]]:
        """name -> donated argnums, for ``name = jax.jit(f, donate_argnums=...)``."""
        donors: dict[str, tuple[int, ...]] = {}
        for stmt in body:
            if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)):
                continue
            if project.dotted_resolved(mi, stmt.value.func) not in (
                "jax.jit", "jit", "pjit", "jax.pjit"
            ):
                continue
            donate = ()
            for kw in stmt.value.keywords:
                if kw.arg == "donate_argnums":
                    from tony_tpu.analysis.callgraph import _const_index_tuple

                    donate = _const_index_tuple(kw.value)
            if donate:
                donors[stmt.targets[0].id] = donate
        return donors

    def _check_scope(self, project: Project, mi, fi, body: list[ast.stmt],
                     donors: dict[str, tuple[int, ...]], em: _Emitter
                     ) -> Iterator[Finding]:
        if not donors:
            return
        symbol = fi.local if fi is not None else ""
        stmts = self._linear(body)
        for pos, stmt in enumerate(stmts):
            for call in self._own_calls(stmt):
                name = call.func.id if isinstance(call.func, ast.Name) else None
                if name not in donors:
                    continue
                for i in donors[name]:
                    if i >= len(call.args):
                        continue
                    arg = dotted(call.args[i])
                    if arg is None:
                        continue
                    yield from self._scan_after(
                        stmts, pos, stmt, arg, name, mi, symbol, em
                    )

    def _own_calls(self, stmt: ast.stmt) -> Iterator[ast.Call]:
        """Call nodes belonging to ``stmt`` itself. For compound statements
        only the header expressions count — their nested statements appear
        separately in the linearized list, where their own rebind handling
        (``state = step(state, b)`` in a loop body) applies."""
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            exprs: list[ast.expr] = [stmt.iter]
        elif isinstance(stmt, (ast.While, ast.If)):
            exprs = [stmt.test]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            exprs = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, (ast.Try, *(
            (ast.TryStar,) if hasattr(ast, "TryStar") else ()
        ))):
            exprs = []
        else:
            yield from (n for n in ast.walk(stmt) if isinstance(n, ast.Call))
            return
        for e in exprs:
            yield from (n for n in ast.walk(e) if isinstance(n, ast.Call))

    def _linear(self, body: list[ast.stmt]) -> list[ast.stmt]:
        """Flatten compound statements into source order, keeping each
        simple statement whole. Nested function/class definitions are their
        own scopes and are NOT flattened in — a donation in one function
        must not taint reads in another."""
        out: list[ast.stmt] = []
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            out.append(stmt)
            for attr in ("body", "orelse", "finalbody"):
                out.extend(self._linear(getattr(stmt, attr, []) or []))
            for h in getattr(stmt, "handlers", []) or []:
                out.extend(self._linear(h.body))
        return out

    def _rebinds(self, stmt: ast.stmt, name: str) -> bool:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            els = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            if any(dotted(el) == name for el in els):
                return True
        return False

    def _reads(self, stmt: ast.stmt, name: str, skip_call: ast.Call | None
               ) -> ast.AST | None:
        skip = set()
        if skip_call is not None:
            skip = {id(n) for n in ast.walk(skip_call)}
        for node in ast.walk(stmt):
            if id(node) in skip:
                continue
            if dotted(node) == name and isinstance(
                getattr(node, "ctx", None), ast.Load
            ):
                # the outermost node of an attr chain carries Load ctx
                return node
        return None

    def _scan_after(self, stmts, pos, call_stmt, arg: str, donor: str,
                    mi, symbol: str, em: _Emitter) -> Iterator[Finding]:
        # `x = donor(x)`: the rebind makes later reads safe
        if self._rebinds(call_stmt, arg):
            return
        for later in stmts[pos + 1:]:
            if later.lineno <= call_stmt.lineno:
                continue
            read = self._reads(later, arg, None)
            if read is not None:
                yield em.emit(
                    mi.sf.path, later.lineno, symbol,
                    f"`{arg}` was donated to `{donor}` (donate_argnums) and "
                    "is read afterwards: the buffer may already be reused — "
                    "rebind the result or drop the donation",
                    f"donated:{donor}:{arg}",
                )
                return
            if self._rebinds(later, arg):
                return


# --- GL004 -------------------------------------------------------------------


_LOCK_ATTR_RE = re.compile(r"(?:^|_)(?:lock|mutex)$")
_LOCK_CALL_RE = re.compile(r"(?:^|_)locked$")
_QUEUEISH_RE = re.compile(r"(?:^|_)(?:q|queue|notifications|inbox)$")
_RPCISH = {"backend", "client", "_client", "stub", "channel", "session_client"}
_FILEISH_RE = re.compile(r"(?:^|_)(?:f|fh|fp|file|sock)$")
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "sleep": "sleep",
    "os.replace": "file I/O (os.replace)",
    "os.rename": "file I/O (os.rename)",
    "os.makedirs": "file I/O (os.makedirs)",
    "shutil.copy": "file I/O", "shutil.copytree": "file I/O",
    "shutil.rmtree": "file I/O",
    "subprocess.run": "subprocess", "subprocess.check_output": "subprocess",
    "subprocess.check_call": "subprocess", "subprocess.Popen": "subprocess",
    "socket.create_connection": "network I/O",
    "open": "file I/O (open)",
    "json.dump": "file I/O (json.dump)",
    "json.load": "file I/O (json.load)",
}


class LockDiscipline:
    CODE = "GL004"
    NAME = "lock-discipline"

    def run(self, project: Project) -> Iterator[Finding]:
        em = _Emitter(self.CODE)
        # lock-order edges: (lockA, lockB) -> first location
        edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        for mi in project.modules.values():
            for fi in mi.funcs.values():
                yield from self._check_func(project, mi, fi, em, edges)
        yield from self._inversions(edges, em)

    # lock identity: "<module-tail>:<attr text minus self.>"
    def _lock_id(self, mi, expr: ast.expr) -> str | None:
        node = expr.func if isinstance(expr, ast.Call) else expr
        name = dotted(node)
        if name is None:
            return None
        last = name.rsplit(".", 1)[-1]
        if isinstance(expr, ast.Call):
            if not _LOCK_CALL_RE.search(last):
                return None
        elif not _LOCK_ATTR_RE.search(last):
            return None
        text = name[5:] if name.startswith("self.") else name
        modtail = mi.modname.rsplit(".", 1)[-1]
        return f"{modtail}:{text}"

    def _check_func(self, project: Project, mi, fi, em: _Emitter,
                    edges: dict) -> Iterator[Finding]:
        held: list[str] = []

        def visit_block(nodes) -> Iterator[Finding]:
            for child in nodes:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    locks_here: list[str] = []
                    for item in child.items:
                        # the context expr evaluates at acquisition time —
                        # scan it under the locks held SO FAR (a lock's own
                        # manager taking its lock is not a self-deadlock)
                        if held:
                            for call in (n for n in ast.walk(item.context_expr)
                                         if isinstance(n, ast.Call)):
                                lid = self._lock_id(mi, call)
                                if lid is None and self._lock_id(
                                    mi, call.func
                                ) is None:
                                    yield from self._check_call(
                                        project, mi, fi, call, held[-1],
                                        em, edges, depth=0,
                                    )
                        lid = self._lock_id(mi, item.context_expr)
                        if lid is not None:
                            if held:
                                edges.setdefault(
                                    (held[-1], lid),
                                    (mi.sf.path, child.lineno, fi.local),
                                )
                            locks_here.append(lid)
                    held.extend(locks_here)
                    yield from visit_block(child.body)
                    for _ in locks_here:
                        held.pop()
                    yield from visit_block(child.orelse if hasattr(child, "orelse") else [])
                    continue
                if held and isinstance(child, ast.Call):
                    yield from self._check_call(
                        project, mi, fi, child, held[-1], em, edges, depth=0
                    )
                yield from visit_block(ast.iter_child_nodes(child))

        yield from visit_block(ast.iter_child_nodes(fi.node))

    def _blocking_reason(self, project: Project, mi, call: ast.Call) -> str | None:
        resolved = project.dotted_resolved(mi, call.func) or ""
        if resolved in _BLOCKING_DOTTED:
            return _BLOCKING_DOTTED[resolved]
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        recv = dotted(call.func.value) or ""
        recv_last = recv.rsplit(".", 1)[-1]
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        nonblocking = any(
            kw.arg == "block" and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in call.keywords
        )
        if attr == "join" and not call.args and not has_timeout:
            return "unbounded .join()"
        if attr == "wait" and not call.args and not has_timeout:
            return "unbounded .wait()"
        if (attr == "get" and _QUEUEISH_RE.search(recv_last)
                and not has_timeout and not nonblocking and not call.args):
            return "blocking queue .get() without timeout"
        if attr in ("read", "write", "flush", "readline") and _FILEISH_RE.search(recv_last):
            return f"file I/O (.{attr})"
        parts = set(recv.replace("self.", "").split("."))
        if parts & _RPCISH:
            return f"RPC/subprocess-backed call ({recv}.{attr})"
        return None

    def _check_call(self, project: Project, mi, fi, call: ast.Call,
                    lock: str, em: _Emitter, edges: dict, depth: int
                    ) -> Iterator[Finding]:
        reason = self._blocking_reason(project, mi, call)
        name = dotted(call.func) or "<call>"
        if reason is not None:
            yield em.emit(
                mi.sf.path, call.lineno, fi.local,
                f"{reason} while holding `{lock}`: the lock is held across "
                "a call that can block — move the blocking work outside "
                "the locked region",
                f"{lock}:{name.replace('self.', '')}",
            )
            return
        if depth >= 1:
            return
        # one hop into analyzed callees: their direct blocking calls and
        # lock acquisitions count against the held lock
        target = project.resolve_callable(mi, fi, call.func)
        if target is None:
            return
        tmi = project.modules[target.module]
        for node in walk_own(target.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lid = self._lock_id(tmi, item.context_expr)
                    if lid is not None:
                        edges.setdefault(
                            (lock, lid), (mi.sf.path, call.lineno, fi.local)
                        )
            if not isinstance(node, ast.Call):
                continue
            reason = self._blocking_reason(project, tmi, node)
            if reason is not None:
                yield em.emit(
                    mi.sf.path, call.lineno, fi.local,
                    f"`{name}` does {reason} while `{lock}` is held "
                    f"(via {target.local} at {tmi.sf.path}:{node.lineno}) — "
                    "move the blocking work outside the locked region",
                    f"{lock}:via:{target.local}",
                )
                return

    def _inversions(self, edges: dict, em: _Emitter) -> Iterator[Finding]:
        seen = set()
        for (a, b), (path, line, symbol) in sorted(edges.items()):
            if a == b or (b, a) not in edges or (b, a) in seen:
                continue
            seen.add((a, b))
            opath, oline, _ = edges[(b, a)]
            yield em.emit(
                path, line, symbol,
                f"lock-order inversion: `{a}` is taken before `{b}` here, "
                f"but `{b}` before `{a}` at {opath}:{oline} — two threads "
                "can deadlock; pick one global order",
                f"inversion:{min(a, b)}:{max(a, b)}",
            )


# --- GL005 -------------------------------------------------------------------


class DisarmedHookCost:
    CODE = "GL005"
    NAME = "disarmed-hook-cost"

    _GUARD_HINTS = ("tracer", "armed", "injector", "enabled")

    def _is_seam(self, resolved: str | None) -> bool:
        if not resolved:
            return False
        parts = resolved.split(".")
        if parts[-1] == "chaos_hook":
            return True
        if parts[-1] in ("span", "instant", "sampled_span"):
            # module-level seam (trace.span); method calls on a tracer
            # object obtained after the armed check are fine
            return len(parts) == 1 or parts[-2] in ("trace", "chaos")
        if parts[-1] == "sample" and len(parts) >= 2 and parts[-2] in (
            "hbm", "health", "series"
        ):
            # the HBM observatory's, the numerics sentinel's, and the
            # series recorder's hot-path seams (obs/hbm.py, obs/health.py,
            # obs/series.py): same disarmed-cost contract as the
            # trace/chaos hooks
            return True
        if parts[-1] == "maybe_capture":
            # the coordinated profiler's step-boundary seam
            # (obs/profile.py): one global load + None compare disarmed
            return len(parts) == 1 or parts[-2] == "profile"
        return False

    def _expensive(self, node: ast.expr) -> str | None:
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                return f"call `{dotted(n.func) or '<expr>'}(...)`"
            if isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
                return "a comprehension"
        return None

    def _guarded(self, guards: list[ast.expr]) -> bool:
        for g in guards:
            try:
                text = ast.unparse(g).lower()
            except Exception:
                continue
            if any(h in text for h in self._GUARD_HINTS):
                return True
        return False

    def run(self, project: Project) -> Iterator[Finding]:
        em = _Emitter(self.CODE)
        for mi in project.modules.values():
            # hook *implementation* modules are exempt: the seam body runs
            # after its own armed check by construction
            if mi.modname.endswith(
                ("obs.trace", "obs.hbm", "obs.health", "obs.series",
                 "obs.profile", "chaos.faults")
            ):
                continue
            for fi in mi.funcs.values():
                yield from self._check_func(project, mi, fi, em)

    def _check_func(self, project: Project, mi, fi, em: _Emitter
                    ) -> Iterator[Finding]:
        guards: list[ast.expr] = []

        def visit(node: ast.AST) -> Iterator[Finding]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue
                pushed = False
                if isinstance(child, (ast.If, ast.While)):
                    guards.append(child.test)
                    pushed = True
                if isinstance(child, ast.Call) and self._is_seam(
                    project.dotted_resolved(mi, child.func)
                ):
                    seam = dotted(child.func) or "hook"
                    for arg in list(child.args) + [
                        kw.value for kw in child.keywords
                    ]:
                        why = self._expensive(arg)
                        if why is None:
                            continue
                        if self._guarded(guards):
                            break
                        yield em.emit(
                            mi.sf.path, child.lineno, fi.local,
                            f"`{seam}(...)` argument contains {why}, "
                            "evaluated even when the hook is disarmed — "
                            "guard the call site (if tracer/injector is "
                            "armed) or precompute cheap values; the "
                            "disarmed hook must stay one global load "
                            "(docs/PERF.md disarmed-hook guard)",
                            f"{seam}",
                        )
                        break
                yield from visit(child)
                if pushed:
                    guards.pop()

        yield from visit(fi.node)


CHECKERS = [HostSyncInJit, RecompileHazard, DonationReuse, LockDiscipline,
            DisarmedHookCost]

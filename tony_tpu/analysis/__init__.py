"""graft-lint: JAX-aware + concurrency-aware static analysis (docs/ANALYSIS.md).

Stdlib-``ast`` only — importable (and runnable) in environments without jax.
``tony lint [paths]`` and ``scripts/lint.py`` are the entry points; the
tier-1 gate is ``tests/test_lint.py::test_codebase_is_lint_clean``.
"""

from tony_tpu.analysis.core import (
    Baseline,
    Finding,
    all_checkers,
    lint_paths,
    load_project,
    run_checkers,
)

__all__ = [
    "Baseline",
    "Finding",
    "all_checkers",
    "lint_paths",
    "load_project",
    "run_checkers",
]

"""Cross-module call graph + jit-entry reachability for graft-lint.

Deliberately an under-approximation: names are resolved through explicit
imports, ``self.``/``cls.`` method access, module-level aliases
(``g = partial(f, ...)``), and call arguments that are function references
(``lax.scan(block, ...)`` adds caller -> block). Dynamic dispatch through
duck-typed attributes is NOT resolved — checkers that need it (GL004's
RPC-ish calls) match attribute patterns instead. Under-approximating keeps
the zero-findings tier-1 gate honest: every finding is explainable from
the source, so a clean tree stays clean without blanket suppressions.

Jit entry points ("roots"): functions decorated with / passed to
``jax.jit`` / ``jit`` / ``pjit`` (directly or through ``partial``). Every
function transitively callable from a root body is **traced** — code in it
runs under tracing, where a host sync or Python branch on a tracer is a
silent recompile/stall (GL001/GL002). Calling an already-jitted function
does not make the *caller* traced.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterable

from tony_tpu.analysis.core import SourceFile

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit", "jax.experimental.pjit.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}


def dotted(node: ast.expr) -> str | None:
    """Textual dotted name of a Name/Attribute chain (None otherwise)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def unwrap_partial(node: ast.expr) -> ast.expr:
    """``partial(f, ...)`` / ``functools.partial(f, ...)`` -> ``f``."""
    if isinstance(node, ast.Call) and dotted(node.func) in _PARTIAL_NAMES and node.args:
        return node.args[0]
    return node


@dataclass
class FuncInfo:
    module: str
    local: str          # "func", "Class.method", "outer.inner"
    node: ast.AST       # FunctionDef | AsyncFunctionDef | Lambda
    class_name: str = ""  # innermost enclosing class ("" for free functions)
    callees: set[str] = field(default_factory=set)  # resolved qualnames

    @property
    def qualname(self) -> str:
        return f"{self.module}:{self.local}"


@dataclass
class JitCall:
    """One ``jax.jit(...)`` call site (GL002/GL003 consume these)."""

    module: str
    func: "FuncInfo | None"   # enclosing function (None = module level)
    node: ast.Call
    target: "FuncInfo | None"  # the function being jitted, when resolvable
    donate: tuple[int, ...] = ()
    static_argnums: tuple[int, ...] = ()
    static_argnames: tuple[str, ...] = ()


class _ModuleIndex:
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.modname = sf.modname
        self.funcs: dict[str, FuncInfo] = {}
        # import name -> ("mod", dotted_module) | ("sym", module, symbol)
        self.imports: dict[str, tuple] = {}
        # module-level: alias name -> candidate function qualnames (an
        # alias assigned in both branches of an if keeps both candidates)
        self.aliases: dict[str, tuple[str, ...]] = {}


def _const_index_tuple(node: ast.expr | None) -> tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _const_str_tuple(node: ast.expr | None) -> tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


class Project:
    """Parsed modules + call graph + jit reachability (see module doc)."""

    def __init__(self, sources: Iterable[SourceFile]):
        self.sources = list(sources)
        self.by_path: dict[str, SourceFile] = {s.path: s for s in self.sources}
        self.modules: dict[str, _ModuleIndex] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.jit_calls: list[JitCall] = []
        self.jit_roots: dict[str, str] = {}  # qualname -> why
        # traced qualname -> one root it is reachable from
        self.traced_from: dict[str, str] = {}
        self._index_all()
        self._resolve_all()
        self._mark_traced()

    # --- pass 1: symbols ------------------------------------------------------

    def _index_all(self) -> None:
        for sf in self.sources:
            mi = _ModuleIndex(sf)
            self.modules[sf.modname] = mi
            self._collect(mi, sf.tree, prefix="", class_name="")
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        name = a.asname or a.name.split(".")[0]
                        mi.imports[name] = ("mod", a.name if a.asname else name)
                        if not a.asname:
                            # "import a.b.c" binds "a" but makes the full
                            # dotted path resolvable too
                            mi.imports.setdefault(a.name, ("mod", a.name))
                elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                    for a in node.names:
                        mi.imports[a.asname or a.name] = (
                            "sym", node.module, a.name
                        )
            for fi in mi.funcs.values():
                self.funcs[fi.qualname] = fi

    def _collect(self, mi: _ModuleIndex, node: ast.AST, prefix: str,
                 class_name: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local = f"{prefix}{child.name}"
                mi.funcs[local] = FuncInfo(mi.modname, local, child, class_name)
                self._collect(mi, child, prefix=f"{local}.", class_name=class_name)
            elif isinstance(child, ast.ClassDef):
                self._collect(mi, child, prefix=f"{prefix}{child.name}.",
                              class_name=child.name)

    # --- pass 2: resolution ---------------------------------------------------

    def resolve_candidates(self, mi: _ModuleIndex, caller: FuncInfo | None,
                           node: ast.expr,
                           local_aliases: dict[str, tuple[str, ...]] | None = None
                           ) -> tuple[FuncInfo, ...]:
        """All known functions a callee/argument expression may refer to
        (aliases assigned in different branches keep every candidate)."""
        node = unwrap_partial(node)
        name = dotted(node)
        if name is None:
            return ()
        parts = name.split(".")
        # self.method / cls.method -> same class (or any class up the chain)
        if parts[0] in ("self", "cls") and caller is not None and len(parts) == 2:
            if caller.class_name:
                fi = mi.funcs.get(f"{caller.class_name}.{parts[1]}")
                if fi is not None:
                    return (fi,)
            return ()
        if len(parts) == 1:
            for aliases in (local_aliases, mi.aliases):
                if aliases and name in aliases:
                    out = tuple(
                        self.funcs[q] for q in aliases[name] if q in self.funcs
                    )
                    if out:
                        return out
            # own nested function, then sibling nested, then module level
            if caller is not None:
                fi = mi.funcs.get(f"{caller.local}.{name}")
                if fi is not None:
                    return (fi,)
                scope = caller.local.rsplit(".", 1)[0] if "." in caller.local else ""
                if scope:
                    fi = mi.funcs.get(f"{scope}.{name}")
                    if fi is not None:
                        return (fi,)
            fi = mi.funcs.get(name)
            if fi is not None:
                return (fi,)
            imp = mi.imports.get(name)
            if imp is not None and imp[0] == "sym":
                target = self.modules.get(imp[1])
                if target is not None:
                    fi = target.funcs.get(imp[2])
                    if fi is not None:
                        return (fi,)
            return ()
        fi = self._resolve_dotted(mi, parts)
        return (fi,) if fi is not None else ()

    def resolve_callable(self, mi: _ModuleIndex, caller: FuncInfo | None,
                         node: ast.expr,
                         local_aliases: dict[str, tuple[str, ...]] | None = None
                         ) -> FuncInfo | None:
        cands = self.resolve_candidates(mi, caller, node, local_aliases)
        return cands[0] if cands else None

    def _resolve_dotted(self, mi: _ModuleIndex, parts: list[str]
                        ) -> FuncInfo | None:
        # dotted: alias.func / package.module.func / Class.method
        head, rest = parts[0], ".".join(parts[1:])
        imp = mi.imports.get(head)
        if imp is not None:
            if imp[0] == "mod":
                return self._resolve_in_module(imp[1], rest)
            if imp[0] == "sym":
                # "from pkg import mod" then mod.func — or a class symbol
                target = self.modules.get(f"{imp[1]}.{imp[2]}")
                if target is not None:
                    return target.funcs.get(rest)
                target = self.modules.get(imp[1])
                if target is not None:
                    return target.funcs.get(f"{imp[2]}.{rest}")
                return None
        # full dotted path to an analyzed module ("import a.b.c" style)
        for split in range(len(parts) - 1, 0, -1):
            modname = ".".join(parts[:split])
            if modname in self.modules:
                return self.modules[modname].funcs.get(".".join(parts[split:]))
        # same-module Class.method
        return mi.funcs.get(".".join(parts))

    def _resolve_in_module(self, modname: str, local: str) -> FuncInfo | None:
        target = self.modules.get(modname)
        if target is None:
            # "import a.b" + "a.b.c.func": c may be a submodule
            head, _, rest = local.partition(".")
            if rest:
                return self._resolve_in_module(f"{modname}.{head}", rest)
            return None
        fi = target.funcs.get(local)
        if fi is not None:
            return fi
        head, _, rest = local.partition(".")
        if rest:
            return self._resolve_in_module(f"{modname}.{head}", rest)
        return None

    def dotted_resolved(self, mi: _ModuleIndex, node: ast.expr) -> str | None:
        """Dotted callee text with the first segment expanded through the
        import map (``from jax import jit`` -> ``jax.jit``)."""
        name = dotted(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        imp = mi.imports.get(head)
        if imp is None:
            return name
        if imp[0] == "mod":
            return f"{imp[1]}.{rest}" if rest else imp[1]
        full = f"{imp[1]}.{imp[2]}"
        return f"{full}.{rest}" if rest else full

    def _scope_aliases(self, mi: _ModuleIndex, caller: FuncInfo | None,
                       root: ast.AST,
                       inherited: dict[str, tuple[str, ...]] | None = None
                       ) -> dict[str, tuple[str, ...]]:
        """Alias assignments anywhere in ``root``'s own body (not nested
        defs): ``g = f`` / ``g = partial(f, ...)`` / ``g = f1 if c else f2``.
        Two passes so alias-of-alias chains resolve."""
        aliases: dict[str, tuple[str, ...]] = dict(inherited or {})
        assigns = sorted(
            (n for n in self._own_nodes(root)
             if isinstance(n, ast.Assign) and len(n.targets) == 1
             and isinstance(n.targets[0], ast.Name)),
            key=lambda n: (n.lineno, n.col_offset),
        )
        for _ in range(2):
            for stmt in assigns:
                values = (
                    [stmt.value.body, stmt.value.orelse]
                    if isinstance(stmt.value, ast.IfExp) else [stmt.value]
                )
                quals: list[str] = []
                for v in values:
                    for fi in self.resolve_candidates(mi, caller, v, aliases):
                        if fi.qualname not in quals:
                            quals.append(fi.qualname)
                if quals:
                    name = stmt.targets[0].id
                    merged = list(aliases.get(name, ()))
                    for q in quals:
                        if q not in merged:
                            merged.append(q)
                    aliases[name] = tuple(merged)
        return aliases

    def _own_nodes(self, root: ast.AST):
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _resolve_all(self) -> None:
        for mi in self.modules.values():
            mi.aliases = self._scope_aliases(mi, None, mi.sf.tree)
        for mi in self.modules.values():
            # parents before children so nested defs inherit aliases
            func_aliases: dict[str, dict[str, tuple[str, ...]]] = {}
            for local in sorted(mi.funcs, key=lambda q: q.count(".")):
                fi = mi.funcs[local]
                inherited = dict(mi.aliases)
                parent = local
                chain = []
                while "." in parent:
                    parent = parent.rsplit(".", 1)[0]
                    chain.append(parent)
                for anc in reversed(chain):
                    inherited.update(func_aliases.get(anc, {}))
                local_aliases = self._scope_aliases(mi, fi, fi.node, inherited)
                func_aliases[local] = local_aliases
                for node in self._own_calls(fi.node):
                    self._record_call(mi, fi, node, local_aliases)
            # module-level calls (jit roots defined at import time)
            for node in self._own_calls(mi.sf.tree, top=True):
                self._record_call(mi, None, node, mi.aliases)

    def _own_calls(self, root: ast.AST, top: bool = False):
        """Call nodes in ``root``'s body, not descending into nested
        function/class definitions (those index their own calls)."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                if not top:
                    continue
                # at module level, descend into classes but not functions
                if isinstance(node, ast.ClassDef):
                    stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))

    def _record_call(self, mi: _ModuleIndex, fi: FuncInfo | None,
                     node: ast.Call, aliases: dict[str, tuple[str, ...]]) -> None:
        callee_dotted = self.dotted_resolved(mi, node.func)
        if fi is not None:
            for target in self.resolve_candidates(mi, fi, node.func, aliases):
                fi.callees.add(target.qualname)
        if callee_dotted in _JIT_NAMES:
            self._record_jit(mi, fi, node, aliases)
            return
        # higher-order propagation: function references passed as args are
        # (likely) called by the callee in the caller's dynamic context —
        # lax.scan(block, ...), vmap(write), value_and_grad(loss_fn), hooks
        if fi is not None:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for ref in self.resolve_candidates(mi, fi, arg, aliases):
                    fi.callees.add(ref.qualname)

    def _record_jit(self, mi: _ModuleIndex, fi: FuncInfo | None,
                    node: ast.Call, aliases: dict[str, str]) -> None:
        fn_node = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg in ("fun", "fn", "f") and fn_node is None:
                fn_node = kw.value
        target = (
            self.resolve_callable(mi, fi, fn_node, aliases)
            if fn_node is not None else None
        )
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        jc = JitCall(
            module=mi.modname, func=fi, node=node, target=target,
            donate=_const_index_tuple(kwargs.get("donate_argnums")),
            static_argnums=_const_index_tuple(kwargs.get("static_argnums")),
            static_argnames=_const_str_tuple(kwargs.get("static_argnames")),
        )
        self.jit_calls.append(jc)
        if target is not None:
            self.jit_roots.setdefault(
                target.qualname,
                f"passed to {dotted(node.func)} at {mi.sf.path}:{node.lineno}",
            )

    # --- pass 3: reachability -------------------------------------------------

    def _mark_traced(self) -> None:
        # decorator roots
        for fi in self.funcs.values():
            deco_list = getattr(fi.node, "decorator_list", [])
            mi = self.modules[fi.module]
            for deco in deco_list:
                expr = deco.func if isinstance(deco, ast.Call) else deco
                expr = unwrap_partial(expr) if isinstance(deco, ast.Call) else expr
                name = self.dotted_resolved(mi, expr)
                if name in _JIT_NAMES or (
                    isinstance(deco, ast.Call)
                    and self.dotted_resolved(mi, deco.func) in _PARTIAL_NAMES
                    and deco.args
                    and self.dotted_resolved(mi, deco.args[0]) in _JIT_NAMES
                ):
                    self.jit_roots.setdefault(
                        fi.qualname, f"decorated @{name or 'jit'}"
                    )
        # closure
        for root in sorted(self.jit_roots):
            stack = [root]
            while stack:
                q = stack.pop()
                if q in self.traced_from:
                    continue
                self.traced_from[q] = root
                fi = self.funcs.get(q)
                if fi is None:
                    continue
                stack.extend(fi.callees - self.traced_from.keys())

    def is_traced(self, qualname: str) -> bool:
        return qualname in self.traced_from

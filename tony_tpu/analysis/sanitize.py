"""Runtime sanitizer: the dynamic twin of graft-lint (GRAFT_SANITIZE=1).

Static analysis (GL001/GL002) catches host syncs and recompile hazards it
can *prove* from the source; this module catches the rest at runtime, the
way tsan complements a lock-discipline lint:

- **transfer guard** — ``jax.transfer_guard_device_to_host("disallow")``
  over the steady-state loop: any IMPLICIT device-to-host transfer (a
  stray ``np.asarray``/``float()`` on a device array) raises instead of
  silently stalling the pipeline. Explicit syncs (``jax.device_get``, the
  log-boundary reads) stay allowed — the contract is "every sync is
  spelled out", not "no syncs". The guard config is thread-local, so the
  prefetch/telemetry daemon threads are unaffected. NOTE: on the CPU
  backend jax skips the guard (no cross-device transfer happens), so this
  arm bites on TPU/GPU only.
- **compile watchdog** — counts XLA backend compiles (the
  ``/jax/core/compile/backend_compile_duration`` monitoring event) inside
  the guarded region. Steady state means ZERO new compiles: a recompile
  per step is the classic silent 100x (GL002's dynamic shadow). Budget
  overruns raise :class:`SanitizeError` at the first excess compile, with
  the count in the message. The counter is the compile LEDGER's
  (obs/compiles.py — one listener serves the watchdog and the always-on
  compile journal ``tony compiles`` reads).

Wired into ``fit()`` (steady state: after the first step resolved) and
``Engine.run()`` under ``GRAFT_SANITIZE=1``; both are no-ops otherwise.
``GRAFT_SANITIZE_MAX_COMPILES`` (default 0) loosens the budget for loops
that legitimately grow signatures mid-run (e.g. an engine trace that
crosses a cache-capacity doubling).
"""

from __future__ import annotations

import contextlib
import os

ENV_FLAG = "GRAFT_SANITIZE"
ENV_MAX_COMPILES = "GRAFT_SANITIZE_MAX_COMPILES"


class SanitizeError(RuntimeError):
    """A sanitized loop broke its contract (excess compiles)."""


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "") == "1"


def _max_compiles(default: int = 0) -> int:
    try:
        return int(os.environ.get(ENV_MAX_COMPILES, "") or default)
    except ValueError:
        return default


def compile_count() -> int:
    """Process-wide backend-compile count since the listener was armed —
    the compile ledger's counter (obs/compiles.py), so the watchdog and
    the compile journal can never disagree on what compiled."""
    from tony_tpu.obs.compiles import get_ledger

    return get_ledger().backend_compiles


class CompileWatchdog:
    """Snapshot-compare compile counter for a region. ``check()`` raises
    :class:`SanitizeError` when the region exceeded its budget; call it
    per iteration (cheap: one int compare) so the failure points at the
    first offending step, not the end of the run."""

    def __init__(self, budget: int, where: str):
        self.budget = budget
        self.where = where
        self._t0 = compile_count()

    @property
    def compiles(self) -> int:
        return compile_count() - self._t0

    def check(self) -> None:
        n = self.compiles
        if n > self.budget:
            raise SanitizeError(
                f"GRAFT_SANITIZE: {n} XLA compile(s) inside the "
                f"steady-state {self.where} loop (budget {self.budget}). "
                "Something retraces per call — look for per-call-fresh "
                "callables/static args (graft-lint GL002) or growing "
                "shapes; raise GRAFT_SANITIZE_MAX_COMPILES only if the "
                "recompile is intended (e.g. a planned capacity change)."
            )


@contextlib.contextmanager
def sanitized_loop(where: str, max_compiles: int | None = None):
    """Context manager arming both sanitizer arms around a steady-state
    loop. Yields the :class:`CompileWatchdog` (or None when disarmed) —
    the loop should call ``watchdog.check()`` each iteration. The compile
    budget is also enforced at region exit for loops that cannot call
    check() conveniently."""
    if not enabled():
        yield None
        return
    import jax

    budget = _max_compiles(0) if max_compiles is None else max_compiles
    watchdog = CompileWatchdog(budget, where)
    with jax.transfer_guard_device_to_host("disallow"):
        yield watchdog
    watchdog.check()


__all__ = [
    "CompileWatchdog", "ENV_FLAG", "ENV_MAX_COMPILES", "SanitizeError",
    "compile_count", "enabled", "sanitized_loop",
]

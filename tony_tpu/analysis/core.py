"""graft-lint engine: findings, suppressions, baseline, checker registry.

The analysis is stdlib-``ast`` only (no jax import, no third-party deps):
executor images for non-JAX frameworks and bare CI runners can lint the
tree. Each checker is a class with a ``CODE`` (``GLxxx``), registered in
``CHECKERS``; checkers consume the shared :class:`Project` (parsed modules
+ cross-module call graph, analysis/callgraph.py) and yield
:class:`Finding`\\ s.

Three escape hatches, in order of preference:

- fix the code (the point of the tool);
- inline ``# graft-lint: disable=GL004`` on the offending line (or a
  standalone comment on the line above) with a justifying comment — for
  load-bearing exceptions the code should document where they live;
- a committed baseline file (``graft_lint_baseline.json``) keyed by
  line-number-independent fingerprints — for grandfathered findings that
  are tracked but not yet fixed. ``scripts/lint.py --update-baseline``
  rewrites it; new findings beyond the baseline fail the tier-1 gate
  (tests/test_lint.py::test_codebase_is_lint_clean).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator

_SUPPRESS_RE = re.compile(r"#\s*graft-lint:\s*disable=([A-Z0-9,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*graft-lint:\s*disable-file=([A-Z0-9,\s]+)")


@dataclass(frozen=True)
class Finding:
    """One lint finding. ``fingerprint`` is line-number-independent (code +
    file + enclosing symbol + a stable detail tag) so a baseline entry
    survives unrelated edits to the file."""

    code: str      # "GL001"
    path: str      # posix path as given to the linter (repo-relative in CI)
    line: int
    symbol: str    # enclosing function qualname ("" = module level)
    message: str
    detail: str = ""  # stable tag for the fingerprint (e.g. offending call)

    @property
    def fingerprint(self) -> str:
        return f"{self.code}|{self.path}|{self.symbol}|{self.detail}"

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.code}{sym} {self.message}"

    def to_json(self) -> dict:
        return {
            "code": self.code, "path": self.path, "line": self.line,
            "symbol": self.symbol, "message": self.message,
            "fingerprint": self.fingerprint,
        }


class Baseline:
    """Committed grandfathered findings: fingerprint -> justification."""

    def __init__(self, entries: dict[str, str] | None = None, path: str = ""):
        self.entries = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as f:
                raw = json.load(f)
        except FileNotFoundError:
            return cls({}, path)
        entries = {
            e["fingerprint"]: e.get("justification", "")
            for e in raw.get("findings", [])
            if isinstance(e, dict) and e.get("fingerprint")
        }
        return cls(entries, path)

    def save(self, path: str | None = None,
             findings: Iterable[Finding] = ()) -> None:
        """Rewrite with the given findings, keeping existing justifications
        (new entries get a placeholder that review should replace)."""
        out = {
            "_comment": (
                "graft-lint baseline: grandfathered findings, keyed by "
                "line-independent fingerprints. Every entry needs a "
                "justification; prefer fixing or inline suppression "
                "(docs/ANALYSIS.md)."
            ),
            "findings": [
                {
                    "fingerprint": f.fingerprint,
                    "justification": self.entries.get(
                        f.fingerprint, "TODO: justify or fix"
                    ),
                    "where": f"{f.path}:{f.symbol or '<module>'}",
                    "message": f.message,
                }
                for f in sorted(findings, key=lambda f: f.fingerprint)
            ],
        }
        with open(path or self.path, "w", encoding="utf-8") as f:
            json.dump(out, f, indent=1, sort_keys=False)
            f.write("\n")

    def covers(self, finding: Finding) -> bool:
        return finding.fingerprint in self.entries


@dataclass
class SourceFile:
    """One parsed file plus its suppression tables."""

    path: str
    source: str
    tree: ast.Module
    modname: str
    # line -> codes suppressed on that line (incl. carried from a
    # standalone comment line above); {"*"} = all codes
    line_suppress: dict[int, set[str]] = field(default_factory=dict)
    file_suppress: set[str] = field(default_factory=set)

    def suppressed(self, code: str, line: int) -> bool:
        if code in self.file_suppress or "*" in self.file_suppress:
            return True
        codes = self.line_suppress.get(line, ())
        return code in codes or "*" in codes


def _parse_suppressions(sf: SourceFile) -> None:
    lines = sf.source.splitlines()
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_FILE_RE.search(text)
        if m:
            sf.file_suppress.update(
                c.strip() for c in m.group(1).split(",") if c.strip()
            )
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        sf.line_suppress.setdefault(i, set()).update(codes)
        if text.lstrip().startswith("#"):
            # standalone comment: applies to the next line too
            sf.line_suppress.setdefault(i + 1, set()).update(codes)


def _module_name(path: str) -> str:
    """Dotted module name by walking up through __init__.py packages
    (bare stem for loose files — e.g. test fixture dirs)."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    if parts[0] == "__init__":
        parts = parts[1:] or parts
    return ".".join(reversed(parts))


def _anchor_for(absp: str) -> str:
    """Display-path anchor: the repo root (the directory holding
    ``graft_lint_baseline.json``, walking up) when there is one, else the
    path's parent. Anchoring at the repo root makes fingerprints identical
    whether the whole tree, a subdirectory, or a single file is linted —
    otherwise baseline entries recorded from ``tony lint tony_tpu/`` would
    read as NEW findings when a developer lints one changed file."""
    d = absp if os.path.isdir(absp) else os.path.dirname(absp)
    while True:
        if os.path.isfile(os.path.join(d, "graft_lint_baseline.json")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.dirname(absp)
        d = parent


def iter_py_files(paths: Iterable[str]) -> Iterator[tuple[str, str]]:
    """Yield (absolute path, display path). Display paths are relative to
    the repo root when one is identifiable, else the linted root's parent
    (``tony_tpu/cluster/lease.py`` no matter the cwd or the argument
    shape), so baseline fingerprints are stable across checkouts and
    across whole-tree vs single-file invocations."""
    for p in paths:
        absp = os.path.abspath(p)
        if os.path.isfile(absp):
            if absp.endswith(".py"):
                yield absp, os.path.relpath(
                    absp, _anchor_for(absp)
                ).replace(os.sep, "/")
        else:
            anchor = _anchor_for(absp)
            for root, dirs, files in os.walk(absp):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git") and not d.startswith(".")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        full = os.path.join(root, name)
                        yield full, os.path.relpath(full, anchor).replace(
                            os.sep, "/"
                        )


def load_project(paths: Iterable[str]):
    """Parse every .py under ``paths`` into a Project (analysis/callgraph.py)
    with the cross-module call graph and jit-reachability precomputed."""
    from tony_tpu.analysis.callgraph import Project

    sources: list[SourceFile] = []
    for path, display in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError):
            continue  # unreadable/unparsable files are not this tool's job
        sf = SourceFile(path=display, source=src, tree=tree,
                        modname=_module_name(path))
        _parse_suppressions(sf)
        sources.append(sf)
    return Project(sources)


def all_checkers() -> list:
    from tony_tpu.analysis import checkers

    return [cls() for cls in checkers.CHECKERS]


def run_checkers(project, checkers: Iterable | None = None,
                 select: Iterable[str] = ()) -> list[Finding]:
    """Run checkers over a loaded project, honouring inline suppressions.
    ``select`` restricts to the given codes (empty = all)."""
    selected = set(select)
    out: list[Finding] = []
    for checker in (checkers if checkers is not None else all_checkers()):
        if selected and checker.CODE not in selected:
            continue
        for f in checker.run(project):
            sf = project.by_path.get(f.path)
            if sf is not None and sf.suppressed(f.code, f.line):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.code))
    return out


def lint_paths(paths: Iterable[str], baseline: Baseline | None = None,
               select: Iterable[str] = ()) -> tuple[list[Finding], list[Finding]]:
    """Lint ``paths``; returns (new_findings, baselined_findings)."""
    project = load_project(paths)
    findings = run_checkers(project, select=select)
    if baseline is None:
        return findings, []
    new = [f for f in findings if not baseline.covers(f)]
    old = [f for f in findings if baseline.covers(f)]
    return new, old


def default_baseline_path(paths: Iterable[str]) -> str:
    """``graft_lint_baseline.json`` next to the first linted path's repo
    root: walk up from the first path looking for the file, else cwd."""
    first = next(iter(paths), ".")
    d = os.path.abspath(first if os.path.isdir(first) else os.path.dirname(first) or ".")
    while True:
        cand = os.path.join(d, "graft_lint_baseline.json")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.join(os.getcwd(), "graft_lint_baseline.json")
        d = parent

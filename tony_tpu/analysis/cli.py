"""`tony lint` / scripts/lint.py driver: lint paths against a baseline.

Exit codes: 0 = no new findings, 1 = new findings, 2 = usage error.
"""

from __future__ import annotations

import argparse
import json
import sys

from tony_tpu.analysis.core import (
    Baseline, all_checkers, default_baseline_path, lint_paths,
)


def add_lint_args(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "paths", nargs="*", default=["tony_tpu"],
        help="files/directories to lint (default: tony_tpu)",
    )
    p.add_argument(
        "--baseline", default="",
        help="baseline JSON (default: graft_lint_baseline.json found by "
             "walking up from the first path); 'none' disables",
    )
    p.add_argument(
        "--format", choices=["text", "json"], default="text",
        dest="fmt", help="output format",
    )
    p.add_argument(
        "--select", default="",
        help="comma-separated checker codes to run (default: all)",
    )
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline file with the current findings "
             "(existing justifications are kept)",
    )


def run_lint(args: argparse.Namespace) -> int:
    paths = args.paths or ["tony_tpu"]
    select = [c.strip() for c in args.select.split(",") if c.strip()]
    known = {c.CODE for c in all_checkers()}
    bad = set(select) - known
    if bad:
        print(f"unknown checker code(s): {', '.join(sorted(bad))} "
              f"(known: {', '.join(sorted(known))})", file=sys.stderr)
        return 2
    if args.baseline == "none":
        baseline = Baseline({}, "")
    else:
        baseline = Baseline.load(args.baseline or default_baseline_path(paths))
    new, old = lint_paths(paths, baseline, select=select)
    if args.update_baseline:
        if not baseline.path:
            print("--update-baseline needs --baseline PATH", file=sys.stderr)
            return 2
        baseline.save(findings=new + old)
        print(f"wrote {baseline.path} ({len(new) + len(old)} entries)")
        return 0
    if args.fmt == "json":
        print(json.dumps({
            "new": [f.to_json() for f in new],
            "baselined": [f.to_json() for f in old],
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        if old:
            print(f"# {len(old)} baselined finding(s) suppressed "
                  f"({baseline.path})", file=sys.stderr)
        if new:
            print(f"\n{len(new)} new finding(s); fix, suppress inline "
                  "(# graft-lint: disable=CODE), or baseline with a "
                  "justification (docs/ANALYSIS.md)", file=sys.stderr)
        else:
            print("graft-lint: clean", file=sys.stderr)
    return 1 if new else 0


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="graft-lint",
        description="JAX-aware + concurrency-aware static analysis "
                    "(docs/ANALYSIS.md)",
    )
    add_lint_args(p)
    return run_lint(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())

"""Resource substrate: ClusterBackend protocol + implementations."""

import logging

from tony_tpu.cluster.backend import (
    ClusterBackend,
    Container,
    ContainerRequest,
    ContainerState,
    InsufficientResources,
    Resource,
)
from tony_tpu.cluster.lease import GangAsk, LeaseStore
from tony_tpu.cluster.local import LocalProcessBackend
from tony_tpu.cluster.remote import LocalTransport, RemoteBackend, SshTransport
from tony_tpu.cluster.tpu_vm import TpuVmBackend

log = logging.getLogger(__name__)


def make_backend(name: str, config=None, **kwargs) -> ClusterBackend:
    """Backend factory keyed by the ``cluster.backend`` config value.

    ``config`` (a TonyConfig) supplies the remote backends' host list,
    transport, and chip inventory — and, for every backend, the shared
    ResourceManager store (``cluster.rm_root``) that arbitrates capacity
    across concurrently-submitted jobs.
    """
    if config is not None:
        from tony_tpu.config.keys import Keys

        rm_root = config.get_str(Keys.CLUSTER_RM_ROOT, "")
        if rm_root and "lease_store" not in kwargs:
            from tony_tpu.cluster.lease import LeaseStore

            ttl = config.get_float(Keys.CLUSTER_LEASE_TTL_S, 600.0)
            # renewal rides the AM heartbeat cadence (throttled to ttl/4):
            # a TTL at or below the heartbeat interval lets a HEALTHY
            # cross-host owner's entries lapse between renewals, so
            # survivors reap a live job and it self-fences. 4x keeps the
            # renewal margin the design assumes.
            hb_s = config.get_int(Keys.TASK_HEARTBEAT_INTERVAL_MS, 1000) / 1000.0
            floor = 4.0 * hb_s
            if 0 < ttl < floor:
                log.warning(
                    "cluster.lease_ttl_s=%.1f is below 4x the heartbeat "
                    "interval (%.1fs): a healthy owner could be TTL-reaped "
                    "between renewals and self-fence; clamping TTL to %.1fs",
                    ttl, hb_s, floor,
                )
                ttl = floor
            kwargs["lease_store"] = LeaseStore(rm_root, lease_ttl_s=ttl)
        kwargs.setdefault(
            "rm_queue_timeout_s",
            config.get_float(Keys.AM_ALLOCATION_TIMEOUT_S, 300.0),
        )
    if name == "local":
        return LocalProcessBackend(**kwargs)
    if name in ("remote", "tpu_vm"):
        if config is not None:
            from tony_tpu.config.keys import Keys

            kwargs.setdefault("hosts", config.get_list(Keys.CLUSTER_HOSTS))
            kwargs.setdefault(
                "transport", config.get_str(Keys.CLUSTER_REMOTE_TRANSPORT, "ssh")
            )
            kwargs.setdefault(
                "localize", config.get_bool(Keys.CLUSTER_LOCALIZE, False)
            )
            kwargs.setdefault(
                "localize_root", config.get_str(Keys.CLUSTER_LOCALIZE_ROOT, "")
            )
            chips = config.get_int(Keys.CLUSTER_TPU_CHIPS_PER_HOST, 4)
            if name == "remote":
                kwargs.setdefault(
                    "host_capacity",
                    Resource(memory_mb=1 << 20, cpus=256, tpu_chips=chips),
                )
            else:
                kwargs.setdefault("chips_per_host", chips)
        return RemoteBackend(**kwargs) if name == "remote" else TpuVmBackend(**kwargs)
    raise ValueError(
        f"unknown cluster backend {name!r} (expected local | remote | tpu_vm)"
    )


__all__ = [
    "ClusterBackend",
    "Container",
    "ContainerRequest",
    "ContainerState",
    "GangAsk",
    "InsufficientResources",
    "LeaseStore",
    "LocalProcessBackend",
    "LocalTransport",
    "RemoteBackend",
    "Resource",
    "SshTransport",
    "TpuVmBackend",
    "make_backend",
]

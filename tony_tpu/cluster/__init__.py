"""Resource substrate: ClusterBackend protocol + implementations."""

from tony_tpu.cluster.backend import (
    ClusterBackend,
    Container,
    ContainerRequest,
    ContainerState,
    InsufficientResources,
    Resource,
)
from tony_tpu.cluster.local import LocalProcessBackend
from tony_tpu.cluster.tpu_vm import TpuVmBackend


def make_backend(name: str, **kwargs) -> ClusterBackend:
    """Backend factory keyed by the ``cluster.backend`` config value."""
    if name == "local":
        return LocalProcessBackend(**kwargs)
    if name == "tpu_vm":
        return TpuVmBackend(**kwargs)
    raise ValueError(f"unknown cluster backend {name!r} (expected local | tpu_vm)")


__all__ = [
    "ClusterBackend",
    "Container",
    "ContainerRequest",
    "ContainerState",
    "InsufficientResources",
    "LocalProcessBackend",
    "Resource",
    "TpuVmBackend",
    "make_backend",
]

"""RemoteBackend: containers are processes on remote hosts.

The multi-host resource substrate — the NMClientAsync role of the reference's
YARN NodeManagers (SURVEY.md sections 1 L0, 3.1 "startContainer"): the AM
launches executors on a fixed set of worker hosts (a TPU pod slice's TPU-VM
workers in production), streams their output back to local per-container log
files, kills remote process groups on release, and reports completion through
the standard callback.

The host-execution mechanism is a pluggable :class:`Transport` so the entire
backend — placement, per-host inventory, log streaming, release, completion —
is exercised by the E2E suite with the ``local`` transport (subprocesses
playing the part of remote hosts), while production uses ``ssh``. This is the
same faked-at-the-infrastructure-level testing posture as LocalProcessBackend
(the tony-mini lesson, SURVEY.md section 4), one level up.

Config surface::

    cluster.backend            = "remote"
    cluster.hosts              = "10.0.0.1,10.0.0.2"   # pod-slice workers
    cluster.remote_transport   = "ssh"                  # or "local" (tests)
    cluster.tpu_chips_per_host = 4                      # v4 hosts

Staging contract: the application dir (config.json, src/, app.token) must be
visible at the same path on every host — an NFS/GCS mount on TPU-VM slices.
This replaces the reference's HDFS localisation (SURVEY.md section 3.1); a
copy-based localiser over the transport is a possible later extension.
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import IO, Mapping, Protocol, Sequence

from tony_tpu.chaos import chaos_hook
from tony_tpu.cluster.backend import (
    CompletionCallback,
    Container,
    ContainerRequest,
    ContainerState,
    InsufficientResources,
    Resource,
    _LeaseRenewalMixin,
)
from tony_tpu.utils.net import canonical_host, local_host

log = logging.getLogger(__name__)


class RemoteProcess(Protocol):
    """A launched container process on some host."""

    pid: int  # process-group leader ON THE REMOTE HOST (0 if unknown)

    def wait(self) -> int: ...

    def poll(self) -> int | None: ...


class Transport(Protocol):
    """How to run and kill process groups on a host.

    The seam between the backend's bookkeeping (testable anywhere) and the
    actual remote-execution mechanism (ssh in production).
    """

    def exec_on(
        self,
        host: str,
        argv: Sequence[str],
        env: Mapping[str, str],
        log_file: IO[bytes],
    ) -> RemoteProcess: ...

    def kill_pg(self, host: str, pid: int, sig: int) -> None: ...

    def exit_authoritative(self, code: int) -> bool:
        """Does this exit code prove the container process group exited?"""
        ...

    def localize(self, host: str, src_dir: str, dst_dir: str) -> None:
        """Copy a staged application dir to ``dst_dir`` on ``host`` (the HDFS
        container-localisation analogue for slices without a shared FS)."""
        ...


# --- local transport (tests / single-host prod) -----------------------------


class _LocalProcess:
    def __init__(self, proc: subprocess.Popen):
        self._proc = proc
        self.pid = proc.pid

    def wait(self) -> int:
        return self._proc.wait()

    def poll(self) -> int | None:
        return self._proc.poll()


class LocalTransport:
    """Runs "remote" containers as local subprocesses.

    Every RemoteBackend code path above the transport seam is genuine; only
    the wire is faked. Also the honest choice for a single-host deployment.
    """

    def exec_on(self, host, argv, env, log_file):
        full_env = dict(os.environ)
        full_env.update(env)
        proc = subprocess.Popen(
            list(argv),
            env=full_env,
            stdout=log_file,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        return _LocalProcess(proc)

    def kill_pg(self, host, pid, sig):
        try:
            os.killpg(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def exit_authoritative(self, code):
        return True  # local waitpid: the group leader really exited

    def localize(self, host, src_dir, dst_dir):
        import shutil

        shutil.copytree(src_dir, dst_dir, dirs_exist_ok=True)


# --- ssh transport (production) ----------------------------------------------


class _SshProcess:
    """The local ssh client process; its exit code is the remote command's
    (ssh propagates it), and the remote pgid is read from the first output
    line (``echo $$`` under ``setsid`` makes pid == pgid)."""

    def __init__(self, proc: subprocess.Popen, pid: int):
        self._proc = proc
        self.pid = pid

    def wait(self) -> int:
        return self._proc.wait()

    def poll(self) -> int | None:
        return self._proc.poll()

    def terminate(self) -> None:
        self._proc.terminate()


class SshTransport:
    """Launch containers over ssh.

    The remote command wraps the executor in ``setsid`` so the whole user
    process tree forms one process group, reports that group's id on the
    first line of output (captured locally, not written to the log), then
    execs the real argv with the env exported. Output streams back over the
    ssh channel into the local per-container log file — the YARN
    log-aggregation analogue with zero remote-side daemons.
    """

    # ConnectTimeout bounds a blackholed host: without it the pid-line read in
    # exec_on blocks the scheduler thread past every allocation timeout.
    def __init__(
        self,
        ssh_argv: Sequence[str] = (
            "ssh", "-o", "BatchMode=yes", "-o", "ConnectTimeout=15",
            "-o", "ServerAliveInterval=30", "-o", "ServerAliveCountMax=4",
        ),
    ):
        self._ssh = list(ssh_argv)

    def _remote_command(self, argv: Sequence[str], env: Mapping[str, str]) -> str:
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in sorted(env.items()))
        inner = " ".join(shlex.quote(a) for a in argv)
        # setsid => new session, sid == pid of the sh; echo it before exec.
        return f"setsid sh -c 'echo $$; exec env {exports} {inner}'"

    # Bounds the pid-line wait: a connection that succeeds but whose remote
    # command is slow to echo must not wedge the scheduler thread forever
    # (ConnectTimeout only covers the connect phase).
    PID_READ_TIMEOUT_S = 30.0

    def exec_on(self, host, argv, env, log_file):
        proc = subprocess.Popen(
            self._ssh + [host, self._remote_command(argv, env)],
            stdout=subprocess.PIPE,
            stderr=log_file,
            start_new_session=True,
        )
        sshp = _SshProcess(proc, 0)
        got_pid = threading.Event()

        # The reader outlives the timeout: on an overloaded host the pid line
        # may arrive after we've returned, and a late update to sshp.pid is
        # what lets release()/kill_pg still reach the remote process group
        # (the echo is sh's first act, so "never arrives" means sh never
        # started and there is nothing remote to leak — unless the local
        # client is killed first, which release() guards with a grace wait).
        def _read():
            line = proc.stdout.readline()
            if line:
                try:
                    sshp.pid = int(line.strip())
                except ValueError:
                    log.warning("bad pid line from %s: %r", host, line[:80])
                got_pid.set()
                self._pump(proc.stdout, log_file)
            else:
                got_pid.set()  # EOF: ssh never reached the echo

        threading.Thread(target=_read, daemon=True).start()
        got_pid.wait(self.PID_READ_TIMEOUT_S)
        if sshp.pid <= 0:
            log.warning("no pid line from %s yet; continuing (pid may arrive late)", host)
        return sshp

    def exit_authoritative(self, code):
        # ssh propagates the remote command's exit code; 255 is ssh's OWN
        # error (auth/connection loss) and a negative code means the LOCAL
        # client was signal-killed — neither proves anything about the
        # remote process group
        return code != 255 and code >= 0

    def localize(self, host, src_dir, dst_dir):
        # tar over the ssh channel: no remote daemon, one round trip, and
        # permissions (the 0600 app.token) survive the copy
        tar = subprocess.Popen(
            ["tar", "-C", src_dir, "-cf", "-", "."], stdout=subprocess.PIPE
        )
        try:
            unpack = subprocess.run(
                self._ssh + [
                    host,
                    f"mkdir -p {shlex.quote(dst_dir)} && "
                    f"tar -xpf - -C {shlex.quote(dst_dir)}",
                ],
                stdin=tar.stdout,
                capture_output=True,
                timeout=600,
            )
        finally:
            tar.stdout.close()
            if tar.poll() is None:
                tar.kill()  # a hung/timed-out unpack must not leak the child
            tar_rc = tar.wait()
        if tar_rc != 0 or unpack.returncode != 0:
            raise RuntimeError(
                f"localization to {host}:{dst_dir} failed "
                f"(tar={tar_rc}, unpack={unpack.returncode}): "
                f"{unpack.stderr.decode(errors='replace')[-500:]}"
            )

    @staticmethod
    def _pump(src, dst) -> None:
        try:
            for chunk in iter(lambda: src.read(8192), b""):
                dst.write(chunk)
                dst.flush()
        except (OSError, ValueError):
            pass

    def kill_pg(self, host, pid, sig):
        if pid <= 0:
            return
        subprocess.run(
            self._ssh + [host, f"kill -{sig} -- -{pid}"],
            capture_output=True,
            timeout=30,
        )


def make_transport(name: str) -> Transport:
    if name == "local":
        return LocalTransport()
    if name == "ssh":
        return SshTransport()
    raise ValueError(f"unknown remote transport {name!r} (expected ssh | local)")


# --- the backend --------------------------------------------------------------


@dataclass
class _HostSlot:
    host: str
    capacity: Resource
    in_use: Resource = field(default_factory=lambda: Resource(0, 0, 0))
    label: str = ""
    # shared-RM mode: the slice of this host the job actually LEASED from
    # the cross-job store; placement is capped by it (None = no store, the
    # whole host belongs to this job's private inventory)
    budget: Resource | None = None

    def available(self) -> Resource:
        cap = self.capacity if self.budget is None else self.budget
        return cap - self.in_use


class RemoteBackend(_LeaseRenewalMixin):
    """Containers on a fixed inventory of remote hosts.

    Placement: first host whose remaining capacity fits the ask (and whose
    label matches the request's ``node_label``, if any) — hosts in config
    order, so task types land deterministically. The slice topology is fixed;
    elastic restart above this layer re-launches on the same hosts.
    """

    def __init__(
        self,
        hosts: Sequence[str],
        *,
        transport: Transport | str = "ssh",
        host_capacity: Resource | None = None,
        host_labels: Mapping[str, str] | None = None,
        localize: bool = False,
        localize_root: str = "",
        lease_store=None,
        app_id: str = "",
        rm_queue_timeout_s: float = 300.0,
    ):
        if not hosts:
            raise ValueError("RemoteBackend needs at least one host (cluster.hosts)")
        cap = host_capacity or Resource(memory_mb=1 << 20, cpus=256, tpu_chips=4)
        self._store = lease_store
        self._app_id = app_id or f"remote-{os.getpid()}"
        self._rm_queue_timeout_s = rm_queue_timeout_s
        self._reserved_gangs: set[str] = set()
        # store-packed container slots: [resource, node_label, host,
        # claimed_by_cid, gang_id] — allocate() claims a matching slot and
        # launches on ITS host, never re-packing greedily (see
        # _store_acquire); gang_id lets a losing on-demand lease be rolled
        # back slot-and-all (_store_release_gang)
        self._gang_slots: list[list] = []
        self._hosts = [
            _HostSlot(
                h,
                cap,
                label=(host_labels or {}).get(h, ""),
                budget=None if lease_store is None else Resource(0, 0, 0),
            )
            for h in hosts
        ]
        self.transport: Transport = (
            make_transport(transport) if isinstance(transport, str) else transport
        )
        # cluster.localize: copy the staged app dir to each host over the
        # transport before its first container, instead of requiring a shared
        # FS at the same path (the reference's HDFS localisation, SURVEY.md
        # section 3.1). The copy lands under <localize_root>/<host>/<app_id>
        # and TONY_APP_DIR/TONY_CONF_PATH are rewritten to it — the NM
        # container-localisation move. Default root assumes the same home
        # path on every host (the TPU-VM norm).
        self._localize = localize
        self._localize_root = localize_root or os.path.expanduser(
            os.path.join("~", ".tony-tpu", "localized")
        )
        # (host, app) -> Event set once the copy COMPLETES; concurrent
        # allocations for the same key wait on it instead of racing a
        # half-copied app dir (allocate() is not contractually serial)
        self._localized: dict[tuple[str, str], threading.Event] = {}
        self._containers: dict[str, Container] = {}
        self._procs: dict[str, RemoteProcess] = {}
        self._logs: dict[str, IO[bytes]] = {}
        self._slot_of: dict[str, _HostSlot] = {}
        self._released: set[str] = set()
        self._waiters: dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._next_id = 0
        self._cb: CompletionCallback | None = None
        self._stopped = False

    # --- protocol -------------------------------------------------------------

    def start(self) -> None:
        self._stopped = False
        if self._store is not None:
            # the store keys inventory by CANONICAL name, so "127.0.0.1"
            # here and the hostname a LocalProcessBackend registers are one
            # arbitrated machine, not two independently-leasable ones
            names = [canonical_host(s.host) for s in self._hosts]
            if len(set(names)) != len(names):
                log.warning(
                    "cluster.hosts repeats a hostname (possibly two "
                    "spellings of this machine); the shared RM store keys "
                    "inventory by canonical name, so duplicates collapse "
                    "to ONE host's capacity (conservative, but less than "
                    "you configured)"
                )
            self._store.register_hosts(
                {canonical_host(s.host): s.capacity for s in self._hosts},
                {canonical_host(s.host): s.label for s in self._hosts if s.label},
            )

    # --- shared-RM integration ---------------------------------------------

    def _store_acquire(
        self, gang_id: str, gang, timeout_s: float, cancel=None
    ) -> None:
        """Lease a gang through the shared store: widen the per-host budgets
        AND record the per-ask packing slots — placement must honor the
        store's packing exactly (a greedy re-pack over budgets can strand
        capacity: a small ask landing on the host the store packed a big
        ask onto leaves the big ask unplaceable). Once per gang_id (the
        store is idempotent across AM re-attempts)."""
        if gang_id in self._reserved_gangs:
            return
        packing = self._store.reserve_gang(
            self._app_id, gang, gang_id=gang_id, timeout_s=timeout_s,
            cancel=cancel,
        )
        self._reserved_gangs.add(gang_id)
        with self._lock:
            # the store speaks canonical names; map grants back to slots
            by_host = {canonical_host(s.host): s for s in self._hosts}
            for ask, host in packing:
                slot = by_host.get(host)
                if slot is not None and slot.budget is not None:
                    slot.budget = slot.budget + ask.resource
                if gang_id != "am":
                    # container AND on-demand asks become claimable
                    # placement slots: allocate() must land on the host the
                    # store packed, or the leased slice on the packed host
                    # strands (capacity lost to every job) while the greedy
                    # re-pack consumes some other host's leftover budget
                    self._gang_slots.append(
                        [ask.resource, ask.node_label,
                         slot.host if slot is not None else host, "", gang_id]
                    )

    def reserve_job(self, asks, *, timeout_s: float | None = None, cancel=None) -> None:
        if self._store is None:
            return
        from tony_tpu.cluster.lease import GangAsk

        mine = tuple(canonical_host(s.host) for s in self._hosts)
        gang = [
            GangAsk(r, node_label=label, candidates=mine) for r, label in asks
        ]
        if timeout_s is None:
            timeout_s = self._rm_queue_timeout_s
        self._store_acquire("containers", gang, timeout_s, cancel)

    def am_advertise_host(self) -> str:
        # remote executors must dial back across the network, never loopback
        return local_host()

    def kill_orphan(self, host: str, pid: int) -> None:
        self.transport.kill_pg(host, pid, signal.SIGKILL)

    def set_completion_callback(self, cb: CompletionCallback) -> None:
        self._cb = cb

    def total_capacity(self) -> Resource:
        total = Resource(0, 0, 0)
        for s in self._hosts:
            total = total + s.capacity
        return total

    def available(self) -> Resource:
        with self._lock:
            total = Resource(0, 0, 0)
            for s in self._hosts:
                total = total + s.available()
            return total

    def fits_one(self, r: Resource) -> bool:
        return any(r.fits_in(s.capacity) for s in self._hosts)

    def reserve(self, r: Resource) -> None:
        """AM footprint. When this machine is part of the inventory (some
        configured host resolves as local), the AM's resources come out of
        that host's capacity like any container — leased through the shared
        store first when one is attached, so even the AM's slice is
        arbitrated cross-job. Otherwise the AM runs OFF-inventory (the
        usual pod-slice layout: AM on the coordinator VM, workers on the
        slice) and its footprint is not counted — stated out loud so
        gang-allocation math never silently drifts."""
        with self._lock:
            am_slot = next(
                (s for s in self._hosts if canonical_host(s.host) == local_host()),
                None,
            )
        if am_slot is None:
            log.info(
                "AM host not in cluster.hosts; AM footprint %s runs "
                "off-inventory", r,
            )
            return
        if self._store is not None:
            from tony_tpu.cluster.lease import GangAsk

            self._store_acquire(
                "am", [GangAsk(r, host=canonical_host(am_slot.host))],
                self._rm_queue_timeout_s,
            )
        with self._lock:
            if r.fits_in(am_slot.available()):
                am_slot.in_use = am_slot.in_use + r
            else:
                log.warning(
                    "AM footprint %s does not fit host %s; not accounted",
                    r, am_slot.host,
                )

    def _unclaimed_slot_reserve(self, host: str) -> Resource:
        """Budget on ``host`` spoken for by UNCLAIMED gang slots — placement
        must keep its hands off it, or a direct allocate of a different
        shape could consume the budget backing a packed slot and the later
        slot claim would push the host past its store lease. Caller holds
        self._lock."""
        total = Resource(0, 0, 0)
        for gs in self._gang_slots:
            if gs[3] == "" and gs[2] == host:
                total = total + gs[0]
        return total

    def _place(self, request: ContainerRequest) -> _HostSlot:
        if request.node_label and not any(
            s.label == request.node_label for s in self._hosts
        ):
            # no amount of waiting invents a labelled host: fail fast
            raise ValueError(f"no host carries node label {request.node_label!r}")
        for s in self._hosts:
            if request.node_label and s.label != request.node_label:
                continue
            free = s.available() - self._unclaimed_slot_reserve(s.host)
            if request.resource.fits_in(free):
                return s
        raise InsufficientResources(
            f"no host fits {request.resource} (label={request.node_label!r})"
        )

    def _claim_gang_slot(self, request: ContainerRequest, cid: str) -> _HostSlot | None:
        """Claim a store-packed container slot matching (resource, label);
        returns its host's _HostSlot, or None when no gang slot matches.
        The claim re-checks the host still has room (its own slot counts
        as available again once excluded) — a defense in depth against
        placement having eaten slot-backing budget. Caller holds
        self._lock."""
        for gs in self._gang_slots:
            if gs[3] == "" and gs[0] == request.resource and gs[1] == request.node_label:
                s = next((h for h in self._hosts if h.host == gs[2]), None)
                if s is not None and request.resource.fits_in(s.available()):
                    gs[3] = cid
                    return s
                # host over-consumed or unknown: try another matching slot
        return None

    def _store_release_gang(self, gang_id: str) -> None:
        """Roll back a losing on-demand lease (nothing launched against
        it): withdraw its unclaimed slot(s) and host budget, then hand the
        gang back to the store. A slot a concurrent allocate already
        claimed stays — its backing lease now belongs to that container —
        so this can never release capacity that is still in use."""
        with self._lock:
            mine = [
                gs for gs in self._gang_slots
                if gs[4] == gang_id and gs[3] == ""
            ]
            for gs in mine:
                self._gang_slots.remove(gs)
                slot = next((h for h in self._hosts if h.host == gs[2]), None)
                if slot is not None and slot.budget is not None:
                    slot.budget = slot.budget - gs[0]
        if not mine:
            return
        self._reserved_gangs.discard(gang_id)
        try:
            self._store.release_gang(self._app_id, gang_id)
        except Exception:
            log.warning(
                "could not return losing on-demand lease %s (TTL/pid "
                "reaping will reclaim)", gang_id, exc_info=True,
            )

    def allocate(self, request: ContainerRequest) -> Container:
        if self._stopped:
            raise InsufficientResources("backend stopped")
        chaos_hook("backend.allocate", task=request.task_id, backend="remote")
        try:
            with self._lock:
                self._next_id += 1
                cid = f"container_{self._next_id:06d}"
                slot = self._claim_gang_slot(request, cid)
                if slot is None:
                    slot = self._place(request)
                slot.in_use = slot.in_use + request.resource
        except InsufficientResources:
            if self._store is None:
                raise
            # shared-RM mode without a covering reservation (direct
            # allocate, or a job grown past its gang): take an on-demand
            # single lease — immediate grant-or-raise, never double-booked
            from tony_tpu.cluster.lease import GangAsk

            # Acquire-then-claim loops: a concurrent allocate can steal the
            # just-granted slot between the store grant and our locked
            # claim, so the loser takes ANOTHER on-demand lease (fresh
            # gang_id — the idempotency guard would no-op a repeat) and
            # retries. Mirrors LocalProcessBackend. Each losing lease is
            # RETURNED to the store before the retry and the loop is
            # bounded: a store whose view of a host exceeds the local one
            # (another job registered it first, wider) would otherwise
            # grant unclaimable leases forever, every one stranded for the
            # job's lifetime.
            attempt = 0
            while True:
                gang_id = f"ondemand:{request.task_id}" + (
                    f":{attempt}" if attempt else ""
                )
                self._store_acquire(
                    gang_id,
                    [
                        GangAsk(
                            request.resource,
                            node_label=request.node_label,
                            candidates=tuple(
                                canonical_host(s.host) for s in self._hosts
                            ),
                        )
                    ],
                    0.0,
                )
                with self._lock:
                    self._next_id += 1
                    cid = f"container_{self._next_id:06d}"
                    # land on the host the store packed (recorded as a
                    # gang slot), never a greedy re-pack over stale budgets
                    slot = self._claim_gang_slot(request, cid)
                    if slot is None:
                        try:
                            slot = self._place(request)
                        except InsufficientResources:
                            slot = None
                    if slot is not None:
                        slot.in_use = slot.in_use + request.resource
                        break
                self._store_release_gang(gang_id)
                attempt += 1
                if attempt >= self.ONDEMAND_MAX_ATTEMPTS:
                    raise InsufficientResources(
                        f"on-demand lease for {request.task_id} was "
                        f"store-granted {attempt} times but never claimable "
                        "locally (store/local capacity views disagree, or "
                        "concurrent allocates keep winning)"
                    )
        if request.log_path:
            os.makedirs(os.path.dirname(request.log_path) or ".", exist_ok=True)
            out: IO[bytes] = open(request.log_path, "ab")
        else:
            out = open(os.devnull, "ab")
        env = dict(request.env)
        env["TONY_CONTAINER_ID"] = cid
        try:
            if self._localize:
                self._localize_app(slot.host, env)
            proc = self.transport.exec_on(slot.host, request.argv, env, out)
        except Exception:
            out.close()
            with self._lock:
                slot.in_use = slot.in_use - request.resource
                self._unclaim_gang_slot(cid)
            raise
        container = Container(
            container_id=cid,
            host=slot.host,
            resource=request.resource,
            request=request,
            state=ContainerState.RUNNING,
            pid=proc.pid,
        )
        with self._lock:
            self._containers[cid] = container
            self._procs[cid] = proc
            self._logs[cid] = out
            self._slot_of[cid] = slot
        waiter = threading.Thread(
            target=self._wait, args=(cid,), daemon=True, name=f"wait-{cid}"
        )
        with self._lock:
            self._waiters[cid] = waiter
        waiter.start()
        log.info(
            "allocated %s for %s on %s pid=%d",
            cid, request.task_id, slot.host, proc.pid,
        )
        return container

    def _unclaim_gang_slot(self, cid: str) -> None:
        """Free the gang slot a finished/failed container claimed, so a
        gang-restart relaunch lands on the same store-packed host. Caller
        holds self._lock."""
        for gs in self._gang_slots:
            if gs[3] == cid:
                gs[3] = ""
                return

    def _localize_app(self, host: str, env: dict) -> None:
        """Copy the app dir to ``host`` once per (host, app) and point the
        container's TONY_APP_DIR/TONY_CONF_PATH at the localized copy."""
        app_dir = env.get("TONY_APP_DIR", "")
        app_id = env.get("TONY_APP_ID") or os.path.basename(app_dir.rstrip("/"))
        if not app_dir:
            return
        dst = os.path.join(self._localize_root, host, app_id)
        key = (host, app_id)
        while True:
            with self._lock:
                done = self._localized.get(key)
                needed = done is None
                if needed:
                    done = self._localized[key] = threading.Event()
            if needed:
                try:
                    self.transport.localize(host, app_dir, dst)
                    log.info("localized %s to %s:%s", app_id, host, dst)
                except Exception:
                    with self._lock:
                        self._localized.pop(key, None)
                    done.set()  # wake waiters; they see the key changed
                    raise
                done.set()
                break
            if not done.wait(timeout=600):
                raise TimeoutError(
                    f"localization of {app_id} to {host} stalled"
                )
            with self._lock:
                current = self._localized.get(key)
            if current is done:
                break  # the copy we waited on completed successfully
            # failed-and-cleared (None) or another waiter already retrying
            # (a NEW event): loop to join/start the retry — never fall
            # through on bare key presence, a fresh in-flight event is not
            # a finished copy
        env["TONY_APP_DIR"] = dst
        env["TONY_CONF_PATH"] = os.path.join(dst, "config.json")

    def _wait(self, cid: str) -> None:
        proc = self._procs[cid]
        code = proc.wait()
        with self._lock:
            container = self._containers[cid]
            released = cid in self._released
            container.exit_code = code
            container.pid = proc.pid  # ssh pid may have arrived late
            container.exit_authoritative = self.transport.exit_authoritative(code)
            container.state = (
                ContainerState.RELEASED if released else ContainerState.COMPLETED
            )
            slot = self._slot_of[cid]
            slot.in_use = slot.in_use - container.resource
            self._unclaim_gang_slot(cid)
            logf = self._logs.pop(cid, None)
        if logf is not None:
            try:
                logf.close()
            except OSError:
                pass
        if not released and not self._stopped and self._cb is not None:
            self._cb(container, code)

    def release(self, container_id: str) -> None:
        with self._lock:
            container = self._containers.get(container_id)
            proc = self._procs.get(container_id)
            if container is None or container_id in self._released:
                return
            self._released.add(container_id)
        if proc is not None and proc.poll() is None:
            # proc.pid is live (an SshTransport pid can arrive late), unlike
            # the snapshot taken into container.pid at allocate time. Give a
            # late pid a short grace window before giving up on group-kill:
            # terminating the local ssh client first would strand the
            # setsid'd remote group with no handle left.
            grace = time.monotonic() + 3.0
            while proc.pid <= 0 and proc.poll() is None and time.monotonic() < grace:
                time.sleep(0.1)
            if proc.pid <= 0 and hasattr(proc, "terminate"):
                proc.terminate()  # no remote pid: tear down the local client
            self.transport.kill_pg(container.host, proc.pid, signal.SIGTERM)
            try:
                t = self._waiters.get(container_id)
                if t is not None:
                    t.join(timeout=3)
                if proc.poll() is None:
                    self.transport.kill_pg(container.host, proc.pid, signal.SIGKILL)
            except Exception:
                pass

    def stop(self) -> None:
        self._stopped = True
        with self._lock:
            cids = [c for c in self._procs if c not in self._released]
            self._released.update(cids)
        for cid in cids:
            c = self._containers[cid]
            proc = self._procs[cid]
            if proc.poll() is None:
                self.transport.kill_pg(c.host, proc.pid, signal.SIGKILL)
        for t in list(self._waiters.values()):
            t.join(timeout=10)
        if self._store is not None:
            # the job is over: hand every lease back to the shared RM —
            # bounded (and skipped entirely after a fence), so a hung
            # store can never wedge teardown before _write_status
            self._release_store_leases()
            self._reserved_gangs.clear()
            with self._lock:
                self._gang_slots.clear()
                for s in self._hosts:
                    s.budget = Resource(0, 0, 0)

    def containers(self) -> list[Container]:
        with self._lock:
            return list(self._containers.values())

    def container_pid(self, container_id: str) -> int:
        """Live process-group pid (an ssh pid may arrive after allocate)."""
        with self._lock:
            proc = self._procs.get(container_id)
        return proc.pid if proc is not None else 0


__all__ = [
    "LocalTransport",
    "RemoteBackend",
    "SshTransport",
    "Transport",
    "make_transport",
]

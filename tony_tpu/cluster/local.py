"""LocalProcessBackend: containers are local subprocesses.

This is the tony-mini ``MiniCluster`` lesson (SURVEY.md section 4) promoted to
a production backend: the resource substrate is faked at the infrastructure
level (fixed inventory, subprocess "containers"), so every framework code path
above it — AM scheduling, gang barrier, executor bootstrap, heartbeats,
restart — is genuine.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import threading
from typing import IO

from tony_tpu.chaos import chaos_hook
from tony_tpu.cluster.backend import (
    CompletionCallback,
    Container,
    ContainerRequest,
    ContainerState,
    InsufficientResources,
    Resource,
    _InventoryMixin,
    _LeaseRenewalMixin,
)
from tony_tpu.utils.net import local_host

log = logging.getLogger(__name__)


class LocalProcessBackend(_InventoryMixin, _LeaseRenewalMixin):
    """Subprocess containers against a fake, fixed inventory.

    With a shared :class:`~tony_tpu.cluster.lease.LeaseStore` attached
    (``cluster.rm_root``), the inventory is arbitrated ACROSS jobs: this
    host registers once in the store, and every claim — the AM footprint
    via :meth:`reserve` and the container gang via :meth:`reserve_job` —
    is leased there first, so two concurrent submits on the same machine
    queue FIFO instead of double-booking (the YARN-RM role the per-process
    inventory alone cannot play)."""

    def __init__(
        self,
        capacity: Resource | None = None,
        *,
        lease_store=None,
        app_id: str = "",
        rm_queue_timeout_s: float = 300.0,
    ):
        super().__init__(capacity or Resource(memory_mb=1 << 20, cpus=256, tpu_chips=64))
        self._store = lease_store
        self._app_id = app_id or f"local-{os.getpid()}"
        self._rm_queue_timeout_s = rm_queue_timeout_s
        self._job_budget = Resource(0, 0, 0)  # store-granted capacity
        self._reserved_gangs: set[tuple] = set()
        self._containers: dict[str, Container] = {}
        self._procs: dict[str, subprocess.Popen] = {}
        self._logs: dict[str, IO[bytes]] = {}
        self._waiters: dict[str, threading.Thread] = {}
        self._released: set[str] = set()
        self._lock = threading.Lock()
        self._next_id = 0
        self._cb: CompletionCallback | None = None
        self._stopped = False

    def start(self) -> None:
        self._stopped = False
        if self._store is not None:
            self._store.register_hosts({local_host(): self._capacity})

    # --- shared-RM integration ---------------------------------------------

    def _store_acquire(
        self, gang_id: str, resources, timeout_s: float, cancel=None
    ) -> None:
        """Lease through the shared store and widen this job's budget once
        per gang_id (the store itself is idempotent across AM restarts)."""
        from tony_tpu.cluster.lease import GangAsk

        if gang_id in self._reserved_gangs:
            return
        gang = [GangAsk(r, host=local_host()) for r in resources]
        self._store.reserve_gang(
            self._app_id, gang, gang_id=gang_id, timeout_s=timeout_s,
            cancel=cancel,
        )
        self._reserved_gangs.add(gang_id)
        with self._inv_lock:
            for a in gang:
                self._job_budget = self._job_budget + a.resource

    def reserve_job(self, asks, *, timeout_s: float | None = None, cancel=None) -> None:
        if self._store is None:
            return
        if timeout_s is None:
            timeout_s = self._rm_queue_timeout_s
        self._store_acquire("containers", [r for r, _ in asks], timeout_s, cancel)

    def reserve(self, r: Resource) -> None:
        if self._store is not None:
            # AM footprint through the same arbiter as every container
            self._store_acquire("am", [r], self._rm_queue_timeout_s)
        super().reserve(r)

    def _release_ondemand(self, gang_id: str, r: Resource) -> None:
        """Roll back a losing on-demand lease: withdraw its budget and hand
        it back to the store — but only when the widening is provably
        unconsumed. If a concurrent allocate already claimed against it,
        the lease now backs that claim and releasing it would let the
        store re-grant chips this job is still using."""
        with self._inv_lock:
            if not r.fits_in(self._job_budget - self._in_use):
                return
            self._job_budget = self._job_budget - r
        self._reserved_gangs.discard(gang_id)
        try:
            self._store.release_gang(self._app_id, gang_id)
        except Exception:
            log.warning(
                "could not return losing on-demand lease %s (TTL/pid "
                "reaping will reclaim)", gang_id, exc_info=True,
            )

    def _claim_within_budget(self, r: Resource, task_id: str) -> None:
        """Atomically budget-check AND claim under ONE ``_inv_lock``
        critical section (mirroring RemoteBackend's atomic budget-capped
        placement). In shared-RM mode a container may only consume
        store-leased budget; when short, an on-demand single lease is
        taken OUTSIDE the lock (an immediate grant-or-raise, so an
        un-reserved direct allocate still works when the cluster is idle
        but can never double-book) and the check re-runs — a concurrent
        allocate that consumed the widened budget in between just sends
        us around the loop again with a fresh lease id, never past the
        store's arbitration. The loop is bounded and every raise path
        returns the leases it acquired but never claimed: a store whose
        view of this host exceeds the local capacity (another job
        registered it first, wider) would otherwise grant leases forever
        that strand for the job's lifetime."""
        attempt = 0
        acquired: list[str] = []
        try:
            while True:
                with self._inv_lock:
                    if self._store is None or (self._in_use + r).fits_in(self._job_budget):
                        if not r.fits_in(self._capacity - self._in_use):
                            raise InsufficientResources(
                                f"ask {r} exceeds available {self._capacity - self._in_use}"
                            )
                        self._in_use = self._in_use + r
                        return
                if attempt >= self.ONDEMAND_MAX_ATTEMPTS:
                    raise InsufficientResources(
                        f"on-demand budget for {task_id} was store-granted "
                        f"{attempt} times but never claimable locally "
                        "(concurrent allocates keep winning the budget race)"
                    )
                gang_id = f"ondemand:{task_id}" + (f":{attempt}" if attempt else "")
                self._store_acquire(gang_id, [r], 0.0)
                acquired.append(gang_id)
                attempt += 1
        except BaseException:
            for gid in acquired:
                self._release_ondemand(gid, r)
            raise

    def shrink_job_lease(self, r: Resource, host: str = "") -> str | None:
        """Elastic shrink: hand a dead member's container lease back to
        the shared RM so other jobs can use the freed capacity while this
        one runs shrunk. Returns the freed host, None when the store
        refused (nothing matching / foreign owner), or "" without a store
        (per-job inventory: nothing to hand back). The job budget narrows
        with the lease so a later allocate cannot consume capacity the
        store may have re-granted elsewhere. ``host`` is accepted for
        interface parity with multi-host backends; every lease here is on
        this host anyway."""
        if self._store is None:
            return ""
        from tony_tpu.cluster.lease import GangAsk

        freed = self._store.shrink_gang(
            self._app_id, "containers", ask=GangAsk(r, host=local_host()),
            host=local_host(),
        )
        if freed is not None:
            with self._inv_lock:
                self._job_budget = self._job_budget - r
        return freed

    def grow_job_lease(self, r: Resource) -> str | None:
        """Elastic grow-back: re-lease one container-sized ask — the
        gang's REAL GangAsk, so the relaunched member's chips are
        arbitrated exactly like the original reservation (a hardcoded
        token ask would double-book). Returns the granted host, None when
        no capacity is free right now (the AM retries on its cadence), or
        "" without a store."""
        if self._store is None:
            return ""
        from tony_tpu.cluster.lease import GangAsk

        host = self._store.grow_gang(
            self._app_id, "containers", GangAsk(r, host=local_host())
        )
        if host is not None:
            with self._inv_lock:
                self._job_budget = self._job_budget + r
        return host

    def am_advertise_host(self) -> str:
        # Containers are subprocesses on this host; loopback is correct.
        return "127.0.0.1"

    def kill_orphan(self, host: str, pid: int) -> None:
        # all containers live on this host; host is informational
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def set_completion_callback(self, cb: CompletionCallback) -> None:
        self._cb = cb

    def allocate(self, request: ContainerRequest) -> Container:
        if self._stopped:
            raise InsufficientResources("backend stopped")
        chaos_hook("backend.allocate", task=request.task_id, backend="local")
        if request.node_label:
            # One host, no labels: honour the ask by refusing it rather than
            # silently placing anywhere (RemoteBackend implements labels).
            # ValueError, not InsufficientResources: the scheduler retries the
            # latter, and no amount of waiting invents a labelled host.
            raise ValueError(
                f"LocalProcessBackend has no node labels (asked {request.node_label!r}); "
                "use cluster.backend='remote' for labelled placement"
            )
        self._claim_within_budget(request.resource, request.task_id)
        try:
            with self._lock:
                self._next_id += 1
                cid = f"container_{self._next_id:06d}"
            env = dict(os.environ)
            env.update(request.env)
            env["TONY_CONTAINER_ID"] = cid
            if request.log_path:
                os.makedirs(os.path.dirname(request.log_path) or ".", exist_ok=True)
                out: IO[bytes] = open(request.log_path, "ab")
            else:
                out = open(os.devnull, "ab")
            # Own process group so release() can kill the executor together
            # with the user training process it spawned.
            proc = subprocess.Popen(
                list(request.argv),
                env=env,
                stdout=out,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        except Exception:
            self._reclaim(request.resource)
            raise
        container = Container(
            container_id=cid,
            host=local_host(),
            resource=request.resource,
            request=request,
            state=ContainerState.RUNNING,
            pid=proc.pid,
        )
        with self._lock:
            self._containers[cid] = container
            self._procs[cid] = proc
            self._logs[cid] = out
        waiter = threading.Thread(target=self._wait, args=(cid,), daemon=True, name=f"wait-{cid}")
        with self._lock:
            self._waiters[cid] = waiter
        waiter.start()
        log.info("allocated %s for %s pid=%d", cid, request.task_id, proc.pid)
        return container

    def _wait(self, cid: str) -> None:
        proc = self._procs[cid]
        code = proc.wait()
        with self._lock:
            container = self._containers[cid]
            released = cid in self._released
            container.exit_code = code
            container.state = (
                ContainerState.RELEASED if released else ContainerState.COMPLETED
            )
            logf = self._logs.pop(cid, None)
        if logf is not None:
            try:
                logf.close()
            except OSError:
                pass
        self._reclaim(container.resource)
        if not released and not self._stopped and self._cb is not None:
            self._cb(container, code)

    def release(self, container_id: str) -> None:
        with self._lock:
            proc = self._procs.get(container_id)
            if proc is None or container_id in self._released:
                return
            self._released.add(container_id)
        self._kill(proc)

    @staticmethod
    def _kill(proc: subprocess.Popen, grace_s: float = 3.0) -> None:
        if proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        try:
            proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def stop(self) -> None:
        self._stopped = True
        with self._lock:
            cids = list(self._procs)
            self._released.update(cids)
        for cid in cids:
            self._kill(self._procs[cid])
        for cid, t in list(self._waiters.items()):
            t.join(timeout=10)
        if self._store is not None:
            # the job is over: hand every lease back to the shared RM —
            # bounded (and skipped entirely after a fence), so a hung
            # store can never wedge teardown before _write_status
            self._release_store_leases()
            self._reserved_gangs.clear()
            with self._inv_lock:
                self._job_budget = Resource(0, 0, 0)

    def containers(self) -> list[Container]:
        with self._lock:
            return list(self._containers.values())

    def container_pid(self, container_id: str) -> int:
        with self._lock:
            c = self._containers.get(container_id)
        return c.pid if c is not None else 0


__all__ = ["LocalProcessBackend"]

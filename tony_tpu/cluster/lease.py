"""Shared ResourceManager: cross-job lease arbitration over a file-locked store.

The reference's L0 is YARN's ResourceManager — ONE daemon arbitrating every
job's containers (SURVEY.md section 1 L0, section 3.1 ``YarnClient.
createApplication`` / RM scheduling). Each tony-tpu AM instantiates its own
backend, so without a shared authority two concurrent ``tony submit`` runs
against the same hosts would each believe they own full capacity and
double-book TPU chips. The :class:`LeaseStore` is that authority, rebuilt
without a daemon: a directory on a filesystem every submitter can reach
(same machine, or a shared FS across submit hosts), where every mutation is
a read-modify-write of one JSON state file under an exclusive ``flock``.

Grant discipline is **gang-atomic FIFO**: a job reserves its ENTIRE
container ask as one ticket (``reserve_gang``), which is granted only when
a feasible first-fit packing onto the registered hosts exists — so two
concurrent jobs can never interleave partial allocations into a cross-job
gang deadlock; the later job queues behind the earlier one (YARN FIFO
scheduler semantics) and runs when capacity frees, or times out with a
message naming the holders. Leases live for the job's duration (elastic
gang restarts relaunch into the same reservation) and are dropped by
``release_app`` at job end.

Crash safety: every app's entry records its owner (submit host, pid, pid
start time from ``/proc``); any later locked operation by a surviving
process on the same host reaps apps whose owner process is gone — the
recovery YARN gets from AM liveness tracking. Cross-host stale owners
cannot be pid-checked; ``force_release_app`` (surfaced as
``tony rm-status --release APP``) is the operator override.
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Iterator, Mapping, Sequence

from tony_tpu.cluster.backend import InsufficientResources, Resource

log = logging.getLogger(__name__)

STATE_FILE = "state.json"
LOCK_FILE = "lock"


@dataclass(frozen=True)
class GangAsk:
    """One container-sized ask inside a gang reservation.

    ``host`` pins the ask to a specific host (the AM-footprint case);
    ``node_label`` restricts packing to hosts registered with that label;
    ``candidates`` restricts packing to the asking job's OWN inventory —
    the store may know hosts from other jobs' configs, and a lease on a
    host this job cannot launch on would be capacity lost to everyone.
    """

    resource: Resource
    node_label: str = ""
    host: str = ""
    candidates: tuple[str, ...] = ()

    def allowed(self, host: str, label: str) -> bool:
        if self.host:
            return host == self.host
        if self.candidates and host not in self.candidates:
            return False
        return not self.node_label or label == self.node_label

    def to_json(self) -> dict:
        r = self.resource
        return {
            "memory_mb": r.memory_mb,
            "cpus": r.cpus,
            "tpu_chips": r.tpu_chips,
            "node_label": self.node_label,
            "host": self.host,
            "candidates": list(self.candidates),
        }

    @staticmethod
    def from_json(d: Mapping) -> "GangAsk":
        return GangAsk(
            Resource(d["memory_mb"], d["cpus"], d["tpu_chips"]),
            d.get("node_label", ""),
            d.get("host", ""),
            tuple(d.get("candidates", ())),
        )


def _pid_start_time(pid: int) -> int:
    """Linux process start time (clock ticks since boot) — pid-reuse guard."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # field 22, but the comm field (2) may contain spaces/parens: split
        # after the LAST ')' so weird process names can't shift the fields
        return int(stat.rsplit(")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return 0


def _pid_alive(pid: int, start_time: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        pass  # exists, owned by someone else
    if start_time:
        now = _pid_start_time(pid)
        if now and now != start_time:
            return False  # pid reused by a different process
    return True


class LeaseStore:
    """File-locked cross-job inventory arbiter (see module docstring)."""

    def __init__(
        self,
        root: str,
        *,
        owner_host: str = "",
        poll_interval_s: float = 0.1,
    ):
        self.root = os.path.abspath(os.path.expanduser(root))
        os.makedirs(self.root, exist_ok=True)
        self._lock_path = os.path.join(self.root, LOCK_FILE)
        self._state_path = os.path.join(self.root, STATE_FILE)
        self._owner_host = owner_host or _this_host()
        self._poll_interval_s = poll_interval_s

    # --- locked state access ------------------------------------------------

    @contextmanager
    def _locked(self) -> Iterator[dict]:
        """EXCLUSIVE flock over load → mutate → atomic replace.

        The state is persisted even when the body raises: rejection paths
        mutate (dequeue their ticket) and then raise, and that dequeue must
        land or the dead ticket would block the queue head forever.
        """
        with open(self._lock_path, "a+") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                before = ""
                try:
                    with open(self._state_path, "r") as f:
                        before = f.read()
                    state = json.loads(before)
                except (FileNotFoundError, json.JSONDecodeError):
                    state = {"hosts": {}, "apps": {}, "queue": [], "next_seq": 1}
                self._reap_dead_owners(state)
                try:
                    yield state
                finally:
                    # skip the rewrite when nothing changed: queued waiters
                    # poll under this lock every poll_interval, and a dirty
                    # write per read-only poll would churn a shared-FS file
                    after = json.dumps(state, indent=1)
                    if after != before:
                        tmp = self._state_path + ".tmp"
                        with open(tmp, "w") as f:
                            f.write(after)
                        os.replace(tmp, self._state_path)
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)

    def _reap_dead_owners(self, state: dict) -> None:
        """Drop apps (leases) and queue tickets whose owner process is gone.

        Only owners on THIS host can be liveness-checked; remote owners are
        left alone (explicit release or operator override). Tickets carry
        their own owner: a job that dies while QUEUED has no app entry yet,
        and its stale ticket would block the FIFO head forever."""
        dead = [
            app_id
            for app_id, app in state["apps"].items()
            if app["owner_host"] == self._owner_host
            and not _pid_alive(app["owner_pid"], app.get("owner_start", 0))
        ]
        for app_id in dead:
            log.warning("reaping leases of dead app %s", app_id)
            state["apps"].pop(app_id, None)
        state["queue"] = [
            t
            for t in state["queue"]
            if t["app_id"] not in dead
            and not (
                t.get("owner_host") == self._owner_host
                and not _pid_alive(t.get("owner_pid", 0), t.get("owner_start", 0))
            )
        ]

    # --- host registry ------------------------------------------------------

    def register_hosts(
        self, capacities: Mapping[str, Resource], labels: Mapping[str, str] | None = None
    ) -> None:
        """Union-register hosts. First registration pins a host's capacity;
        a later conflicting capacity is IGNORED with a loud warning (the
        conservative choice: silently widening a host that another job is
        already leasing from would re-open double-booking)."""
        labels = labels or {}
        with self._locked() as state:
            for host, cap in capacities.items():
                entry = {
                    "memory_mb": cap.memory_mb,
                    "cpus": cap.cpus,
                    "tpu_chips": cap.tpu_chips,
                    "label": labels.get(host, ""),
                }
                existing = state["hosts"].get(host)
                if existing is None:
                    state["hosts"][host] = entry
                elif existing != entry:
                    log.warning(
                        "host %s already registered as %s; keeping it "
                        "(this job declared %s)", host, existing, entry,
                    )

    # --- gang reservation ---------------------------------------------------

    def reserve_gang(
        self,
        app_id: str,
        asks: Sequence[GangAsk],
        *,
        gang_id: str = "containers",
        timeout_s: float = 0.0,
        cancel: Callable[[], bool] | None = None,
    ) -> list[tuple[GangAsk, str]]:
        """Atomically lease capacity for every ask, or queue for it (FIFO).

        Returns the packing ``[(ask, host), ...]``. Raises
        :class:`InsufficientResources` when the gang cannot be granted
        within ``timeout_s`` (0 = one immediate attempt), with a message
        naming the current holders. Idempotent per (app_id, gang_id):
        calling again returns the existing packing — gang restarts and AM
        re-attempts re-enter the same reservation (``gang_id`` keeps an
        app's distinct reservations — AM footprint vs containers — from
        colliding when their asks happen to be equal).
        """
        asks = list(asks)
        want = [a.to_json() for a in asks]
        deadline = time.monotonic() + timeout_s
        ticket_seq: int | None = None
        while True:
            with self._locked() as state:
                app = state["apps"].get(app_id)
                if app is not None:
                    for gang in app["gangs"]:
                        if gang["gang_id"] == gang_id:
                            if gang["asks"] != want:
                                raise LeaseStoreError(
                                    f"gang {gang_id!r} of {app_id} already "
                                    "reserved with different asks; release "
                                    "the app before reshaping the job"
                                )
                            return [
                                (a, h)
                                for a, h in zip(asks, gang["hosts"])
                            ]
                if not state["hosts"]:
                    raise LeaseStoreError(
                        "no hosts registered in the lease store; call "
                        "register_hosts() before reserve_gang()"
                    )
                infeasible = self._infeasible_reason(state, asks)
                if infeasible:
                    self._dequeue(state, app_id, ticket_seq)
                    raise InsufficientResources(
                        f"gang for {app_id} can never be placed: {infeasible}"
                    )
                if ticket_seq is None:
                    ticket_seq = state["next_seq"]
                    state["next_seq"] += 1
                    state["queue"].append(
                        {
                            "seq": ticket_seq,
                            "app_id": app_id,
                            "asks": want,
                            "owner_host": self._owner_host,
                            "owner_pid": os.getpid(),
                            "owner_start": _pid_start_time(os.getpid()),
                        }
                    )
                elif not any(t["seq"] == ticket_seq for t in state["queue"]):
                    # our ticket vanished without a grant: someone released
                    # this app externally (tony rm-status --release) — a
                    # clean rejection, not a crash
                    raise InsufficientResources(
                        f"gang for {app_id} was released externally while "
                        "queued (operator rm-status --release?)"
                    )
                head = min(state["queue"], key=lambda t: t["seq"])
                if head["seq"] == ticket_seq:
                    packing = self._try_pack(state, asks)
                    if packing is not None:
                        self._dequeue(state, app_id, ticket_seq)
                        self._commit(
                            state, app_id, gang_id, want, packing,
                            self._owner_host,
                        )
                        return list(zip(asks, packing))
                expired = time.monotonic() >= deadline
                cancelled = cancel is not None and cancel()
                if expired or cancelled:
                    holders = self._holders_summary(state, exclude=app_id)
                    self._dequeue(state, app_id, ticket_seq)
                    why = "cancelled" if cancelled else f"timed out ({timeout_s:.0f}s)"
                    raise InsufficientResources(
                        f"gang for {app_id} {why} waiting for capacity; "
                        f"current holders: {holders or 'none (queued behind another job)'}"
                    )
            time.sleep(self._poll_interval_s)

    @staticmethod
    def _dequeue(state: dict, app_id: str, seq: int | None) -> None:
        if seq is not None:
            state["queue"] = [
                t
                for t in state["queue"]
                if not (t["app_id"] == app_id and t["seq"] == seq)
            ]

    @staticmethod
    def _commit(
        state: dict, app_id: str, gang_id: str, want: list[dict],
        packing: list[str], owner_host: str,
    ) -> None:
        app = state["apps"].setdefault(
            app_id,
            {
                "owner_host": owner_host,
                "owner_pid": os.getpid(),
                "owner_start": _pid_start_time(os.getpid()),
                "gangs": [],
            },
        )
        app["gangs"].append(
            {
                "gang_id": gang_id,
                "asks": want,
                "hosts": packing,
                "granted_at": time.time(),
            }
        )

    # --- packing ------------------------------------------------------------

    def _host_available(self, state: dict) -> dict[str, Resource]:
        avail = {
            h: Resource(e["memory_mb"], e["cpus"], e["tpu_chips"])
            for h, e in state["hosts"].items()
        }
        for app in state["apps"].values():
            for gang in app["gangs"]:
                for ask, host in zip(gang["asks"], gang["hosts"]):
                    if host in avail:
                        avail[host] = avail[host] - GangAsk.from_json(ask).resource
        return avail

    def _try_pack(self, state: dict, asks: Sequence[GangAsk]) -> list[str] | None:
        """First-fit packing of the whole gang against current availability,
        hosts in registration order (matches RemoteBackend placement order).
        Returns per-ask hosts, or None if the gang does not fit NOW."""
        avail = self._host_available(state)
        packing: list[str] = []
        for ask in asks:
            placed = ""
            for h, entry in state["hosts"].items():
                if not ask.allowed(h, entry["label"]):
                    continue
                if ask.resource.fits_in(avail[h]):
                    avail[h] = avail[h] - ask.resource
                    placed = h
                    break
            if not placed:
                return None
            packing.append(placed)
        return packing

    def _infeasible_reason(self, state: dict, asks: Sequence[GangAsk]) -> str:
        """A gang that cannot fit even an EMPTY cluster should fail fast,
        not queue until timeout."""
        empty = {
            h: Resource(e["memory_mb"], e["cpus"], e["tpu_chips"])
            for h, e in state["hosts"].items()
        }
        for ask in asks:
            if not any(
                ask.allowed(h, state["hosts"][h]["label"])
                and ask.resource.fits_in(empty[h])
                for h in empty
            ):
                return (
                    f"ask {ask.resource} (label={ask.node_label!r}, "
                    f"host={ask.host!r}) fits no registered host even when idle"
                )
        # aggregate bound: the whole gang vs whole cluster (first-fit on an
        # empty cluster is not simulated exactly; the per-ask check plus the
        # aggregate bound catches the common impossibilities fast)
        total = Resource(0, 0, 0)
        for a in asks:
            total = total + a.resource
        cap = Resource(0, 0, 0)
        for r in empty.values():
            cap = cap + r
        if not total.fits_in(cap):
            return f"gang total {total} exceeds cluster capacity {cap}"
        return ""

    def _holders_summary(self, state: dict, exclude: str = "") -> str:
        parts = []
        for app_id, app in state["apps"].items():
            if app_id == exclude:
                continue
            total = Resource(0, 0, 0)
            n = 0
            for gang in app["gangs"]:
                for ask in gang["asks"]:
                    total = total + GangAsk.from_json(ask).resource
                    n += 1
            parts.append(
                f"{app_id} holds {n} leases ({total}) from "
                f"{app['owner_host']}:{app['owner_pid']}"
            )
        return "; ".join(parts)

    # --- release / inspection ----------------------------------------------

    def release_app(self, app_id: str) -> None:
        with self._locked() as state:
            state["apps"].pop(app_id, None)
            state["queue"] = [t for t in state["queue"] if t["app_id"] != app_id]

    # operator override for cross-host stale owners (cannot be pid-checked)
    force_release_app = release_app

    def available(self) -> dict[str, Resource]:
        with self._locked() as state:
            return self._host_available(state)

    def summary(self) -> dict:
        """Snapshot for `tony rm-status`: hosts, per-app leases, queue."""
        with self._locked() as state:
            avail = self._host_available(state)
            return {
                "root": self.root,
                "hosts": {
                    h: {
                        **e,
                        "available": {
                            "memory_mb": avail[h].memory_mb,
                            "cpus": avail[h].cpus,
                            "tpu_chips": avail[h].tpu_chips,
                        },
                    }
                    for h, e in state["hosts"].items()
                },
                "apps": {
                    app_id: {
                        "owner": f"{a['owner_host']}:{a['owner_pid']}",
                        "leases": [
                            # granted host LAST so it wins over the ask's
                            # own (usually empty) pin field
                            {**ask, "host": h}
                            for g in a["gangs"]
                            for ask, h in zip(g["asks"], g["hosts"])
                        ],
                    }
                    for app_id, a in state["apps"].items()
                },
                "queue": [
                    {"seq": t["seq"], "app_id": t["app_id"], "asks": len(t["asks"])}
                    for t in sorted(state["queue"], key=lambda t: t["seq"])
                ],
            }


class LeaseStoreError(RuntimeError):
    """Misuse of the store (e.g. reserving before registering hosts)."""


def _this_host() -> str:
    import socket

    return socket.gethostname()


__all__ = ["GangAsk", "LeaseStore", "LeaseStoreError"]

"""Shared ResourceManager: cross-job lease arbitration over a file-locked store.

The reference's L0 is YARN's ResourceManager — ONE daemon arbitrating every
job's containers (SURVEY.md section 1 L0, section 3.1 ``YarnClient.
createApplication`` / RM scheduling). Each tony-tpu AM instantiates its own
backend, so without a shared authority two concurrent ``tony submit`` runs
against the same hosts would each believe they own full capacity and
double-book TPU chips. The :class:`LeaseStore` is that authority, rebuilt
without a daemon: a directory on a filesystem every submitter can reach
(same machine, or a shared FS across submit hosts), where every mutation is
a read-modify-write of one JSON state file under an exclusive ``flock``.

Grant discipline is **gang-atomic FIFO**: a job reserves its ENTIRE
container ask as one ticket (``reserve_gang``), which is granted only when
a feasible first-fit packing onto the registered hosts exists — so two
concurrent jobs can never interleave partial allocations into a cross-job
gang deadlock; the later job queues behind the earlier one (YARN FIFO
scheduler semantics) and runs when capacity frees, or times out with a
message naming the holders. Leases live for the job's duration (elastic
gang restarts relaunch into the same reservation) and are dropped by
``release_app`` at job end.

Crash safety: every app's entry records its owner (submit host, pid, pid
start time from ``/proc``); any later locked operation by a surviving
process on the same host reaps apps whose owner process is gone — the
recovery YARN gets from AM liveness tracking. Cross-host stale owners are
covered by lease TTL (``cluster.lease_ttl_s``): entries carry their own
``ttl_s`` + ``renewed_at``, owners renew on the AM heartbeat cadence
(:meth:`LeaseStore.renew_app`, throttled) and while polling the grant
queue, and any surviving process reaps entries whose TTL lapsed —
UNLESS the owner is pid-verifiably alive on this host (local liveness
beats the coarse timer). ``force_release_app`` (surfaced as
``tony rm-status --release APP``) remains the immediate operator
override; plain ``release_app`` only releases entries the caller owns
(or dead/expired ones), so one job's teardown can never yank a live
sibling's chips.
"""

from __future__ import annotations

import fcntl
import json
import logging
import os
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable, Iterator, Mapping, Sequence

from tony_tpu.chaos import chaos_hook
from tony_tpu.cluster.backend import InsufficientResources, Resource
from tony_tpu.obs import trace

log = logging.getLogger(__name__)

STATE_FILE = "state.json"
LOCK_FILE = "lock"


@dataclass(frozen=True)
class GangAsk:
    """One container-sized ask inside a gang reservation.

    ``host`` pins the ask to a specific host (the AM-footprint case);
    ``node_label`` restricts packing to hosts registered with that label;
    ``candidates`` restricts packing to the asking job's OWN inventory —
    the store may know hosts from other jobs' configs, and a lease on a
    host this job cannot launch on would be capacity lost to everyone.
    """

    resource: Resource
    node_label: str = ""
    host: str = ""
    candidates: tuple[str, ...] = ()

    def allowed(self, host: str, label: str) -> bool:
        if self.host:
            return host == self.host
        if self.candidates and host not in self.candidates:
            return False
        return not self.node_label or label == self.node_label

    def to_json(self) -> dict:
        r = self.resource
        return {
            "memory_mb": r.memory_mb,
            "cpus": r.cpus,
            "tpu_chips": r.tpu_chips,
            "node_label": self.node_label,
            "host": self.host,
            "candidates": list(self.candidates),
        }

    @staticmethod
    def from_json(d: Mapping) -> "GangAsk":
        return GangAsk(
            Resource(d["memory_mb"], d["cpus"], d["tpu_chips"]),
            d.get("node_label", ""),
            d.get("host", ""),
            tuple(d.get("candidates", ())),
        )


def _pid_start_time(pid: int) -> int:
    """Linux process start time (clock ticks since boot) — pid-reuse guard."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            stat = f.read().decode("ascii", "replace")
        # field 22, but the comm field (2) may contain spaces/parens: split
        # after the LAST ')' so weird process names can't shift the fields
        return int(stat.rsplit(")", 1)[1].split()[19])
    except (OSError, IndexError, ValueError):
        return 0


def _pid_alive(pid: int, start_time: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        pass  # exists, owned by someone else
    if start_time:
        now = _pid_start_time(pid)
        if now and now != start_time:
            return False  # pid reused by a different process
    return True


class LeaseStore:
    """File-locked cross-job inventory arbiter (see module docstring)."""

    def __init__(
        self,
        root: str,
        *,
        owner_host: str = "",
        poll_interval_s: float = 0.1,
        lease_ttl_s: float = 0.0,
    ):
        self.root = os.path.abspath(os.path.expanduser(root))
        os.makedirs(self.root, exist_ok=True)
        self._lock_path = os.path.join(self.root, LOCK_FILE)
        self._state_path = os.path.join(self.root, STATE_FILE)
        self._owner_host = owner_host or _this_host()
        self._poll_interval_s = poll_interval_s
        # TTL THIS handle stamps onto entries it creates (0 = no expiry,
        # manual/pid reaping only). Each entry is reaped against its OWN
        # recorded ttl, so jobs with different configs coexist in one store.
        self._lease_ttl_s = lease_ttl_s
        self._last_renew = 0.0  # client-side renew throttle
        # Fence clock: monotonic time of the last ``renewed_at`` the store
        # actually RECORDED for this owner (commit, ticket enqueue, or an
        # unthrottled touch) — NOT of arbitrary locked ops, which don't
        # move the reapers' deadline. Survivors reap at renewed_at + ttl
        # on THEIR clock; the owner fences at ack + ttl/2, leaving half a
        # TTL of margin for wall-clock skew and scheduling delay.
        self._last_renew_ack = time.monotonic()

    @property
    def lease_ttl_s(self) -> float:
        return self._lease_ttl_s

    # --- locked state access ------------------------------------------------

    @contextmanager
    def _locked(self) -> Iterator[dict]:
        """EXCLUSIVE flock over load → mutate → atomic replace.

        The state is persisted even when the body raises: rejection paths
        mutate (dequeue their ticket) and then raise, and that dequeue must
        land or the dead ticket would block the queue head forever.
        """
        # chaos seam: hang_store blocks here (a hard-mounted shared FS that
        # stalls in open/flock), partition_host raises OSError here (store
        # unreachable from this owner only). BEFORE the flock, so an
        # injected outage in one process never locks the store for
        # survivors — exactly the real failure's shape. No-op unless this
        # process armed an injector.
        chaos_hook("lease.locked", root=self.root)
        # trace spine: one span per locked read-modify-write, so store
        # contention/hangs are visible on the shared timeline (no-op when
        # this process is untraced)
        sp = trace.span("lease.locked")
        with sp, open(self._lock_path, "a+") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                before = ""
                try:
                    with open(self._state_path, "r") as f:
                        before = f.read()
                    state = json.loads(before)
                except (FileNotFoundError, json.JSONDecodeError):
                    state = {"hosts": {}, "apps": {}, "queue": [], "next_seq": 1}
                self._reap_dead_owners(state)
                try:
                    yield state
                finally:
                    # skip the rewrite when nothing changed: queued waiters
                    # poll under this lock every poll_interval, and a dirty
                    # write per read-only poll would churn a shared-FS file
                    after = json.dumps(state, indent=1)
                    if after != before:
                        tmp = self._state_path + ".tmp"
                        with open(tmp, "w") as f:
                            f.write(after)
                        os.replace(tmp, self._state_path)
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)

    def _entry_dead(self, entry: Mapping) -> str:
        """Why this app/ticket entry should be reaped ('' = keep).

        Two independent detectors, mirroring YARN's AM-liveness tracking:

        - pid check — authoritative, but only for owners on THIS host;
        - TTL — an entry whose own ``ttl_s`` lapsed without renewal is
          reaped REGARDLESS of host (the cross-host crash case pid checks
          cannot cover), except when the owner is pid-verifiably alive
          here: local liveness always beats the coarse timer, so a
          same-host job wedged past its renew cadence is never yanked
          while its process still runs.
        """
        local = entry.get("owner_host") == self._owner_host
        alive = local and _pid_alive(
            entry.get("owner_pid", 0), entry.get("owner_start", 0)
        )
        if local and not alive:
            return "owner process gone"
        ttl = entry.get("ttl_s", 0)
        if ttl and not alive:
            renewed = entry.get("renewed_at", 0)
            if renewed and time.time() - renewed > ttl:
                return f"lease TTL lapsed ({ttl:.0f}s without renewal)"
        return ""

    def _reap_dead_owners(self, state: dict) -> None:
        """Drop apps (leases) and queue tickets whose owner is gone — by
        pid on this host, by TTL anywhere (see :meth:`_entry_dead`).
        Tickets carry their own owner: a job that dies while QUEUED has no
        app entry yet, and its stale ticket would block the FIFO head
        forever."""
        dead = {
            app_id: why
            for app_id, app in state["apps"].items()
            if (why := self._entry_dead(app))
        }
        for app_id, why in dead.items():
            log.warning("reaping leases of dead app %s (%s)", app_id, why)
            state["apps"].pop(app_id, None)
        state["queue"] = [
            t
            for t in state["queue"]
            if t["app_id"] not in dead and not self._entry_dead(t)
        ]

    # --- host registry ------------------------------------------------------

    def register_hosts(
        self, capacities: Mapping[str, Resource], labels: Mapping[str, str] | None = None
    ) -> None:
        """Union-register hosts. First registration pins a host's capacity;
        a later conflicting capacity is IGNORED with a loud warning (the
        conservative choice: silently widening a host that another job is
        already leasing from would re-open double-booking)."""
        labels = labels or {}
        with self._locked() as state:
            for host, cap in capacities.items():
                entry = {
                    "memory_mb": cap.memory_mb,
                    "cpus": cap.cpus,
                    "tpu_chips": cap.tpu_chips,
                    "label": labels.get(host, ""),
                }
                existing = state["hosts"].get(host)
                if existing is None:
                    state["hosts"][host] = entry
                elif existing != entry:
                    log.warning(
                        "host %s already registered as %s; keeping it "
                        "(this job declared %s)", host, existing, entry,
                    )

    # --- gang reservation ---------------------------------------------------

    def reserve_gang(
        self,
        app_id: str,
        asks: Sequence[GangAsk],
        *,
        gang_id: str = "containers",
        timeout_s: float = 0.0,
        cancel: Callable[[], bool] | None = None,
    ) -> list[tuple[GangAsk, str]]:
        """Atomically lease capacity for every ask, or queue for it (FIFO).

        Returns the packing ``[(ask, host), ...]``. Raises
        :class:`InsufficientResources` when the gang cannot be granted
        within ``timeout_s`` (0 = one immediate attempt), with a message
        naming the current holders. Idempotent per (app_id, gang_id):
        calling again returns the existing packing — gang restarts and AM
        re-attempts re-enter the same reservation (``gang_id`` keeps an
        app's distinct reservations — AM footprint vs containers — from
        colliding when their asks happen to be equal).
        """
        asks = list(asks)
        want = [a.to_json() for a in asks]
        deadline = time.monotonic() + timeout_s
        ticket_seq: int | None = None
        while True:
            with self._locked() as state:
                app = state["apps"].get(app_id)
                if app is not None:
                    for gang in app["gangs"]:
                        if gang["gang_id"] == gang_id:
                            if gang["asks"] != want:
                                raise LeaseStoreError(
                                    f"gang {gang_id!r} of {app_id} already "
                                    "reserved with different asks; release "
                                    "the app before reshaping the job"
                                )
                            # idempotent re-entry by a NEW process (AM
                            # restart attempt): take over ownership, or
                            # liveness/TTL tracking would keep following
                            # the dead predecessor and reap the live
                            # successor's leases out from under it.
                            # But ONLY from an owner that is dead or our
                            # own: a live incumbent (duplicate submit of
                            # the same app_id, or a cross-host restart
                            # before TTL expiry) must not be silently
                            # dispossessed — that launches a second gang
                            # onto chips the incumbent keeps using until
                            # its next renew fences it (~ttl/4 + heartbeat
                            # of double-booking). Dead same-host owners
                            # never reach here (_reap_dead_owners already
                            # dropped them), so refusing live non-owned
                            # incumbents loses only the
                            # cross-host-restart-within-TTL case, which
                            # force_release_app covers by design.
                            if not (
                                self._owned_by_caller(app)
                                or self._entry_dead(app)
                            ):
                                # like every rejection path: drop our own
                                # queued ticket (we may have enqueued while
                                # the incumbent's identical gang was still
                                # queued ahead) or the dead ticket would
                                # block the FIFO head for everyone
                                self._dequeue(state, app_id, ticket_seq)
                                log.warning(
                                    "refusing reservation takeover of %s "
                                    "gang %r from live owner %s:%s "
                                    "(duplicate submit? cross-host restart "
                                    "before TTL expiry needs "
                                    "force_release_app / tony rm-status "
                                    "--release)",
                                    app_id, gang_id,
                                    app.get("owner_host"),
                                    app.get("owner_pid"),
                                )
                                raise LeaseStoreError(
                                    f"gang {gang_id!r} of {app_id} is held "
                                    "by live owner "
                                    f"{app.get('owner_host')}:"
                                    f"{app.get('owner_pid')}; refusing "
                                    "ownership takeover (use "
                                    "force_release_app to override)"
                                )
                            app.update(
                                owner_host=self._owner_host,
                                owner_pid=os.getpid(),
                                owner_start=_pid_start_time(os.getpid()),
                                renewed_at=time.time(),
                                ttl_s=self._lease_ttl_s,
                            )
                            return [
                                (a, h)
                                for a, h in zip(asks, gang["hosts"])
                            ]
                if not state["hosts"]:
                    raise LeaseStoreError(
                        "no hosts registered in the lease store; call "
                        "register_hosts() before reserve_gang()"
                    )
                infeasible = self._infeasible_reason(state, asks)
                if infeasible:
                    self._dequeue(state, app_id, ticket_seq)
                    raise InsufficientResources(
                        f"gang for {app_id} can never be placed: {infeasible}"
                    )
                if ticket_seq is None:
                    ticket_seq = state["next_seq"]
                    state["next_seq"] += 1
                    state["queue"].append(
                        {
                            "seq": ticket_seq,
                            "app_id": app_id,
                            "asks": want,
                            "owner_host": self._owner_host,
                            "owner_pid": os.getpid(),
                            "owner_start": _pid_start_time(os.getpid()),
                            "renewed_at": time.time(),
                            "ttl_s": self._lease_ttl_s,
                        }
                    )
                    self._last_renew_ack = time.monotonic()
                elif not any(t["seq"] == ticket_seq for t in state["queue"]):
                    # our ticket vanished without a grant: someone released
                    # this app externally (tony rm-status --release) — a
                    # clean rejection, not a crash
                    raise InsufficientResources(
                        f"gang for {app_id} was released externally while "
                        "queued (operator rm-status --release?)"
                    )
                # each locked poll renews our ticket (and any leases this
                # app already holds — e.g. the AM gang granted while the
                # container gang queues), throttled to ttl/4 so read-only
                # polls keep skipping the state-file rewrite
                self._touch_entries(state, app_id, ticket_seq)
                head = min(state["queue"], key=lambda t: t["seq"])
                if head["seq"] == ticket_seq:
                    packing = self._try_pack(state, asks)
                    if packing is not None:
                        self._dequeue(state, app_id, ticket_seq)
                        self._commit(
                            state, app_id, gang_id, want, packing,
                            self._owner_host,
                        )
                        return list(zip(asks, packing))
                expired = time.monotonic() >= deadline
                cancelled = cancel is not None and cancel()
                if expired or cancelled:
                    holders = self._holders_summary(state, exclude=app_id)
                    self._dequeue(state, app_id, ticket_seq)
                    why = "cancelled" if cancelled else f"timed out ({timeout_s:.0f}s)"
                    raise InsufficientResources(
                        f"gang for {app_id} {why} waiting for capacity; "
                        f"current holders: {holders or 'none (queued behind another job)'}"
                    )
            time.sleep(self._poll_interval_s)

    @staticmethod
    def _dequeue(state: dict, app_id: str, seq: int | None) -> None:
        if seq is not None:
            state["queue"] = [
                t
                for t in state["queue"]
                if not (t["app_id"] == app_id and t["seq"] == seq)
            ]

    def _commit(
        self, state: dict, app_id: str, gang_id: str, want: list[dict],
        packing: list[str], owner_host: str,
    ) -> None:
        app = state["apps"].setdefault(
            app_id,
            {
                "owner_host": owner_host,
                "owner_pid": os.getpid(),
                "owner_start": _pid_start_time(os.getpid()),
                "renewed_at": time.time(),
                "ttl_s": self._lease_ttl_s,
                "gangs": [],
            },
        )
        app["gangs"].append(
            {
                "gang_id": gang_id,
                "asks": want,
                "hosts": packing,
                "granted_at": time.time(),
            }
        )
        self._last_renew_ack = time.monotonic()

    def _touch_entries(
        self, state: dict, app_id: str, ticket_seq: int | None = None
    ) -> None:
        """Refresh ``renewed_at`` on this app's entry and its queue
        ticket(s) — the specific ticket when ``ticket_seq`` is given (the
        grant-poll path), else every ticket of the app (the heartbeat
        path). Throttled to a quarter of each entry's own TTL so renewal
        traffic never dominates the store."""
        now = time.time()
        wrote = False
        app = state["apps"].get(app_id)
        if app is not None:
            ttl = app.get("ttl_s", 0)
            if ttl and now - app.get("renewed_at", 0) > ttl / 4:
                app["renewed_at"] = now
                wrote = True
        for t in state["queue"]:
            if t["seq"] == ticket_seq or (
                ticket_seq is None and t["app_id"] == app_id
            ):
                ttl = t.get("ttl_s", 0)
                if ttl and now - t.get("renewed_at", 0) > ttl / 4:
                    t["renewed_at"] = now
                    wrote = True
        if wrote:
            self._last_renew_ack = time.monotonic()

    def renew_app(self, app_id: str) -> bool:
        """Heartbeat-piggybacked lease renewal: the AM calls this on its
        supervision cadence (1s-ish); the client-side throttle makes the
        actual locked write at most once per ttl/4, and a no-op store
        (ttl 0) never locks at all.

        Returns False when the owner must FENCE — stop its containers
        because it no longer holds its chips:

        - the app's entries are GONE from a reachable store (TTL-reaped by
          a survivor, or an operator ran ``rm-status --release``) — the
          chips may already be re-leased to another job;
        - the store has been unreachable for longer than the TTL (e.g. a
          shared-FS partition), so survivors have by now reaped us and the
          same double-booking is imminent. Transient hiccups inside the
          TTL window just log and carry on: renewal has a 4x margin, a
          skipped beat is harmless.
        """
        if not self._lease_ttl_s:
            return True
        now = time.monotonic()
        if now - self._last_renew < self._lease_ttl_s / 4:
            return True
        try:
            with self._locked() as state:
                app = state["apps"].get(app_id)
                if app is not None and not self._owned_by_caller(app):
                    # a successor attempt took over this reservation
                    # (re-entry ownership transfer): this process is the
                    # SUPERSEDED owner and must not keep the entry alive —
                    # or a dead successor's reservation would never expire
                    log.error(
                        "leases of %s now belong to %s:%s (successor "
                        "attempt); this superseded owner must fence",
                        app_id, app.get("owner_host"), app.get("owner_pid"),
                    )
                    return False
                present = app is not None or any(
                    t["app_id"] == app_id for t in state["queue"]
                )
                self._touch_entries(state, app_id)
        except Exception as e:
            # fence at HALF the TTL since the last recorded renewal:
            # survivors reap at renewed_at + ttl on their own wall clock,
            # so the margin absorbs clock skew and scheduling delay —
            # fencing early is safe, fencing late double-books
            if now - self._last_renew_ack > self._lease_ttl_s / 2:
                log.error(
                    "lease store unreachable since the last recorded "
                    "renewal %.0fs ago (TTL %.0fs): fencing before "
                    "survivors reap %s",
                    now - self._last_renew_ack, self._lease_ttl_s, app_id,
                )
                return False
            log.warning("lease renewal hiccup (TTL margin covers it): %s", e)
            return True
        self._last_renew = now
        if not present:
            log.error(
                "leases of %s are GONE from the store (TTL-reaped or "
                "operator-released); owner must fence", app_id,
            )
        return present

    # --- packing ------------------------------------------------------------

    def _host_available(self, state: dict) -> dict[str, Resource]:
        avail = {
            h: Resource(e["memory_mb"], e["cpus"], e["tpu_chips"])
            for h, e in state["hosts"].items()
        }
        for app in state["apps"].values():
            for gang in app["gangs"]:
                for ask, host in zip(gang["asks"], gang["hosts"]):
                    if host in avail:
                        avail[host] = avail[host] - GangAsk.from_json(ask).resource
        return avail

    def _try_pack(self, state: dict, asks: Sequence[GangAsk]) -> list[str] | None:
        """First-fit packing of the whole gang against current availability,
        hosts in registration order (matches RemoteBackend placement order).
        Returns per-ask hosts, or None if the gang does not fit NOW."""
        avail = self._host_available(state)
        packing: list[str] = []
        for ask in asks:
            placed = ""
            for h, entry in state["hosts"].items():
                if not ask.allowed(h, entry["label"]):
                    continue
                if ask.resource.fits_in(avail[h]):
                    avail[h] = avail[h] - ask.resource
                    placed = h
                    break
            if not placed:
                return None
            packing.append(placed)
        return packing

    def _infeasible_reason(self, state: dict, asks: Sequence[GangAsk]) -> str:
        """A gang that cannot fit even an EMPTY cluster should fail fast,
        not queue until timeout."""
        empty = {
            h: Resource(e["memory_mb"], e["cpus"], e["tpu_chips"])
            for h, e in state["hosts"].items()
        }
        for ask in asks:
            if not any(
                ask.allowed(h, state["hosts"][h]["label"])
                and ask.resource.fits_in(empty[h])
                for h in empty
            ):
                return (
                    f"ask {ask.resource} (label={ask.node_label!r}, "
                    f"host={ask.host!r}) fits no registered host even when idle"
                )
        # aggregate bound: the whole gang vs whole cluster (first-fit on an
        # empty cluster is not simulated exactly; the per-ask check plus the
        # aggregate bound catches the common impossibilities fast)
        total = Resource(0, 0, 0)
        for a in asks:
            total = total + a.resource
        cap = Resource(0, 0, 0)
        for r in empty.values():
            cap = cap + r
        if not total.fits_in(cap):
            return f"gang total {total} exceeds cluster capacity {cap}"
        return ""

    def _holders_summary(self, state: dict, exclude: str = "") -> str:
        parts = []
        for app_id, app in state["apps"].items():
            if app_id == exclude:
                continue
            total = Resource(0, 0, 0)
            n = 0
            for gang in app["gangs"]:
                for ask in gang["asks"]:
                    total = total + GangAsk.from_json(ask).resource
                    n += 1
            parts.append(
                f"{app_id} holds {n} leases ({total}) from "
                f"{app['owner_host']}:{app['owner_pid']}"
            )
        return "; ".join(parts)

    # --- release / inspection ----------------------------------------------

    def _owned_by_caller(self, entry: Mapping) -> bool:
        return (
            entry.get("owner_host") == self._owner_host
            and entry.get("owner_pid") == os.getpid()
        )

    def release_app(self, app_id: str) -> bool:
        """Release an app's leases and tickets — but ONLY entries the
        caller owns, or entries that are already dead/expired (see
        :meth:`_entry_dead`). A live sibling's leases are refused with a
        warning (returns False): one job's teardown must never yank
        another's chips. Use :meth:`force_release_app` to override."""
        with self._locked() as state:
            app = state["apps"].get(app_id)
            if app is not None and not (
                self._owned_by_caller(app) or self._entry_dead(app)
            ):
                log.warning(
                    "refusing to release %s: owned by live %s:%s (use "
                    "force_release_app / tony rm-status --release)",
                    app_id, app.get("owner_host"), app.get("owner_pid"),
                )
                return False
            state["apps"].pop(app_id, None)
            state["queue"] = [
                t
                for t in state["queue"]
                if t["app_id"] != app_id
                or not (self._owned_by_caller(t) or self._entry_dead(t))
            ]
        return True

    # --- autoscale / elastic hooks (serve/frontend.py, am elastic path) -----

    @staticmethod
    def _emit_event(state: dict, op: str, app_id: str, gang_id: str,
                    host: str, owner: str) -> None:
        """Append one grow/shrink record to the store's bounded event log
        — the audit trail the chaos invariant checker replays
        (``lease-events-audit``): every elastic/autoscale capacity change
        must be attributable to an owner and a registered host."""
        ev = state.setdefault("events", [])
        ev.append({
            "ts": time.time(), "op": op, "app_id": app_id,
            "gang_id": gang_id, "host": host, "owner": owner,
        })
        if len(ev) > 512:
            del ev[: len(ev) - 512]

    def grow_gang(self, app_id: str, gang_id: str, ask: GangAsk) -> str | None:
        """Append ONE ask to an existing (or new) gang reservation if it
        fits current availability RIGHT NOW — the non-blocking grow hook
        the serving autoscaler calls on sustained queue depth. Returns
        the granted host, or None when no capacity is free (the
        autoscaler retries on its own cadence; queueing here would wedge
        a live serving job behind a batch ticket). Same ownership rules
        as release: only the app's owner (or a fresh app entry) may grow
        it."""
        with self._locked() as state:
            app = state["apps"].get(app_id)
            if app is not None and not self._owned_by_caller(app):
                log.warning(
                    "refusing to grow gang %r of %s: owned by live %s:%s",
                    gang_id, app_id, app.get("owner_host"), app.get("owner_pid"),
                )
                return None
            if not state["hosts"]:
                return None
            packing = self._try_pack(state, [ask])
            if packing is None:
                return None
            for gang in (app or {}).get("gangs", ()):
                if gang["gang_id"] == gang_id:
                    gang["asks"].append(ask.to_json())
                    gang["hosts"].append(packing[0])
                    self._touch_entries(state, app_id)
                    break
            else:
                self._commit(
                    state, app_id, gang_id, [ask.to_json()], packing,
                    self._owner_host,
                )
            self._emit_event(
                state, "grow", app_id, gang_id, packing[0],
                f"{self._owner_host}:{os.getpid()}",
            )
            return packing[0]

    def shrink_gang(self, app_id: str, gang_id: str,
                    ask: GangAsk | None = None,
                    host: str = "") -> str | None:
        """Drop one ask of a gang reservation and return its host: the
        LAST ask by default (the serve-autoscale shrink), or — with
        ``ask``/``host`` given — the last entry matching both (the
        elastic path hands back the dead member's REAL container lease;
        in a homogeneous gang the ask value alone cannot identify WHICH
        member's lease is being returned, so callers that know the dead
        host must pin it or the freed host may be a survivor's). Returns
        None when nothing matches. An emptied gang is removed like
        release_gang would."""
        want = ask.to_json() if ask is not None else None
        with self._locked() as state:
            app = state["apps"].get(app_id)
            if app is None:
                return None
            if not self._owned_by_caller(app) and not self._entry_dead(app):
                log.warning(
                    "refusing to shrink gang %r of %s: owned by live %s:%s",
                    gang_id, app_id, app.get("owner_host"), app.get("owner_pid"),
                )
                return None
            for gang in app["gangs"]:
                if gang["gang_id"] != gang_id or not gang["asks"]:
                    continue
                idx = len(gang["asks"]) - 1
                if want is not None or host:
                    while idx >= 0 and not (
                        (want is None or gang["asks"][idx] == want)
                        and (not host or gang["hosts"][idx] == host)
                    ):
                        idx -= 1
                    if idx < 0:
                        return None
                gang["asks"].pop(idx)
                freed = gang["hosts"].pop(idx)
                if not gang["asks"]:
                    app["gangs"] = [
                        g for g in app["gangs"] if g["gang_id"] != gang_id
                    ]
                    if not app["gangs"]:
                        state["apps"].pop(app_id, None)
                self._emit_event(
                    state, "shrink", app_id, gang_id, freed,
                    f"{self._owner_host}:{os.getpid()}",
                )
                return freed
            return None

    def release_gang(self, app_id: str, gang_id: str) -> bool:
        """Release ONE gang of an app while its other reservations stay
        live — the rollback path for a losing on-demand lease (the backend
        acquired it but a concurrent allocate consumed the matching local
        budget, or the store's view of a host exceeds the local one).
        Without this, every lost race strands a lease for the job's whole
        lifetime. Same ownership rules as :meth:`release_app`."""
        with self._locked() as state:
            app = state["apps"].get(app_id)
            if app is None:
                return True
            if not (self._owned_by_caller(app) or self._entry_dead(app)):
                log.warning(
                    "refusing to release gang %r of %s: owned by live %s:%s",
                    gang_id, app_id, app.get("owner_host"), app.get("owner_pid"),
                )
                return False
            app["gangs"] = [g for g in app["gangs"] if g["gang_id"] != gang_id]
            if not app["gangs"]:
                # a gang-less app entry would pin ownership forever while
                # holding nothing; queue tickets carry their own owner
                state["apps"].pop(app_id, None)
        return True

    def force_release_app(self, app_id: str) -> None:
        """Operator override (``tony rm-status --release``): drop the app's
        leases and tickets unconditionally, ignoring owner liveness — the
        fast path for a wedged or unreachable cross-host owner that TTL
        expiry has not yet caught."""
        with self._locked() as state:
            state["apps"].pop(app_id, None)
            state["queue"] = [t for t in state["queue"] if t["app_id"] != app_id]

    def available(self) -> dict[str, Resource]:
        with self._locked() as state:
            return self._host_available(state)

    def summary(self) -> dict:
        """Snapshot for `tony rm-status`: hosts, per-app leases, queue."""
        with self._locked() as state:
            avail = self._host_available(state)
            return {
                "root": self.root,
                "hosts": {
                    h: {
                        **e,
                        "available": {
                            "memory_mb": avail[h].memory_mb,
                            "cpus": avail[h].cpus,
                            "tpu_chips": avail[h].tpu_chips,
                        },
                    }
                    for h, e in state["hosts"].items()
                },
                "apps": {
                    app_id: {
                        "owner": f"{a['owner_host']}:{a['owner_pid']}",
                        "leases": [
                            # granted host LAST so it wins over the ask's
                            # own (usually empty) pin field
                            {**ask, "host": h}
                            for g in a["gangs"]
                            for ask, h in zip(g["asks"], g["hosts"])
                        ],
                    }
                    for app_id, a in state["apps"].items()
                },
                "queue": [
                    {"seq": t["seq"], "app_id": t["app_id"], "asks": len(t["asks"])}
                    for t in sorted(state["queue"], key=lambda t: t["seq"])
                ],
            }


class LeaseStoreError(RuntimeError):
    """Misuse of the store (e.g. reserving before registering hosts)."""


def _this_host() -> str:
    import socket

    return socket.gethostname()


__all__ = ["GangAsk", "LeaseStore", "LeaseStoreError"]

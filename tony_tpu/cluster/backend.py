"""Resource-manager abstraction: the YARN RM/NM analogue.

The reference sits on Hadoop YARN: the AM calls ``AMRMClientAsync.
addContainerRequest`` with (memory, vcores, yarn.io/gpu=n) and launches
executors through ``NMClientAsync.startContainer`` (SURVEY.md sections 1, 3.1).
There is no YARN here, so the substrate itself is a pluggable
``ClusterBackend`` with a first-class ``tpu`` resource type (the
``yarn.io/tpu`` analogue from BASELINE.json's north star). Backends:

- :class:`~tony_tpu.cluster.local.LocalProcessBackend` — containers are local
  subprocesses against a fake inventory. This is both the dev/test substrate
  (the tony-mini ``MiniCluster`` lesson, SURVEY.md section 4) and the
  single-host production path.
- :class:`~tony_tpu.cluster.remote.RemoteBackend` — containers are processes
  on a fixed set of remote hosts over a pluggable transport (ssh in
  production, local subprocesses in tests).
- :class:`~tony_tpu.cluster.tpu_vm.TpuVmBackend` — RemoteBackend plus TPU
  slice host discovery (explicit ``cluster.hosts`` today; Cloud TPU API
  discovery raises with instructions — no cloud creds in this image).
"""

from __future__ import annotations

import enum
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Mapping, Protocol, Sequence

log = logging.getLogger(__name__)


class ContainerState(enum.Enum):
    REQUESTED = "REQUESTED"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    RELEASED = "RELEASED"


@dataclass(frozen=True)
class Resource:
    """A container-sized resource ask. ``tpu_chips`` is the yarn.io/tpu analogue."""

    memory_mb: int = 2048
    cpus: int = 1
    tpu_chips: int = 0

    def fits_in(self, other: "Resource") -> bool:
        return (
            self.memory_mb <= other.memory_mb
            and self.cpus <= other.cpus
            and self.tpu_chips <= other.tpu_chips
        )

    def __add__(self, other: "Resource") -> "Resource":
        return Resource(
            self.memory_mb + other.memory_mb,
            self.cpus + other.cpus,
            self.tpu_chips + other.tpu_chips,
        )

    def __sub__(self, other: "Resource") -> "Resource":
        return Resource(
            self.memory_mb - other.memory_mb,
            self.cpus - other.cpus,
            self.tpu_chips - other.tpu_chips,
        )


@dataclass(frozen=True)
class ContainerRequest:
    """One container ask from the AM's TaskScheduler."""

    task_type: str
    task_index: int
    resource: Resource
    argv: Sequence[str]             # executor launch command
    env: Mapping[str, str] = field(default_factory=dict)
    log_path: str = ""              # container stdout+stderr destination
    node_label: str = ""            # placement constraint (RemoteBackend labels)

    @property
    def task_id(self) -> str:
        return f"{self.task_type}:{self.task_index}"


@dataclass
class Container:
    """A granted container. ``host`` feeds cluster-spec assembly.

    ``pid`` is the container's process-group leader on its host (0 when the
    backend has no such notion); the AM journals it so a restarted AM attempt
    can reap orphans from its predecessor.
    """

    container_id: str
    host: str
    resource: Resource
    request: ContainerRequest
    state: ContainerState = ContainerState.RUNNING
    exit_code: int | None = None
    pid: int = 0
    # False when the exit code only proves the *channel* to the container
    # died (an ssh client exiting 255), not the remote process group itself —
    # consumers must then treat the group as a possible orphan
    exit_authoritative: bool = True


# (container, exit_code) — fired from a backend thread when a container's
# process exits on its own (not via release()).
CompletionCallback = Callable[[Container, int], None]


class ClusterBackend(Protocol):
    """What the AM needs from a resource substrate.

    Unlike YARN's async two-phase allocate (request -> callback), allocation
    here is synchronous-or-raise: placement latency on local/TPU-VM substrates
    is dominated by process start, not by queueing, so the gang wait moves to
    the AM's registration barrier where it belongs.
    """

    def start(self) -> None: ...

    def stop(self) -> None:
        """Release every container and shut down."""
        ...

    def am_advertise_host(self) -> str:
        """The host executors should dial to reach AM-side services.

        Loopback is only correct when containers share the AM's host; a
        remote backend must return an externally-reachable address or every
        remote registration would silently dial the wrong machine.
        """
        ...

    def reserve(self, r: Resource) -> None:
        """Permanently claim capacity for out-of-band consumers (the AM's
        own footprint). Called once at AM startup."""
        ...

    def reserve_job(
        self,
        asks: Sequence[tuple[Resource, str]],
        *,
        timeout_s: float | None = None,
        cancel: Callable[[], bool] | None = None,
    ) -> None:
        """Gang-reserve the job's ENTIRE container ask (one (resource,
        node_label) pair per instance) before any allocate().

        With a shared :class:`~tony_tpu.cluster.lease.LeaseStore` attached
        this is the cross-job arbitration point — the YARN-RM analogue:
        the whole gang is leased atomically (FIFO-queued behind earlier
        jobs up to ``timeout_s``: None = the backend's configured queue
        timeout, 0 = one immediate attempt) so concurrent jobs cannot
        interleave into deadlock or double-book TPU chips. Without a store
        it is a no-op: the backend's private inventory is the only
        consumer. Idempotent — gang restarts re-enter the same
        reservation."""
        ...

    def kill_orphan(self, host: str, pid: int) -> None:
        """Kill a process group journalled by a previous AM attempt.

        ``host`` is where the group lives; a local backend may ignore it, a
        remote backend must route the kill through its transport.
        """
        ...

    def renew_leases(self) -> bool:
        """Renew this job's shared-RM lease TTLs (no-op True without a
        store). Called from the AM supervision loop on the heartbeat
        cadence; the store throttles the actual locked write. Returns
        False when the job's leases are GONE (TTL-reaped, operator
        release, or store unreachable past the TTL) — the AM must then
        fence: stop the job before its chips are double-booked."""
        ...

    def total_capacity(self) -> Resource: ...

    def available(self) -> Resource: ...

    def fits_one(self, r: Resource) -> bool:
        """Could a single container of this size EVER be placed (empty
        cluster)? Aggregate capacity is not enough for per-host backends:
        8 chips across two 4-chip hosts fit no 8-chip container."""
        ...

    def allocate(self, request: ContainerRequest) -> Container:
        """Grant + launch a container, or raise :class:`InsufficientResources`."""
        ...

    def container_pid(self, container_id: str) -> int:
        """Current process-group pid of a container (0 when unknown). May be
        fresher than the pid snapshotted at allocate time: a remote pid can
        arrive after launch (SshTransport's late pid line)."""
        ...

    def release(self, container_id: str) -> None:
        """Kill/release a container. No completion callback is fired."""
        ...

    def set_completion_callback(self, cb: CompletionCallback) -> None: ...


class InsufficientResources(RuntimeError):
    """The ask does not fit in the currently-available inventory."""


class _LeaseRenewalMixin:
    """Shared-RM renewal surface for backends carrying a ``_store``
    (LeaseStore or None), ``_app_id`` and ``_reserved_gangs``."""

    # set by fence_leases(): this job's leases are lost/unreachable and
    # teardown must NOT touch the store again
    _lease_fenced = False

    # lost on-demand acquire-then-claim races are bounded: past this many
    # store grants that never become locally claimable, allocate() gives
    # up instead of spinning (each losing lease is returned to the store)
    ONDEMAND_MAX_ATTEMPTS = 5

    def fence_leases(self) -> None:
        """The AM calls this when it fences (leases gone, or store
        unreachable past the TTL): teardown then skips ``release_app``
        entirely. Releasing would at best be redundant (the entries are
        already gone or TTL/pid reaping reclaims them) and at worst wedge
        the AM forever in a flock against the very store whose hang caused
        the fence — the ADVICE round-5 failure where the client never sees
        FAILED."""
        self._lease_fenced = True

    def _release_store_leases(self, timeout_s: float = 10.0) -> None:
        """Hand every lease back at job end — bounded. The release runs in
        a daemon thread with a join timeout so a store that hangs in
        open()/flock can never stall teardown past ``timeout_s``: the AM
        must always reach ``_write_status``, and an unreleased entry is
        reclaimed by pid/TTL reaping anyway."""
        if self._store is None:
            return
        if self._lease_fenced:
            log.warning(
                "fenced: skipping lease release of %s (reaping reclaims the "
                "entries; releasing could block on the unreachable store)",
                self._app_id,
            )
            return
        done = threading.Event()

        def _rel() -> None:
            try:
                self._store.release_app(self._app_id)
            except Exception:
                log.warning(
                    "lease release of %s failed (pid/TTL reaping will "
                    "reclaim)", self._app_id, exc_info=True,
                )
            finally:
                done.set()

        threading.Thread(target=_rel, daemon=True, name="lease-release").start()
        if not done.wait(timeout_s):
            log.error(
                "lease release of %s still blocked after %.0fs (hung store?); "
                "abandoning it to pid/TTL reaping so teardown can finish",
                self._app_id, timeout_s,
            )

    def renew_leases(self) -> bool:
        """Keep this job's store leases alive (TTL renewal); the AM calls
        this on its heartbeat cadence, the store throttles internally.
        False = this job's leases are gone (revoked or store unreachable
        past the TTL): the caller must stop the job before its chips are
        double-booked."""
        if self._store is None or not self._reserved_gangs:
            return True
        return self._store.renew_app(self._app_id)

    def lease_ttl_s(self) -> float:
        """TTL of this job's shared-RM leases (0 = no store / no expiry);
        the AM's lease keeper sizes its staleness fence from this."""
        return self._store.lease_ttl_s if self._store is not None else 0.0


class _InventoryMixin:
    """Shared capacity bookkeeping for backends with a fixed inventory."""

    def __init__(self, capacity: Resource):
        self._capacity = capacity
        self._in_use = Resource(0, 0, 0)
        self._inv_lock = threading.Lock()

    def total_capacity(self) -> Resource:
        return self._capacity

    def available(self) -> Resource:
        with self._inv_lock:
            return self._capacity - self._in_use

    def _claim(self, r: Resource) -> None:
        with self._inv_lock:
            if not r.fits_in(self._capacity - self._in_use):
                raise InsufficientResources(
                    f"ask {r} exceeds available {self._capacity - self._in_use}"
                )
            self._in_use = self._in_use + r

    def _reclaim(self, r: Resource) -> None:
        with self._inv_lock:
            self._in_use = self._in_use - r

    def reserve(self, r: Resource) -> None:
        """Permanently claim capacity for out-of-band consumers — the AM
        reserves its own footprint (am.memory_mb/am.cpus) here, the way a
        YARN AM container consumes queue capacity."""
        self._claim(r)

    def fits_one(self, r: Resource) -> bool:
        return r.fits_in(self._capacity)


__all__ = [
    "ClusterBackend",
    "CompletionCallback",
    "Container",
    "ContainerRequest",
    "ContainerState",
    "InsufficientResources",
    "Resource",
]

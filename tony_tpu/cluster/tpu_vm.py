"""TpuVmBackend: pod-slice hosts as containers.

The north star (BASELINE.json) has the AM "allocate TPU-VM pod-slice hosts as
YARN containers via a yarn.io/tpu resource type". This backend is thin
node-discovery glue over :class:`~tony_tpu.cluster.remote.RemoteBackend`:
every mechanism — remote launch, log streaming, process-group release,
completion callbacks, per-host chip inventory — is the RemoteBackend's, which
the E2E suite exercises with the local transport. What this class adds is
resolving a slice's worker hostnames:

- explicit ``cluster.hosts`` (pre-created slice whose workers you know) — the
  path that works today; or
- Cloud TPU API discovery (``tpu.nodes.get`` ``networkEndpoints``) — requires
  GCE credentials + network, neither of which exists in this image, so that
  path raises with instructions at ``start()``.

Slice topology is fixed: elastic restart is barrier-restart of the whole gang
(SURVEY.md section 5 "failure detection"), implemented above this layer; the
backend re-launches on the same hosts.
"""

from __future__ import annotations

from typing import Sequence

from tony_tpu.cluster.backend import Resource
from tony_tpu.cluster.remote import RemoteBackend, Transport


# chips per TPU-VM worker host by accelerator generation (public machine shapes)
CHIPS_PER_HOST = {"v4": 4, "v5litepod": 8, "v5p": 4, "v6e": 8}


def chips_per_host_for(accelerator_type: str) -> int:
    family = accelerator_type.split("-")[0]
    return CHIPS_PER_HOST.get(family, 4)


class TpuVmBackend(RemoteBackend):
    """RemoteBackend + TPU slice host discovery."""

    def __init__(
        self,
        hosts: Sequence[str] = (),
        *,
        accelerator_type: str = "v4-32",
        chips_per_host: int = 0,
        zone: str = "",
        project: str = "",
        node: str = "",
        transport: Transport | str = "ssh",
        localize: bool = False,
        localize_root: str = "",
        lease_store=None,
        app_id: str = "",
        rm_queue_timeout_s: float = 300.0,
    ):
        self.accelerator_type = accelerator_type
        self.zone = zone
        self.project = project
        self.node = node
        chips = chips_per_host or chips_per_host_for(accelerator_type)
        if not hosts:
            hosts = self._discover_hosts()
        super().__init__(
            hosts,
            transport=transport,
            host_capacity=Resource(memory_mb=1 << 20, cpus=256, tpu_chips=chips),
            localize=localize,
            localize_root=localize_root,
            lease_store=lease_store,
            app_id=app_id,
            rm_queue_timeout_s=rm_queue_timeout_s,
        )

    def _discover_hosts(self) -> list[str]:
        """Resolve worker hostnames from the Cloud TPU API (needs creds)."""
        raise RuntimeError(
            "TPU-VM host discovery needs the Cloud TPU API (no credentials/"
            "network in this environment). Set cluster.hosts to the slice's "
            "worker addresses explicitly, e.g. cluster.hosts = "
            '"t1v-n-xxxxxxx-w-0,t1v-n-xxxxxxx-w-1" — everything else '
            "(launch, logs, release) works over ssh from there."
        )


__all__ = ["CHIPS_PER_HOST", "TpuVmBackend", "chips_per_host_for"]

"""TpuVmBackend: pod-slice hosts as containers (documented stub).

The north star (BASELINE.json) has the AM "allocate TPU-VM pod-slice hosts as
YARN containers via a yarn.io/tpu resource type". On a real deployment each
``Container`` maps to one TPU-VM worker host of a pod slice:

- ``start()``        -> TPU API ``nodes.create`` (acceleratorType=v4-32 etc.)
                        or attach to a pre-created slice; discover worker
                        hostnames from instance metadata.
- ``allocate(req)``  -> pick the next unassigned worker host; run the executor
                        argv there over SSH with ``req.env`` exported
                        (equivalent of NMClientAsync.startContainer).
- ``release(cid)``   -> kill the remote process group.
- completion         -> SSH channel exit status -> completion callback.
- inventory          -> hosts x chips-per-host (v4: 4 chips/host).

The slice topology is fixed — elastic restart is barrier-restart of the whole
gang (SURVEY.md section 5 "failure detection"), which the AM implements above
this layer; the backend only needs to re-launch on the same (or replacement)
host.

No cloud credentials or network exist in this image, so this backend raises on
use; the protocol surface is kept identical to LocalProcessBackend so swapping
backends is a config change (``cluster.backend = "tpu_vm"``).
"""

from __future__ import annotations

from tony_tpu.cluster.backend import (
    CompletionCallback,
    Container,
    ContainerRequest,
    Resource,
)


class TpuVmBackend:
    """Stub: same protocol as LocalProcessBackend, gated on cloud access."""

    def __init__(
        self,
        accelerator_type: str = "v4-32",
        chips_per_host: int = 4,
        zone: str = "",
        project: str = "",
    ):
        self.accelerator_type = accelerator_type
        self.chips_per_host = chips_per_host
        self.zone = zone
        self.project = project

    def _unavailable(self) -> RuntimeError:
        return RuntimeError(
            "TpuVmBackend requires Cloud TPU API access (none in this "
            "environment); use cluster.backend = 'local'"
        )

    def start(self) -> None:
        raise self._unavailable()

    def stop(self) -> None:
        pass

    def total_capacity(self) -> Resource:
        raise self._unavailable()

    def available(self) -> Resource:
        raise self._unavailable()

    def allocate(self, request: ContainerRequest) -> Container:
        raise self._unavailable()

    def release(self, container_id: str) -> None:
        raise self._unavailable()

    def set_completion_callback(self, cb: CompletionCallback) -> None:
        pass


__all__ = ["TpuVmBackend"]

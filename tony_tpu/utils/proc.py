"""Subprocess helpers (the TaskExecutor.executeShell analogue)."""

from __future__ import annotations

import os
import subprocess
import sys
import threading
from dataclasses import dataclass
from typing import IO, Mapping, Sequence


def _pump(src: IO[bytes], dst: IO[bytes], prefix: bytes) -> None:
    for line in iter(src.readline, b""):
        try:
            dst.write(prefix + line)
            dst.flush()
        except ValueError:  # dst closed
            break
    src.close()


@dataclass
class LoggedProc:
    """A child process plus its log-pump thread.

    ``wait()`` drains the pump before returning so the tail of the child's
    output (typically the crash traceback) is never lost — the exact contract
    the reference executor needs ("stream logs, then propagate exit code",
    SURVEY.md section 2 "TaskExecutor").
    """

    proc: subprocess.Popen[bytes]
    pump: threading.Thread

    @property
    def pid(self) -> int:
        return self.proc.pid

    def poll(self) -> int | None:
        return self.proc.poll()

    def terminate(self) -> None:
        self.proc.terminate()

    def kill(self) -> None:
        self.proc.kill()

    def wait(self, timeout: float | None = None) -> int:
        code = self.proc.wait(timeout)
        self.pump.join(timeout=10)
        return code


def run_logged(
    command: str | Sequence[str],
    *,
    env: Mapping[str, str] | None = None,
    cwd: str | None = None,
    log_prefix: str = "",
    stdout: IO[bytes] | None = None,
) -> LoggedProc:
    """Start a command, streaming its output line-by-line with a prefix.

    A string runs through the shell (user ``command`` strings from config);
    a sequence execs argv directly.
    """
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    proc = subprocess.Popen(
        command,
        shell=isinstance(command, str),
        env=full_env,
        cwd=cwd,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    out = stdout if stdout is not None else sys.stdout.buffer
    t = threading.Thread(
        target=_pump, args=(proc.stdout, out, log_prefix.encode()), daemon=True
    )
    t.start()
    return LoggedProc(proc, t)

"""Shared utilities (reference: tony-core/.../util/Utils.java, HdfsUtils.java)."""

from tony_tpu.utils.net import bind_with_retry, find_free_port, local_host
from tony_tpu.utils.proc import LoggedProc, run_logged

__all__ = [
    "bind_with_retry", "find_free_port", "local_host", "LoggedProc",
    "run_logged",
]

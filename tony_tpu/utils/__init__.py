"""Shared utilities (reference: tony-core/.../util/Utils.java, HdfsUtils.java)."""

from tony_tpu.utils.net import find_free_port, local_host
from tony_tpu.utils.proc import LoggedProc, run_logged

__all__ = ["find_free_port", "local_host", "LoggedProc", "run_logged"]

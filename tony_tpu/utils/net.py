"""Network helpers.

The reference's executors bind-probe for free ports to build host:port cluster
specs (SURVEY.md section 5 notes this as a known race-prone wart). In the TPU
build only the AM RPC endpoint and the jax.distributed coordinator need ports;
ICI/DCN endpoints are invisible to user code, which shrinks the race window to
the coordinator port only.
"""

from __future__ import annotations

import socket


def local_host() -> str:
    return socket.gethostname()


def find_free_port(host: str = "") -> int:
    """Bind-probe an ephemeral port and release it.

    Racy by construction (the port can be taken between release and reuse);
    callers that can, should bind port 0 themselves and report what they got.
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]

"""Network helpers.

The reference's executors bind-probe for free ports to build host:port cluster
specs (SURVEY.md section 5 notes this as a known race-prone wart). In the TPU
build only the AM RPC endpoint and the jax.distributed coordinator need ports;
ICI/DCN endpoints are invisible to user code, which shrinks the race window to
the coordinator port only.
"""

from __future__ import annotations

import socket


def local_host() -> str:
    return socket.gethostname()


def canonical_host(name: str) -> str:
    """One canonical key per physical machine for cross-job arbitration.

    Backends spell the same machine differently (LocalProcessBackend
    registers the hostname, a RemoteBackend config may say ``127.0.0.1`` or
    ``localhost``); the shared LeaseStore keys inventory by name, so two
    spellings of one machine would be two independently-leasable hosts —
    silent double-booking. Loopback spellings and the local hostname all
    collapse to the hostname; anything else (a genuinely remote address)
    passes through untouched. Deliberately no DNS: resolution differing
    between submit hosts would make the key non-deterministic.
    """
    if name in ("", "localhost", "127.0.0.1", "::1") or name == socket.gethostname():
        return socket.gethostname()
    return name


def find_free_port(host: str = "") -> int:
    """Bind-probe an ephemeral port and release it.

    Racy by construction (the port can be taken between release and reuse);
    callers that can, should bind port 0 themselves and report what they got.
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]

"""Network helpers.

The reference's executors bind-probe for free ports to build host:port cluster
specs (SURVEY.md section 5 notes this as a known race-prone wart). In the TPU
build only the AM RPC endpoint and the jax.distributed coordinator need ports;
ICI/DCN endpoints are invisible to user code, which shrinks the race window to
the coordinator port only.
"""

from __future__ import annotations

import logging
import socket
import time
from typing import Callable

log = logging.getLogger(__name__)


def local_host() -> str:
    return socket.gethostname()


def canonical_host(name: str) -> str:
    """One canonical key per physical machine for cross-job arbitration.

    Backends spell the same machine differently (LocalProcessBackend
    registers the hostname, a RemoteBackend config may say ``127.0.0.1`` or
    ``localhost``); the shared LeaseStore keys inventory by name, so two
    spellings of one machine would be two independently-leasable hosts —
    silent double-booking. Loopback spellings and the local hostname all
    collapse to the hostname; anything else (a genuinely remote address)
    passes through untouched. Deliberately no DNS: resolution differing
    between submit hosts would make the key non-deterministic.
    """
    if name in ("", "localhost", "127.0.0.1", "::1") or name == socket.gethostname():
        return socket.gethostname()
    return name


def find_free_port(host: str = "") -> int:
    """Bind-probe an ephemeral port and release it.

    Racy by construction (the port can be taken between release and reuse);
    callers that can, should bind port 0 themselves and report what they got.
    When the consumer of the port is a server you control, use
    :func:`bind_with_retry` instead — it closes the pick-then-bind gap.
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]


def bind_with_retry(
    bind: Callable[[int], int | None],
    port: int,
    *,
    attempts: int = 8,
    retry_delay_s: float = 0.25,
) -> int:
    """Bounded bind-with-retry: the fix for the find_free_port TOCTOU.

    ``bind(port)`` must attempt the bind and return the bound port (or
    None / raise OSError on failure; ``port`` 0 means "pick ephemeral").
    A busy non-ephemeral port is retried up to ``attempts`` times with a
    short delay — enough for the restart races this actually covers
    (TIME_WAIT from the previous incarnation of the same listener, a
    probe socket not yet closed) — and then raises instead of silently
    serving on a port nobody registered. Ephemeral asks (``port`` 0)
    retry without the delay: each attempt picks a fresh port, so waiting
    buys nothing.
    """
    attempts = max(1, int(attempts))
    last_err: OSError | None = None
    for i in range(attempts):
        if i:
            if port:
                time.sleep(retry_delay_s)
            log.warning(
                "bind of port %d failed; retry %d/%d", port, i, attempts - 1
            )
        try:
            bound = bind(port)
        except OSError as e:
            last_err = e
            continue
        if bound:
            return bound
    raise OSError(
        f"could not bind port {port or '(ephemeral)'} after {attempts} "
        f"attempt(s)" + (f": {last_err}" if last_err else "")
    )

#!/bin/sh
# Regenerate tony_pb2.py from tony.proto. The generated file is committed
# because the image has protoc but not grpcio-tools; service stubs are
# hand-written in service.py.
set -e
cd "$(dirname "$0")"
protoc --python_out=. tony.proto

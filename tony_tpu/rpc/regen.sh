#!/bin/sh
# Regenerate tony_pb2.py from tony.proto. The generated file is committed
# because images may ship neither protoc nor grpcio-tools; service stubs are
# hand-written in service.py.
#
# Without protoc, new messages can be appended programmatically instead:
# parse the serialized FileDescriptorProto out of the committed tony_pb2.py
# with google.protobuf.descriptor_pb2, add DescriptorProtos for the new
# messages (keep tony.proto in sync by hand), reserialize, and rewrite the
# AddSerializedFile blob — the ServeRpc messages were added that way.
set -e
cd "$(dirname "$0")"
protoc --python_out=. tony.proto

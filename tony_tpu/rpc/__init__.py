"""Control-plane + serving-data-plane RPC: protobuf messages, gRPC services.

See tony.proto for the protocol and service.py for the plumbing.
"""

from tony_tpu.rpc import tony_pb2 as pb
from tony_tpu.rpc.service import (
    SERVE_SERVICE_NAME,
    SERVICE_NAME,
    ApplicationRpcClient,
    ApplicationRpcServicer,
    ServeRpcClient,
    ServeRpcServicer,
    serve,
    serve_rpc,
)

__all__ = [
    "ApplicationRpcClient",
    "ApplicationRpcServicer",
    "SERVE_SERVICE_NAME",
    "SERVICE_NAME",
    "ServeRpcClient",
    "ServeRpcServicer",
    "pb",
    "serve",
    "serve_rpc",
]

"""Control-plane RPC: protobuf messages + gRPC service/client.

See tony.proto for the protocol and service.py for the plumbing.
"""

from tony_tpu.rpc import tony_pb2 as pb
from tony_tpu.rpc.service import (
    SERVICE_NAME,
    ApplicationRpcClient,
    ApplicationRpcServicer,
    serve,
)

__all__ = [
    "ApplicationRpcClient",
    "ApplicationRpcServicer",
    "SERVICE_NAME",
    "pb",
    "serve",
]

"""Control-plane auth: per-application shared token.

The reference's security layer is Hadoop-native — Kerberos keytab login and
HDFS/RM delegation tokens propagated into container credentials, gated by
``tony.application.security.enabled`` (SURVEY.md section 2 "Security").
There is no Kerberos here; the equivalent trust model is a per-application
random token, minted by the client at staging time, passed to containers via
a file (never argv), and required on every control-plane RPC through gRPC
metadata. Gated by ``application.security.enabled`` just like the reference.
"""

from __future__ import annotations

import hmac
import os
import secrets

import grpc

TOKEN_FILE = "app.token"
_HEADER = "tony-auth-token"


def mint_token(app_dir: str) -> str:
    """Create the application token file (client-side, at staging)."""
    token = secrets.token_hex(32)
    path = os.path.join(app_dir, TOKEN_FILE)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(token)
    return token


def read_token(app_dir: str) -> str | None:
    try:
        with open(os.path.join(app_dir, TOKEN_FILE)) as f:
            return f.read().strip()
    except OSError:
        return None


class TokenServerInterceptor(grpc.ServerInterceptor):
    """Rejects any call without the right token (UNAUTHENTICATED)."""

    def __init__(self, token: str):
        self._token = token

        def deny(request, context):
            context.abort(grpc.StatusCode.UNAUTHENTICATED, "bad or missing token")

        self._deny = grpc.unary_unary_rpc_method_handler(deny)

    def intercept_service(self, continuation, handler_call_details):
        meta = dict(handler_call_details.invocation_metadata or ())
        if hmac.compare_digest(meta.get(_HEADER, ""), self._token):
            return continuation(handler_call_details)
        return self._deny


class TokenCallCredentials(grpc.AuthMetadataPlugin):
    """Client-side: attach the token to every call."""

    def __init__(self, token: str):
        self._token = token

    def __call__(self, context, callback):
        callback(((_HEADER, self._token),), None)


def client_metadata(token: str) -> list[tuple[str, str]]:
    return [(_HEADER, token)]


__all__ = [
    "TOKEN_FILE",
    "TokenCallCredentials",
    "TokenServerInterceptor",
    "client_metadata",
    "mint_token",
    "read_token",
]

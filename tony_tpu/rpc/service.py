"""gRPC service plumbing for the control plane.

The reference exposes ``ApplicationRpc`` (registerWorkerSpec / getClusterSpec /
taskExecutorHeartbeat / registerExecutionResult / registerTensorBoardUrl /
getTaskInfos) and ``MetricsRpc`` as protobuf-over-Hadoop-RPC services
(SURVEY.md section 2). Here both are folded into one gRPC service,
``tony_tpu.ApplicationRpc``; stubs are hand-written against the generated
message classes because the image ships protoc but not grpcio-tools.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Any, Callable

import grpc

from tony_tpu.chaos import chaos_hook
from tony_tpu.obs import trace
from tony_tpu.obs.registry import get_registry
from tony_tpu.rpc import tony_pb2 as pb

log = logging.getLogger(__name__)

SERVICE_NAME = "tony_tpu.ApplicationRpc"

# method name -> (request class, response class). The single source of truth
# for both server handler table and client stubs.
_METHODS: dict[str, tuple[Any, Any]] = {
    "RegisterWorkerSpec": (pb.RegisterWorkerSpecRequest, pb.RegisterWorkerSpecResponse),
    "GetClusterSpec": (pb.GetClusterSpecRequest, pb.GetClusterSpecResponse),
    "Heartbeat": (pb.HeartbeatRequest, pb.HeartbeatResponse),
    "RegisterExecutionResult": (
        pb.RegisterExecutionResultRequest,
        pb.RegisterExecutionResultResponse,
    ),
    "RegisterTensorBoardUrl": (pb.RegisterTensorBoardUrlRequest, pb.Empty),
    "PushMetrics": (pb.PushMetricsRequest, pb.Empty),
    "GetTaskInfos": (pb.GetTaskInfosRequest, pb.GetTaskInfosResponse),
    "GetApplicationStatus": (
        pb.GetApplicationStatusRequest,
        pb.GetApplicationStatusResponse,
    ),
    "StopApplication": (pb.StopApplicationRequest, pb.Empty),
}


class ApplicationRpcServicer:
    """Override the methods you serve; unimplemented ones raise UNIMPLEMENTED."""

    def RegisterWorkerSpec(self, request, context):  # noqa: N802 (rpc casing)
        raise NotImplementedError

    def GetClusterSpec(self, request, context):  # noqa: N802
        raise NotImplementedError

    def Heartbeat(self, request, context):  # noqa: N802
        raise NotImplementedError

    def RegisterExecutionResult(self, request, context):  # noqa: N802
        raise NotImplementedError

    def RegisterTensorBoardUrl(self, request, context):  # noqa: N802
        raise NotImplementedError

    def PushMetrics(self, request, context):  # noqa: N802
        raise NotImplementedError

    def GetTaskInfos(self, request, context):  # noqa: N802
        raise NotImplementedError

    def GetApplicationStatus(self, request, context):  # noqa: N802
        raise NotImplementedError

    def StopApplication(self, request, context):  # noqa: N802
        raise NotImplementedError


def _remote_parent(context) -> str:
    """Span id the caller attached in metadata ('' for untraced callers)."""
    try:
        for k, v in context.invocation_metadata() or ():
            if k == trace.RPC_METADATA_KEY:
                return v.rsplit("/", 1)[-1]
    except Exception:
        pass
    return ""


def _wrap(method: Callable[[Any, Any], Any]) -> Callable[[Any, Any], Any]:
    requests = get_registry().counter(
        "tony_rpc_requests_total", "served control-plane RPCs",
        method=method.__name__,
    )

    def handler(request, context):
        # chaos seam: delay_rpc injects latency into served control-plane
        # calls (per-method filterable); no-op unless this process armed
        chaos_hook("rpc.server", method=method.__name__)
        requests.inc()
        tracer = trace.active_tracer()
        sp = trace.NOOP_SPAN
        if tracer is not None:
            # server dispatch span, parented on the CALLER's client span
            # via metadata — the cross-process edge of the trace tree
            sp = tracer.span(
                f"rpc.server/{method.__name__}",
                parent=_remote_parent(context) or None,
                method=method.__name__,
            )
        with sp:
            try:
                return method(request, context)
            except NotImplementedError:
                context.abort(grpc.StatusCode.UNIMPLEMENTED, "not implemented")
            except Exception as e:  # surface servicer bugs to the caller
                log.exception("rpc %s failed", method.__name__)
                context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

    return handler


def serve(
    servicer: ApplicationRpcServicer,
    host: str = "0.0.0.0",
    port: int = 0,
    max_workers: int = 16,
    token: str | None = None,
) -> tuple[grpc.Server, int]:
    """Start the RPC server; returns (server, bound_port).

    ``token`` enables per-application auth (application.security.enabled):
    every call must carry it in metadata (see rpc.auth).
    """
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            _wrap(getattr(servicer, name)),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
        for name, (req, resp) in _METHODS.items()
    }
    interceptors = ()
    if token:
        from tony_tpu.rpc.auth import TokenServerInterceptor

        interceptors = (TokenServerInterceptor(token),)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers), interceptors=interceptors
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"could not bind RPC port {host}:{port}")
    server.start()
    return server, bound


class ApplicationRpcClient:
    """Typed client for every control-plane method.

    Used by executors (register/heartbeat/result/metrics) and by the CLI
    (status/stop/task-infos) — the reference splits these across
    ApplicationRpcClient and YARN report polling; here the AM answers both.
    """

    def __init__(self, address: str, timeout_s: float = 10.0, token: str | None = None):
        self.address = address
        self.timeout_s = timeout_s
        self._metadata = None
        if token:
            from tony_tpu.rpc.auth import client_metadata

            self._metadata = client_metadata(token)
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.enable_retries", 1),
                ("grpc.keepalive_time_ms", 30000),
            ],
        )
        for name, (req, resp) in _METHODS.items():
            stub = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            setattr(self, f"_stub_{name}", stub)

    def _call(self, name: str, request, timeout_s: float | None = None):
        stub = getattr(self, f"_stub_{name}")
        tracer = trace.active_tracer()
        if tracer is None:
            return stub(
                request, timeout=timeout_s or self.timeout_s, metadata=self._metadata
            )
        # client dispatch span; its id rides the call metadata so the
        # server's span parents on it across the process boundary —
        # tracer.ctx() owns the "<trace_id>/<span_id>" wire format
        with tracer.span(f"rpc.client/{name}", method=name):
            md = tuple(self._metadata or ()) + (
                (trace.RPC_METADATA_KEY, tracer.ctx()),
            )
            return stub(request, timeout=timeout_s or self.timeout_s, metadata=md)

    # --- executor-side ---
    def register_worker_spec(
        self,
        job_name: str,
        index: int,
        host: str,
        port: int,
        attempt: int = 0,
        container_id: str = "",
    ) -> pb.RegisterWorkerSpecResponse:
        return self._call(
            "RegisterWorkerSpec",
            pb.RegisterWorkerSpecRequest(
                job_name=job_name,
                index=index,
                host=host,
                port=port,
                attempt=attempt,
                container_id=container_id,
            ),
        )

    def get_cluster_spec(
        self, job_name: str, index: int, attempt: int = 0
    ) -> pb.GetClusterSpecResponse:
        return self._call(
            "GetClusterSpec",
            pb.GetClusterSpecRequest(job_name=job_name, index=index, attempt=attempt),
        )

    def heartbeat(self, job_name: str, index: int, attempt: int = 0) -> pb.HeartbeatResponse:
        return self._call(
            "Heartbeat",
            pb.HeartbeatRequest(job_name=job_name, index=index, attempt=attempt),
        )

    def register_execution_result(
        self, job_name: str, index: int, exit_code: int, message: str = "", attempt: int = 0
    ) -> pb.RegisterExecutionResultResponse:
        return self._call(
            "RegisterExecutionResult",
            pb.RegisterExecutionResultRequest(
                job_name=job_name,
                index=index,
                exit_code=exit_code,
                message=message,
                attempt=attempt,
            ),
        )

    def register_tensorboard_url(self, url: str) -> None:
        self._call("RegisterTensorBoardUrl", pb.RegisterTensorBoardUrlRequest(url=url))

    def push_metrics(
        self, job_name: str, index: int, samples: list[tuple[str, float, float]]
    ) -> None:
        self._call(
            "PushMetrics",
            pb.PushMetricsRequest(
                job_name=job_name,
                index=index,
                samples=[
                    pb.MetricSample(name=n, value=v, timestamp=ts)
                    for n, v, ts in samples
                ],
            ),
        )

    # --- client-side ---
    def get_task_infos(self) -> pb.GetTaskInfosResponse:
        return self._call("GetTaskInfos", pb.GetTaskInfosRequest())

    def get_application_status(self) -> pb.GetApplicationStatusResponse:
        return self._call("GetApplicationStatus", pb.GetApplicationStatusRequest())

    def stop_application(self, reason: str = "") -> None:
        self._call("StopApplication", pb.StopApplicationRequest(reason=reason))

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "ApplicationRpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = [
    "ApplicationRpcClient",
    "ApplicationRpcServicer",
    "SERVICE_NAME",
    "serve",
]

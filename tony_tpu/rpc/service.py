"""gRPC service plumbing for the control plane.

The reference exposes ``ApplicationRpc`` (registerWorkerSpec / getClusterSpec /
taskExecutorHeartbeat / registerExecutionResult / registerTensorBoardUrl /
getTaskInfos) and ``MetricsRpc`` as protobuf-over-Hadoop-RPC services
(SURVEY.md section 2). Here both are folded into one gRPC service,
``tony_tpu.ApplicationRpc``; stubs are hand-written against the generated
message classes because the image ships protoc but not grpcio-tools.
"""

from __future__ import annotations

import logging
from concurrent import futures
from typing import Any, Callable

import grpc

from tony_tpu.chaos import chaos_hook
from tony_tpu.obs import trace
from tony_tpu.obs.registry import get_registry
from tony_tpu.rpc import tony_pb2 as pb

log = logging.getLogger(__name__)

SERVICE_NAME = "tony_tpu.ApplicationRpc"

# method name -> (request class, response class). The single source of truth
# for both server handler table and client stubs.
_METHODS: dict[str, tuple[Any, Any]] = {
    "RegisterWorkerSpec": (pb.RegisterWorkerSpecRequest, pb.RegisterWorkerSpecResponse),
    "GetClusterSpec": (pb.GetClusterSpecRequest, pb.GetClusterSpecResponse),
    "Heartbeat": (pb.HeartbeatRequest, pb.HeartbeatResponse),
    "RegisterExecutionResult": (
        pb.RegisterExecutionResultRequest,
        pb.RegisterExecutionResultResponse,
    ),
    "RegisterTensorBoardUrl": (pb.RegisterTensorBoardUrlRequest, pb.Empty),
    "PushMetrics": (pb.PushMetricsRequest, pb.Empty),
    "GetTaskInfos": (pb.GetTaskInfosRequest, pb.GetTaskInfosResponse),
    "GetApplicationStatus": (
        pb.GetApplicationStatusRequest,
        pb.GetApplicationStatusResponse,
    ),
    "StopApplication": (pb.StopApplicationRequest, pb.Empty),
    "StartProfile": (pb.StartProfileRequest, pb.StartProfileResponse),
}


class ApplicationRpcServicer:
    """Override the methods you serve; unimplemented ones raise UNIMPLEMENTED."""

    def RegisterWorkerSpec(self, request, context):  # noqa: N802 (rpc casing)
        raise NotImplementedError

    def GetClusterSpec(self, request, context):  # noqa: N802
        raise NotImplementedError

    def Heartbeat(self, request, context):  # noqa: N802
        raise NotImplementedError

    def RegisterExecutionResult(self, request, context):  # noqa: N802
        raise NotImplementedError

    def RegisterTensorBoardUrl(self, request, context):  # noqa: N802
        raise NotImplementedError

    def PushMetrics(self, request, context):  # noqa: N802
        raise NotImplementedError

    def GetTaskInfos(self, request, context):  # noqa: N802
        raise NotImplementedError

    def GetApplicationStatus(self, request, context):  # noqa: N802
        raise NotImplementedError

    def StopApplication(self, request, context):  # noqa: N802
        raise NotImplementedError

    def StartProfile(self, request, context):  # noqa: N802
        raise NotImplementedError


def _remote_parent(context) -> str:
    """Span id the caller attached in metadata ('' for untraced callers)."""
    try:
        for k, v in context.invocation_metadata() or ():
            if k == trace.RPC_METADATA_KEY:
                return v.rsplit("/", 1)[-1]
    except Exception:
        pass
    return ""


def _wrap(method: Callable[[Any, Any], Any]) -> Callable[[Any, Any], Any]:
    requests = get_registry().counter(
        "tony_rpc_requests_total", "served control-plane RPCs",
        method=method.__name__,
    )

    def handler(request, context):
        # chaos seam: delay_rpc injects latency into served control-plane
        # calls (per-method filterable); no-op unless this process armed
        chaos_hook("rpc.server", method=method.__name__)
        requests.inc()
        tracer = trace.active_tracer()
        sp = trace.NOOP_SPAN
        if tracer is not None:
            # server dispatch span, parented on the CALLER's client span
            # via metadata — the cross-process edge of the trace tree
            sp = tracer.span(
                f"rpc.server/{method.__name__}",
                parent=_remote_parent(context) or None,
                method=method.__name__,
            )
        with sp:
            try:
                return method(request, context)
            except NotImplementedError:
                context.abort(grpc.StatusCode.UNIMPLEMENTED, "not implemented")
            except Exception as e:  # surface servicer bugs to the caller
                log.exception("rpc %s failed", method.__name__)
                context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

    return handler


def serve(
    servicer: ApplicationRpcServicer,
    host: str = "0.0.0.0",
    port: int = 0,
    max_workers: int = 16,
    token: str | None = None,
) -> tuple[grpc.Server, int]:
    """Start the RPC server; returns (server, bound_port).

    ``token`` enables per-application auth (application.security.enabled):
    every call must carry it in metadata (see rpc.auth).
    """
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            _wrap(getattr(servicer, name)),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
        for name, (req, resp) in _METHODS.items()
    }
    interceptors = ()
    if token:
        from tony_tpu.rpc.auth import TokenServerInterceptor

        interceptors = (TokenServerInterceptor(token),)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers), interceptors=interceptors
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"could not bind RPC port {host}:{port}")
    server.start()
    return server, bound


class ApplicationRpcClient:
    """Typed client for every control-plane method.

    Used by executors (register/heartbeat/result/metrics) and by the CLI
    (status/stop/task-infos) — the reference splits these across
    ApplicationRpcClient and YARN report polling; here the AM answers both.
    """

    def __init__(self, address: str, timeout_s: float = 10.0, token: str | None = None):
        self.address = address
        self.timeout_s = timeout_s
        self._metadata = None
        if token:
            from tony_tpu.rpc.auth import client_metadata

            self._metadata = client_metadata(token)
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.enable_retries", 1),
                ("grpc.keepalive_time_ms", 30000),
            ],
        )
        for name, (req, resp) in _METHODS.items():
            stub = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            setattr(self, f"_stub_{name}", stub)

    def _call(self, name: str, request, timeout_s: float | None = None):
        stub = getattr(self, f"_stub_{name}")
        tracer = trace.active_tracer()
        if tracer is None:
            return stub(
                request, timeout=timeout_s or self.timeout_s, metadata=self._metadata
            )
        # client dispatch span; its id rides the call metadata so the
        # server's span parents on it across the process boundary —
        # tracer.ctx() owns the "<trace_id>/<span_id>" wire format
        with tracer.span(f"rpc.client/{name}", method=name):
            md = tuple(self._metadata or ()) + (
                (trace.RPC_METADATA_KEY, tracer.ctx()),
            )
            return stub(request, timeout=timeout_s or self.timeout_s, metadata=md)

    # --- executor-side ---
    def register_worker_spec(
        self,
        job_name: str,
        index: int,
        host: str,
        port: int,
        attempt: int = 0,
        container_id: str = "",
    ) -> pb.RegisterWorkerSpecResponse:
        return self._call(
            "RegisterWorkerSpec",
            pb.RegisterWorkerSpecRequest(
                job_name=job_name,
                index=index,
                host=host,
                port=port,
                attempt=attempt,
                container_id=container_id,
            ),
        )

    def get_cluster_spec(
        self, job_name: str, index: int, attempt: int = 0
    ) -> pb.GetClusterSpecResponse:
        return self._call(
            "GetClusterSpec",
            pb.GetClusterSpecRequest(job_name=job_name, index=index, attempt=attempt),
        )

    def heartbeat(self, job_name: str, index: int, attempt: int = 0) -> pb.HeartbeatResponse:
        return self._call(
            "Heartbeat",
            pb.HeartbeatRequest(job_name=job_name, index=index, attempt=attempt),
        )

    def register_execution_result(
        self, job_name: str, index: int, exit_code: int, message: str = "", attempt: int = 0
    ) -> pb.RegisterExecutionResultResponse:
        return self._call(
            "RegisterExecutionResult",
            pb.RegisterExecutionResultRequest(
                job_name=job_name,
                index=index,
                exit_code=exit_code,
                message=message,
                attempt=attempt,
            ),
        )

    def register_tensorboard_url(self, url: str) -> None:
        self._call("RegisterTensorBoardUrl", pb.RegisterTensorBoardUrlRequest(url=url))

    def push_metrics(
        self, job_name: str, index: int, samples: list[tuple[str, float, float]]
    ) -> None:
        self._call(
            "PushMetrics",
            pb.PushMetricsRequest(
                job_name=job_name,
                index=index,
                samples=[
                    pb.MetricSample(name=n, value=v, timestamp=ts)
                    for n, v, ts in samples
                ],
            ),
        )

    # --- client-side ---
    def get_task_infos(self) -> pb.GetTaskInfosResponse:
        return self._call("GetTaskInfos", pb.GetTaskInfosRequest())

    def get_application_status(self) -> pb.GetApplicationStatusResponse:
        return self._call("GetApplicationStatus", pb.GetApplicationStatusRequest())

    def stop_application(self, reason: str = "") -> None:
        self._call("StopApplication", pb.StopApplicationRequest(reason=reason))

    def start_profile(
        self, steps: int = 0, duration_s: float = 0.0
    ) -> pb.StartProfileResponse:
        """Ask the AM to broadcast a bounded profile window to the fleet
        (`tony profile <app_id>`; docs/OBS.md "Step anatomy")."""
        return self._call(
            "StartProfile",
            pb.StartProfileRequest(steps=steps, duration_s=duration_s),
        )

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "ApplicationRpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --- serving data plane (tony_tpu.ServeRpc) ----------------------------------
#
# The `tony serve` gang's RPC surface (docs/SERVE.md "Gang serving"): decode
# hosts serve it (serve/gang.py), the frontend both consumes it (routing) and
# re-serves it (the public endpoint), so one protocol covers client ->
# frontend -> host. Generate is server-streaming: tokens flow back as the
# engine samples them (the streaming completion return of the serve job type).

SERVE_SERVICE_NAME = "tony_tpu.ServeRpc"

# method name -> (request class, response class, server-streaming?)
_SERVE_METHODS: dict[str, tuple[Any, Any, bool]] = {
    "Generate": (pb.InferenceRequest, pb.TokenChunk, True),
    "DecodeStats": (pb.DecodeStatsRequest, pb.DecodeStatsResponse, False),
    "Drain": (pb.DrainRequest, pb.DrainResponse, False),
    "Prefill": (pb.PrefillRequest, pb.PrefillResponse, False),
    "ShipBlocks": (pb.ShipBlocksRequest, pb.ShipBlocksResponse, False),
}


class ServeRpcServicer:
    """Override the methods you serve; unimplemented ones raise UNIMPLEMENTED."""

    def Generate(self, request, context):  # noqa: N802 (rpc casing)
        raise NotImplementedError

    def DecodeStats(self, request, context):  # noqa: N802
        raise NotImplementedError

    def Drain(self, request, context):  # noqa: N802
        raise NotImplementedError

    def Prefill(self, request, context):  # noqa: N802
        raise NotImplementedError

    def ShipBlocks(self, request, context):  # noqa: N802
        raise NotImplementedError


def _wrap_stream(method: Callable[[Any, Any], Any]) -> Callable[[Any, Any], Any]:
    """Server-streaming twin of _wrap: the span covers the WHOLE stream
    (first chunk to exhaustion), so a slow consumer or a mid-stream death
    is visible as span duration / an error arg on the shared timeline."""
    requests = get_registry().counter(
        "tony_rpc_requests_total", "served control-plane RPCs",
        method=method.__name__,
    )

    def handler(request, context):
        chaos_hook("rpc.server", method=method.__name__)
        requests.inc()
        tracer = trace.active_tracer()
        sp = trace.NOOP_SPAN
        if tracer is not None:
            sp = tracer.span(
                f"rpc.server/{method.__name__}",
                parent=_remote_parent(context) or None,
                method=method.__name__,
            )
        with sp:
            try:
                yield from method(request, context)
            except NotImplementedError:
                context.abort(grpc.StatusCode.UNIMPLEMENTED, "not implemented")
            except Exception as e:  # surface servicer bugs to the caller
                log.exception("rpc %s failed", method.__name__)
                context.abort(grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}")

    return handler


def serve_rpc(
    servicer: ServeRpcServicer,
    host: str = "0.0.0.0",
    port: int = 0,
    max_workers: int = 16,
    token: str | None = None,
    bind_attempts: int = 1,
) -> tuple[grpc.Server, int]:
    """Start a ServeRpc server; returns (server, bound_port).

    ``bind_attempts`` > 1 retries a busy non-ephemeral port with a short
    backoff (utils.net.bind_with_retry): the decode host binds the exact
    port the executor registered in the cluster spec, and the old
    pick-then-bind gap means that port can be in TIME_WAIT or briefly
    stolen when the host restarts.
    """
    handlers = {}
    for name, (req, resp, streaming) in _SERVE_METHODS.items():
        make = (
            grpc.unary_stream_rpc_method_handler
            if streaming
            else grpc.unary_unary_rpc_method_handler
        )
        wrap = _wrap_stream if streaming else _wrap
        handlers[name] = make(
            wrap(getattr(servicer, name)),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
    interceptors = ()
    if token:
        from tony_tpu.rpc.auth import TokenServerInterceptor

        interceptors = (TokenServerInterceptor(token),)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers), interceptors=interceptors
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVE_SERVICE_NAME, handlers),)
    )
    from tony_tpu.utils.net import bind_with_retry

    bound = bind_with_retry(
        lambda p: server.add_insecure_port(f"{host}:{p}") or None,
        port, attempts=bind_attempts,
    )
    server.start()
    return server, bound


class ServeRpcClient:
    """Typed client for the serving data plane (frontend -> decode host,
    and external clients -> frontend). Same trace-context propagation as
    ApplicationRpcClient: every call's client span id rides the metadata
    so the server span parents on it across the process boundary."""

    def __init__(self, address: str, timeout_s: float = 30.0, token: str | None = None):
        self.address = address
        self.timeout_s = timeout_s
        self._metadata = None
        if token:
            from tony_tpu.rpc.auth import client_metadata

            self._metadata = client_metadata(token)
        self._channel = grpc.insecure_channel(
            address, options=[("grpc.enable_retries", 1)]
        )
        for name, (req, resp, streaming) in _SERVE_METHODS.items():
            make = self._channel.unary_stream if streaming else self._channel.unary_unary
            stub = make(
                f"/{SERVE_SERVICE_NAME}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            setattr(self, f"_stub_{name}", stub)

    def _metadata_with_ctx(self) -> tuple | None:
        tracer = trace.active_tracer()
        if tracer is None:
            return self._metadata
        return tuple(self._metadata or ()) + (
            (trace.RPC_METADATA_KEY, tracer.ctx()),
        )

    def generate(self, request: pb.InferenceRequest, timeout_s: float | None = None):
        """Server-streaming call; yields TokenChunk. The client span wraps
        only the DISPATCH (the stream outlives the call frame); chunk
        arrival cadence is the host-side serve.decode span's business."""
        tracer = trace.active_tracer()
        if tracer is not None:
            with tracer.span("rpc.client/Generate", method="Generate", rid=request.rid):
                return self._stub_Generate(
                    request, timeout=timeout_s or self.timeout_s,
                    metadata=self._metadata_with_ctx(),
                )
        return self._stub_Generate(
            request, timeout=timeout_s or self.timeout_s, metadata=self._metadata
        )

    def _call(self, name: str, request, timeout_s: float | None = None):
        stub = getattr(self, f"_stub_{name}")
        tracer = trace.active_tracer()
        if tracer is None:
            return stub(
                request, timeout=timeout_s or self.timeout_s, metadata=self._metadata
            )
        with tracer.span(f"rpc.client/{name}", method=name):
            return stub(
                request, timeout=timeout_s or self.timeout_s,
                metadata=self._metadata_with_ctx(),
            )

    def decode_stats(self, timeout_s: float | None = None) -> pb.DecodeStatsResponse:
        return self._call("DecodeStats", pb.DecodeStatsRequest(), timeout_s)

    def prefill(
        self,
        rid: str,
        prompt: list[int],
        target: str,
        rng_seed: int = 0,
        timeout_s: float | None = None,
    ) -> pb.PrefillResponse:
        """Ask a prefill host to prefill ``prompt`` and ship the finished KV
        blocks to ``target`` (a decode host address)."""
        return self._call(
            "Prefill",
            pb.PrefillRequest(rid=rid, prompt=prompt, target=target, rng_seed=rng_seed),
            timeout_s,
        )

    def ship_blocks(
        self, request: pb.ShipBlocksRequest, timeout_s: float | None = None
    ) -> pb.ShipBlocksResponse:
        """Stream a finished block payload to a decode host (prefill -> decode
        edge of the handoff; the caller builds the request from pack_payload)."""
        return self._call("ShipBlocks", request, timeout_s)

    def drain(
        self, timeout_s: float = 0.0, recycle: bool = False,
        rpc_timeout_s: float | None = None,
    ) -> pb.DrainResponse:
        # the RPC deadline must OUTLIVE the server-side work: the host's
        # drain wait (its own configured budget when timeout_s is 0 — the
        # client cannot see it, so allow generously) plus an engine rebuild
        # on recycle (model init + first compiles can take minutes on a
        # big model). A deadline shorter than the drain would report a
        # successfully drained host as failed.
        deadline = rpc_timeout_s or (timeout_s + 180.0 if timeout_s else 300.0)
        return self._call(
            "Drain", pb.DrainRequest(timeout_s=timeout_s, recycle=recycle),
            deadline,
        )

    def close(self) -> None:
        self._channel.close()

    def __enter__(self) -> "ServeRpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


__all__ = [
    "ApplicationRpcClient",
    "ApplicationRpcServicer",
    "SERVE_SERVICE_NAME",
    "SERVICE_NAME",
    "ServeRpcClient",
    "ServeRpcServicer",
    "serve",
    "serve_rpc",
]

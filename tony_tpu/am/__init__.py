"""ApplicationMaster: session state, scheduling, supervision, history."""

from tony_tpu.am.events import EventType, EventWriter, read_history
from tony_tpu.am.scheduler import (
    AllocationTimeout,
    DependencyTimeout,
    SchedulerHooks,
    TaskScheduler,
)
from tony_tpu.am.session import JobState, Session, Task, TaskState

__all__ = [
    "AllocationTimeout",
    "DependencyTimeout",
    "EventType",
    "EventWriter",
    "JobState",
    "SchedulerHooks",
    "Session",
    "Task",
    "TaskScheduler",
    "TaskState",
    "read_history",
]

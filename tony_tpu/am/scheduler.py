"""TaskScheduler: orders container requests, honours dependencies.

Rebuild of the reference's ``TaskScheduler.scheduleTasks`` (SURVEY.md
section 2): inter-task-type dependencies with timeouts (e.g. workers wait on
ps), GANG vs FCFS distributed modes, plus the partial-allocation guard the
survey ranks as hard part #3 (AM holds some containers while waiting for the
rest -> allocation timeout + release).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Callable, Mapping

from tony_tpu.am.session import Session, TaskState
from tony_tpu.cluster.backend import (
    ClusterBackend,
    ContainerRequest,
    InsufficientResources,
    Resource,
)
from tony_tpu.config.config import TaskTypeSpec

log = logging.getLogger(__name__)


class DependencyTimeout(RuntimeError):
    """A task type's depends_on did not reach readiness within its timeout."""


class AllocationTimeout(RuntimeError):
    """Gang allocation did not complete within am.allocation_timeout_s."""


@dataclass
class SchedulerHooks:
    """How the scheduler launches things (wired by the AM)."""

    # builds the executor ContainerRequest for a task instance
    make_request: Callable[[TaskTypeSpec, int], ContainerRequest]
    # called after a container is granted (records container_id/pid, journals)
    on_allocated: Callable[..., None]  # (job_name, idx, container, log_path)


class TaskScheduler:
    """Dependency-ordered, mode-aware container scheduling.

    GANG (default): all types are launched as resources permit, but the
    *cluster spec* is withheld until everyone registers (the barrier lives in
    Session.all_registered). FCFS: same launch order, but GetClusterSpec
    answers as soon as the asking task's own dependencies are satisfied —
    used for PS-style jobs where workers may start before all workers exist.

    depends_on gates *launch*: a type with ``depends_on = "ps"`` is not even
    allocated until every ps instance has REGISTERED (matches the reference's
    dependency-with-timeout semantics).
    """

    def __init__(
        self,
        session: Session,
        backend: ClusterBackend,
        hooks: SchedulerHooks,
        *,
        allocation_timeout_s: float = 300.0,
        poll_interval_s: float = 0.2,
    ):
        self.session = session
        self.backend = backend
        self.hooks = hooks
        self.allocation_timeout_s = allocation_timeout_s
        self.poll_interval_s = poll_interval_s
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    # --- dependency evaluation ----------------------------------------------

    def _dependency_ready(self, spec: TaskTypeSpec) -> bool:
        if not spec.depends_on:
            return True
        dep_tasks = self.session.tasks_of_type(spec.depends_on)
        if not dep_tasks:
            raise ValueError(
                f"job type {spec.name!r} depends on unknown type {spec.depends_on!r}"
            )
        return all(
            t.state in (TaskState.REGISTERED, TaskState.RUNNING, TaskState.SUCCEEDED)
            for t in dep_tasks
        )

    # --- main entry ---------------------------------------------------------

    def schedule_all(self, specs: Mapping[str, TaskTypeSpec]) -> None:
        """Launch every PENDING task, honouring dependencies and inventory.

        Blocks until all tasks are allocated or a timeout fires. Safe to call
        again after a gang restart (only PENDING tasks are touched).
        """
        deadline = time.monotonic() + self.allocation_timeout_s
        dep_deadlines: dict[str, float] = {}
        total_ask = self._total_ask(specs)
        cap = self.backend.total_capacity()
        if not total_ask.fits_in(cap):
            raise InsufficientResources(
                f"job needs {total_ask} but cluster capacity is {cap}"
            )
        for spec in specs.values():
            one = Resource(spec.memory_mb, spec.cpus, spec.tpu_chips)
            if not self.backend.fits_one(one):
                # aggregate capacity can mask a per-host impossibility
                # (8 chips over two 4-chip hosts); fail fast, don't spin
                # until the allocation timeout
                raise InsufficientResources(
                    f"no single host can fit a {spec.name!r} container ({one})"
                )
        # Cross-job arbitration: gang-reserve the WHOLE job through the
        # shared RM store (no-op without one) before any container launch —
        # FIFO-queued behind earlier jobs, so two jobs can never interleave
        # partial allocations into deadlock or double-book chips.
        self.backend.reserve_job(
            [
                (Resource(spec.memory_mb, spec.cpus, spec.tpu_chips), spec.node_label)
                for name in sorted(specs)
                for spec in (specs[name],)
                for _ in range(spec.instances)
            ],
            timeout_s=max(deadline - time.monotonic(), 0.0),
            cancel=lambda: self._stop,
        )
        while not self._stop:
            progress = False
            pending_left = False
            for name in sorted(specs):
                spec = specs[name]
                pending = [
                    t
                    for t in self.session.tasks_of_type(name)
                    if t.state == TaskState.PENDING
                ]
                if not pending:
                    continue
                if not self._dependency_ready(spec):
                    pending_left = True
                    dl = dep_deadlines.setdefault(
                        name,
                        time.monotonic() + (spec.depends_timeout_s or self.allocation_timeout_s),
                    )
                    if time.monotonic() > dl:
                        raise DependencyTimeout(
                            f"type {name!r} waited too long on {spec.depends_on!r}"
                        )
                    continue
                for t in pending:
                    req = self.hooks.make_request(spec, t.index)
                    try:
                        container = self.backend.allocate(req)
                    except InsufficientResources:
                        pending_left = True
                        break  # inventory full now; retry next sweep
                    t.state = TaskState.ALLOCATED
                    t.container_id = container.container_id
                    t.host = container.host
                    t.started_at = time.time()
                    self.hooks.on_allocated(name, t.index, container, req.log_path)
                    progress = True
            if not pending_left and all(
                t.state != TaskState.PENDING for t in self.session.tasks.values()
            ):
                return
            if time.monotonic() > deadline:
                raise AllocationTimeout(
                    f"gang allocation incomplete after {self.allocation_timeout_s}s"
                )
            if not progress:
                time.sleep(self.poll_interval_s)

    @staticmethod
    def _total_ask(specs: Mapping[str, TaskTypeSpec]) -> Resource:
        total = Resource(0, 0, 0)
        for spec in specs.values():
            for _ in range(spec.instances):
                total = total + Resource(spec.memory_mb, spec.cpus, spec.tpu_chips)
        return total


__all__ = ["AllocationTimeout", "DependencyTimeout", "SchedulerHooks", "TaskScheduler"]

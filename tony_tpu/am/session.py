"""Session: AM-side job state machine.

Rebuild of the reference's ``TonySession`` / ``TonySession.TonyTask``
(SURVEY.md section 2): the task table, per-type counts, cluster-spec JSON
builder, completion/failure accounting, and the final-status decision
(untracked types excluded; chief semantics optional). All mutation goes
through one lock — the reference leans on concurrent collections inside a
multi-threaded AM; here threads are the RPC pool + monitor loop.
"""

from __future__ import annotations

import enum
import json
import threading
import time
from dataclasses import dataclass, field

from tony_tpu.config.config import TaskTypeSpec


class TaskState(enum.Enum):
    PENDING = "PENDING"          # not yet allocated
    ALLOCATED = "ALLOCATED"      # container granted, executor starting
    REGISTERED = "REGISTERED"    # executor registered (host:port known)
    RUNNING = "RUNNING"          # cluster spec delivered, user proc running
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    LOST = "LOST"                # heartbeat loss / container vanished


TERMINAL = frozenset({TaskState.SUCCEEDED, TaskState.FAILED, TaskState.LOST})


class JobState(enum.Enum):
    NEW = "NEW"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    KILLED = "KILLED"


@dataclass
class Task:
    """One task instance (the TonyTask analogue)."""

    job_name: str
    index: int
    state: TaskState = TaskState.PENDING
    host: str = ""
    port: int = 0
    container_id: str = ""
    container_pid: int = 0       # process-group leader on the container host
    exit_code: int | None = None
    attempt: int = 0             # bumped on every restart
    restarts: int = 0
    last_heartbeat: float = 0.0
    log_path: str = ""
    started_at: float = 0.0
    finished_at: float = 0.0

    @property
    def task_id(self) -> str:
        return f"{self.job_name}:{self.index}"

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


class Session:
    """Job state: task table + gang barrier + final-status accounting."""

    def __init__(self, specs: dict[str, TaskTypeSpec], *, chief_type: str = ""):
        self.specs = specs
        self.chief_type = chief_type  # if set, job finishes when chief does
        self.lock = threading.RLock()
        self.tasks: dict[str, Task] = {}
        self.state = JobState.NEW
        self.diagnostics = ""
        self.tensorboard_url = ""
        # generation bumps on every gang restart; executors of an older
        # generation are told to ABORT on heartbeat.
        self.generation = 0
        for spec in specs.values():
            for i in range(spec.instances):
                t = Task(job_name=spec.name, index=i)
                self.tasks[t.task_id] = t

    # --- lookups -----------------------------------------------------------

    def task(self, job_name: str, index: int) -> Task | None:
        return self.tasks.get(f"{job_name}:{index}")

    def tasks_of_type(self, job_name: str) -> list[Task]:
        return sorted(
            (t for t in self.tasks.values() if t.job_name == job_name),
            key=lambda t: t.index,
        )

    def tracked_tasks(self) -> list[Task]:
        return [t for t in self.tasks.values() if not self.specs[t.job_name].untracked]

    # --- registration / gang barrier ---------------------------------------

    def register(self, job_name: str, index: int, host: str, port: int, attempt: int) -> bool:
        """Record an executor registration. Returns False for unknown/stale."""
        with self.lock:
            t = self.task(job_name, index)
            if t is None or attempt != t.attempt:
                return False
            t.host, t.port = host, port
            t.state = TaskState.REGISTERED
            t.last_heartbeat = time.monotonic()
            return True

    def touch(self, job_name: str, index: int, attempt: int | None = None) -> bool:
        """Record executor liveness under the lock. Returns False for
        unknown/stale tasks (the caller should order an abort).

        Called from both the Heartbeat handler and GetClusterSpec polls:
        a registered executor spinning on the gang barrier is alive even
        though its heartbeat thread hasn't started yet — without this,
        gangs that take longer than heartbeat_interval*max_missed to
        assemble (dependency chains, capacity queueing) would have their
        early registrants spuriously marked LOST.
        """
        with self.lock:
            t = self.task(job_name, index)
            if t is None or (attempt is not None and attempt != t.attempt):
                return False
            t.last_heartbeat = time.monotonic()
            return True

    def mark_running(self, job_name: str, index: int) -> None:
        """REGISTERED -> RUNNING transition (cluster spec delivered)."""
        with self.lock:
            t = self.task(job_name, index)
            if t is not None and t.state == TaskState.REGISTERED:
                t.state = TaskState.RUNNING
                t.started_at = time.time()
                t.last_heartbeat = time.monotonic()

    def all_registered(self) -> bool:
        """The gang barrier: every instance of every type has registered.

        The reference assembles the cluster spec only after *all* task types
        register (SURVEY.md section 3.1 "gang barrier"); untracked types (e.g.
        tensorboard) are included in the spec but a job that defines them
        cannot hang on them — they still must register since they occupy
        containers. FCFS mode relaxes this per-type (see TaskScheduler).
        """
        with self.lock:
            return all(
                t.state not in (TaskState.PENDING, TaskState.ALLOCATED)
                for t in self.tasks.values()
            )

    def cluster_spec_json(self) -> str:
        """``{"worker": ["host:port", ...], "ps": [...]}`` — the TF_CONFIG shape."""
        with self.lock:
            spec = {
                name: [t.address for t in self.tasks_of_type(name)]
                for name in self.specs
            }
        return json.dumps(spec, sort_keys=True)

    # --- global rank assignment (jax.distributed contract) ------------------

    def rank_table(self) -> dict[str, int]:
        """task_id -> global rank, deterministic across processes.

        Ranks are assigned over *tracked* types in sorted-type order then
        index order, so the coordinator (rank 0) is the first instance of the
        first tracked type. Matches the JaxTpuRuntime contract: process_id is
        stable under gang restart (same table, new attempt numbers).
        """
        with self.lock:
            ranked = [
                t
                for name in sorted(self.specs)
                if not self.specs[name].untracked
                for t in self.tasks_of_type(name)
            ]
            return {t.task_id: i for i, t in enumerate(ranked)}

    def coordinator_task(self) -> Task | None:
        table = self.rank_table()
        for tid, rank in table.items():
            if rank == 0:
                return self.tasks[tid]
        return None

    # --- completion accounting ----------------------------------------------

    def on_task_completed(self, job_name: str, index: int, exit_code: int) -> None:
        with self.lock:
            t = self.task(job_name, index)
            if t is None or t.state in TERMINAL:
                return
            t.exit_code = exit_code
            t.finished_at = time.time()
            t.state = TaskState.SUCCEEDED if exit_code == 0 else TaskState.FAILED

    def on_task_lost(self, job_name: str, index: int) -> None:
        with self.lock:
            t = self.task(job_name, index)
            if t is None or t.state in TERMINAL:
                return
            t.finished_at = time.time()
            t.state = TaskState.LOST

    def failed_tasks(self) -> list[Task]:
        with self.lock:
            return [
                t
                for t in self.tracked_tasks()
                if t.state in (TaskState.FAILED, TaskState.LOST)
            ]

    def job_done(self) -> bool:
        """Done when all tracked tasks are terminal, or the chief is."""
        with self.lock:
            tracked = self.tracked_tasks()
            if not tracked:
                return True
            if self.chief_type:
                chief = [t for t in tracked if t.job_name == self.chief_type]
                if chief and all(t.state in TERMINAL for t in chief):
                    return True
            return all(t.state in TERMINAL for t in tracked)

    def final_status(self) -> tuple[JobState, int]:
        """(job state, client exit code) — untracked types never fail a job."""
        with self.lock:
            tracked = self.tracked_tasks()
            if self.chief_type:
                tracked = [t for t in tracked if t.job_name == self.chief_type] or tracked
            bad = [t for t in tracked if t.state in (TaskState.FAILED, TaskState.LOST)]
            if bad:
                code = next((t.exit_code for t in bad if t.exit_code), 1) or 1
                return JobState.FAILED, code
            return JobState.SUCCEEDED, 0

    # --- gang restart (elastic path) ----------------------------------------

    def reset_for_restart(self, job_names: set[str] | None = None) -> list[Task]:
        """Reset tasks to PENDING for re-launch; bump attempt + generation.

        ``job_names=None`` resets every task — the TPU barrier-restart
        (fixed-topology slice: one lost host restarts the whole gang,
        SURVEY.md section 5). Returns the reset tasks.
        """
        with self.lock:
            self.generation += 1
            reset: list[Task] = []
            for t in self.tasks.values():
                if job_names is not None and t.job_name not in job_names:
                    continue
                t.state = TaskState.PENDING
                t.host, t.port = "", 0
                t.container_id = ""
                t.container_pid = 0
                t.exit_code = None
                t.attempt += 1
                t.restarts += 1
                t.last_heartbeat = 0.0
                reset.append(t)
            return reset


__all__ = ["JobState", "Session", "Task", "TaskState", "TERMINAL"]

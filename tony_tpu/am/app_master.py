"""ApplicationMaster: the brain of a job.

Rebuild of the reference's ``TonyApplicationMaster`` (SURVEY.md sections 2,
3.1, 3.3): registers with the resource substrate, requests containers per task
type, launches executors, runs the control-plane RPC server, assembles the
cluster spec after all registrations (gang semantics), supervises heartbeats,
applies the failure/retry policy including the elastic worker-restart path,
emits history events, and reports final status.

Threading discipline (the survey flags AM state races as "the bug farm",
section 7 hard part #2): RPC handlers and backend callbacks never apply
failure policy themselves — they update the Session table (internally locked)
and enqueue notifications; the single main supervision loop makes every
life-cycle decision (restart / fail / finish).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import sys
import threading
import time
from typing import Any

from tony_tpu.am.events import EventType, EventWriter
from tony_tpu.chaos import chaos_hook
from tony_tpu.obs import hbm, health, profile as profile_mod, series, slo, trace
from tony_tpu.am.scheduler import SchedulerHooks, TaskScheduler
from tony_tpu.am.session import JobState, Session, TaskState, TERMINAL
from tony_tpu.cluster import make_backend
from tony_tpu.cluster.backend import Container, ContainerRequest, Resource
from tony_tpu.config.config import TaskTypeSpec, TonyConfig
from tony_tpu.config.keys import Keys
from tony_tpu.rpc import ApplicationRpcServicer, pb, serve

log = logging.getLogger(__name__)


class ApplicationMaster(ApplicationRpcServicer):
    """One instance per job. ``run()`` blocks until the job is terminal."""

    def __init__(self, config: TonyConfig, app_id: str, app_dir: str, am_attempt: int = 0):
        self.config = config
        self.app_id = app_id
        self.app_dir = app_dir
        self.am_attempt = am_attempt
        self.specs: dict[str, TaskTypeSpec] = config.task_specs()
        if not self.specs:
            raise ValueError("no job types configured (need job.<type>.instances)")
        max_total = config.get_int(Keys.TASK_MAX_TOTAL_INSTANCES, -1)
        total = sum(s.instances for s in self.specs.values())
        if 0 <= max_total < total:
            raise ValueError(
                f"{total} task instances exceed task.max_total_instances={max_total}"
            )
        chief = "chief" if "chief" in self.specs else ""
        # AM-side pre-schedule validation hook (reference: Framework.AMAdapter
        # validateConfig), e.g. mxnet requiring exactly one scheduler.
        from tony_tpu.runtime import make_runtime

        make_runtime(config.get_str(Keys.APPLICATION_FRAMEWORK, "jax")).validate(config)
        self.session = Session(self.specs, chief_type=chief)
        self.backend = make_backend(
            config.get_str(Keys.CLUSTER_BACKEND, "local"), config, app_id=app_id
        )
        self.events = EventWriter(
            app_id,
            config.get_str(Keys.HISTORY_INTERMEDIATE_DIR)
            or os.path.join(app_dir, "events"),
            config.get_str(Keys.HISTORY_FINISHED_DIR),
        )
        self.scheduler = TaskScheduler(
            self.session,
            self.backend,
            SchedulerHooks(self._make_request, self._on_allocated),
            allocation_timeout_s=config.get_float(Keys.AM_ALLOCATION_TIMEOUT_S, 300.0),
        )
        self._notifications: queue.Queue[tuple[str, Any]] = queue.Queue()
        self._server = None
        self.port = 0
        self._killed = threading.Event()
        self._heartbeat_interval_s = config.get_int(Keys.TASK_HEARTBEAT_INTERVAL_MS, 1000) / 1000
        self._max_missed = config.get_int(Keys.TASK_MAX_MISSED_HEARTBEATS, 25)
        self._restart_policy = config.get_str(Keys.RESTART_POLICY, "never")
        self._max_restarts = config.get_int(Keys.RESTART_MAX_WORKER_RESTARTS, 0)
        if (
            config.get_str(Keys.APPLICATION_FRAMEWORK) == "serve"
            and self._restart_policy == "never"
        ):
            # gang-serving supervision: decode hosts are SERVICES. Under
            # `never` (the training-oriented baked default) one container
            # death fails the whole job and tears down every survivor
            # mid-stream — the opposite of the serving contract, where the
            # frontend re-queues the dead host's in-flight requests onto
            # survivors while the AM relaunches just the lost host. Jobs
            # that really want never can set restart.policy explicitly
            # alongside a max_worker_restarts of 0.
            self._restart_policy = "failed_only"
            if self._max_restarts <= 0:
                self._max_restarts = 2
            log.warning(
                "serve job: restart.policy never -> failed_only "
                "(max_worker_restarts %d): a lost decode host relaunches "
                "alone while survivors keep serving", self._max_restarts,
            )
        # elastic training (tony_tpu/elastic/, docs/ELASTIC.md): on a lost
        # member the AM declares a new cluster generation instead of
        # cold-restarting the gang; auto-enabled for framework "elastic"
        self._elastic_enabled = (
            config.get_bool(Keys.ELASTIC_ENABLED, False)
            or config.get_str(Keys.APPLICATION_FRAMEWORK) == "elastic"
        )
        self._elastic_min_members = config.get_int(Keys.ELASTIC_MIN_MEMBERS, 1)
        self._elastic_grow_back = config.get_bool(Keys.ELASTIC_GROW_BACK, True)
        self._elastic_grow_retry_s = config.get_float(
            Keys.ELASTIC_GROW_RETRY_S, 2.0
        )
        # seats currently out of the membership: task_id -> member rank.
        # Detached tasks sit PENDING but UNSCHEDULED until grow-back
        # re-leases their capacity; _elastic_relaunching tracks the ones
        # back in flight (their registration declares the grow generation)
        self._elastic_detached: dict[str, int] = {}
        self._elastic_relaunching: set[str] = set()
        self._elastic_last_grow = 0.0
        self._latest_metrics: dict[str, dict[str, float]] = {}
        self._last_metrics_event: dict[str, float] = {}
        self._step_metric_seen: set[str] = set()
        self._metrics_event_min_interval_s = 30.0
        # per-task series scraped off the PushMetrics heartbeat-path RPC:
        # a bounded recent window per task plus the wall time it arrived,
        # rolled up into <app_dir>/series/am_rollup.json (throttled) —
        # the fleet view `tony top` and the portal /api/series serve even
        # when workers run on hosts whose journals the AM cannot read
        self._series_history: dict[str, Any] = {}
        self._series_push_ts: dict[str, float] = {}
        self._last_series_rollup = 0.0
        self._series_rollup_min_interval_s = 5.0
        # guards the two dicts above against concurrent PushMetrics
        # handler threads; held for dict ops only, NEVER across file I/O
        self._series_lock = threading.Lock()
        self._scheduler_mode = config.get_str(Keys.SCHEDULER_MODE, "GANG").upper()
        # serializes am.state.json writes (scheduler + supervise threads)
        self._am_state_write_lock = threading.Lock()
        # gloo rendezvous store for horovod jobs (the reference's AM-side
        # HorovodDriver, SURVEY.md section 3.4); started in run()
        self._rendezvous = None
        # shared-RM lease keeper (started in run()): renews from its own
        # thread so a hung store can never stall supervision
        self._lease_keeper_stop = threading.Event()
        self._lease_ok_t = time.monotonic()
        self._lease_ttl = 0.0
        # root span for the whole AM attempt (trace spine): opened in run(),
        # its id rides into every container env so executor/user spans nest
        # under it on the merged timeline
        self._run_span = trace.NOOP_SPAN

    # --- executor launch ----------------------------------------------------

    def _make_request(self, spec: TaskTypeSpec, index: int) -> ContainerRequest:
        task = self.session.task(spec.name, index)
        attempt = task.attempt if task else 0
        python = self.config.get_str(Keys.TASK_EXECUTOR_PYTHON) or sys.executable
        env = {
            "TONY_APP_ID": self.app_id,
            "TONY_APP_DIR": self.app_dir,
            "TONY_JOB_NAME": spec.name,
            "TONY_TASK_INDEX": str(index),
            "TONY_ATTEMPT": str(attempt),
            "TONY_AM_ADDR": f"{self.backend.am_advertise_host()}:{self.port}",
            "TONY_CONF_PATH": os.path.join(self.app_dir, "config.json"),
            **spec.env,
        }
        if self._rendezvous is not None:
            env["TONY_HOROVOD_RENDEZVOUS_PORT"] = str(self._rendezvous.port)
        tracer = trace.active_tracer()
        if tracer is not None:
            # trace context AM -> executor: same trace id, journals in the
            # shared app-dir trace/, executor roots under the AM run span
            env[trace.ENV_DIR] = os.path.join(self.app_dir, "trace")
            env[trace.ENV_TRACE_ID] = tracer.trace_id
            env[trace.ENV_SAMPLE] = str(tracer.sample_steps)
            env[trace.ENV_RING] = str(tracer.ring_size)
            env[trace.ENV_JOURNAL_MB] = str(tracer.max_journal_mb)
            env[trace.ENV_PROC] = f"{spec.name}_{index}_exec_a{attempt}"
            env[trace.ENV_PARENT] = self._run_span.sid
        # HBM-observatory contract (obs/hbm.py): the device-owning user
        # process arms itself from these; the AM holds no device
        env[hbm.ENV_ENABLED] = (
            "1" if self.config.get_bool(Keys.OBS_HBM_ENABLED, True) else "0"
        )
        env[hbm.ENV_SAMPLE] = str(
            self.config.get_int(Keys.OBS_HBM_SAMPLE_STEPS, 16)
        )
        env[hbm.ENV_HISTORY] = str(
            self.config.get_int(Keys.OBS_HBM_HISTORY, 512)
        )
        # numerics-sentinel contract (obs/health.py): armed in the
        # device-owning user process; the AM only exports the knobs
        env[health.ENV_ENABLED] = (
            "1" if self.config.get_bool(Keys.OBS_HEALTH_ENABLED, True) else "0"
        )
        env[health.ENV_SAMPLE] = str(
            self.config.get_int(Keys.OBS_HEALTH_SAMPLE_STEPS, 16)
        )
        env[health.ENV_WINDOW] = str(
            self.config.get_int(Keys.OBS_HEALTH_WINDOW, 64)
        )
        # live-series contract (obs/series.py): the worker journals
        # stride-scraped points under <app_dir>/series/; the AM also
        # aggregates the metrics pushes it already receives (PushMetrics)
        # into the app-level rollup `tony top` and /api/series read
        env[series.ENV_ENABLED] = (
            "1" if self.config.get_bool(Keys.OBS_SERIES_ENABLED, True) else "0"
        )
        env[series.ENV_SAMPLE] = str(
            self.config.get_int(Keys.OBS_SERIES_SAMPLE_STEPS, 16)
        )
        env[series.ENV_JOURNAL_MB] = str(
            self.config.get_int(Keys.OBS_SERIES_JOURNAL_MB, 16)
        )
        # SLO contract (obs/slo.py): the resolved slo.* group as one JSON
        # blob; workers arm a burn-rate engine only when targets are active
        env[slo.ENV_SLO] = slo.SloConfig.from_config(self.config).to_json()
        # coordinated-profiling contract (obs/profile.py): device-owning
        # processes watch <app_dir>/profile/request.json for the windows
        # the StartProfile RPC broadcasts; the AM only exports the knobs
        env[profile_mod.ENV_ENABLED] = (
            "1" if self.config.get_bool(Keys.OBS_PROFILE_ENABLED, True) else "0"
        )
        env[profile_mod.ENV_POLL] = str(
            self.config.get_float(Keys.OBS_PROFILE_POLL_S, 0.5)
        )
        env[profile_mod.ENV_MAX_STEPS] = str(
            self.config.get_int(Keys.OBS_PROFILE_MAX_STEPS, 64)
        )
        log_path = os.path.join(
            self.app_dir, "logs", f"{spec.name}_{index}_attempt{attempt}.log"
        )
        return ContainerRequest(
            task_type=spec.name,
            task_index=index,
            resource=Resource(spec.memory_mb, spec.cpus, spec.tpu_chips),
            argv=[python, "-m", "tony_tpu.executor"],
            env=env,
            log_path=log_path,
            node_label=spec.node_label,
        )

    def _on_allocated(self, job_name: str, index: int, container: Container, log_path: str) -> None:
        t = self.session.task(job_name, index)
        if t is not None:
            t.log_path = log_path
            t.container_pid = container.pid
        self.events.emit(
            EventType.TASK_STARTED,
            task=f"{job_name}:{index}",
            container=container.container_id,
            attempt=t.attempt if t else 0,
        )
        self._write_am_state()

    # --- RPC handlers (executor-facing) -------------------------------------

    def RegisterWorkerSpec(self, request, context):  # noqa: N802
        ok = self.session.register(
            request.job_name, request.index, request.host, request.port, request.attempt
        )
        if ok:
            self.events.emit(
                EventType.TASK_REGISTERED,
                task=f"{request.job_name}:{request.index}",
                address=f"{request.host}:{request.port}",
                attempt=request.attempt,
            )
            log.info(
                "registered %s:%d at %s:%d (attempt %d)",
                request.job_name, request.index, request.host, request.port, request.attempt,
            )
            trace.instant(
                "am.task_registered",
                task=f"{request.job_name}:{request.index}", attempt=request.attempt,
            )
        return pb.RegisterWorkerSpecResponse(
            accepted=ok, message="" if ok else "unknown task or stale attempt"
        )

    def GetClusterSpec(self, request, context):  # noqa: N802
        # A poll proves liveness — but only for the CURRENT attempt: a ghost
        # from before a gang restart must neither refresh the replacement's
        # heartbeat nor receive the new generation's spec.
        if not self.session.touch(request.job_name, request.index, request.attempt):
            return pb.GetClusterSpecResponse(ready=False)
        task = self.session.task(request.job_name, request.index)
        if self._scheduler_mode == "FCFS":
            ready = self._fcfs_ready(request.job_name)
        else:
            ready = self.session.all_registered()
            if not ready and self._elastic_enabled:
                # a grown-back member polls while OTHER detached seats may
                # still be empty: the barrier counts live seats only —
                # detached tasks are out of the membership by declaration,
                # not stragglers the gang should wait for
                ready = self._elastic_ready()
        if not ready:
            return pb.GetClusterSpecResponse(ready=False)
        self.session.mark_running(request.job_name, request.index)
        table = self.session.rank_table()
        coord = self.session.coordinator_task()
        return pb.GetClusterSpecResponse(
            ready=True,
            spec_json=self.session.cluster_spec_json(),
            coordinator_address=coord.address if coord else "",
            process_id=table.get(task.task_id, -1),
            num_processes=len(table),
            generation=self.session.generation,
        )

    def _fcfs_ready(self, job_name: str) -> bool:
        """FCFS: a task may proceed once its own type + dependency chain are up."""
        spec = self.specs[job_name]
        names = {job_name}
        dep = spec.depends_on
        while dep:
            names.add(dep)
            dep = self.specs[dep].depends_on if dep in self.specs else ""
        return all(
            t.state not in (TaskState.PENDING, TaskState.ALLOCATED)
            for n in names
            for t in self.session.tasks_of_type(n)
        )

    # --- elastic membership (tony_tpu/elastic/protocol.py) -------------------

    def _elastic_ready(self) -> bool:
        with self.session.lock:
            return all(
                t.state not in (TaskState.PENDING, TaskState.ALLOCATED)
                or t.task_id in self._elastic_detached
                for t in self.session.tasks.values()
            )

    def _elastic_members_live(self) -> list[int]:
        """Current membership: every tracked seat not detached."""
        ranks = self.session.rank_table()
        return sorted(
            rank for tid, rank in ranks.items()
            if tid not in self._elastic_detached
        )

    def _elastic_declare(self, boundary: str, *, dead: list[int] = (),
                         added: list[int] = (), reason: str = "",
                         freed_host: str = "", granted_host: str = "") -> None:
        """Declare a new cluster generation: bump the session generation
        (the same monotonic counter gang restarts use — the
        generation-monotonic invariant covers both) and broadcast the
        membership over the shared app dir; survivors fence on it."""
        from tony_tpu.elastic.protocol import GenerationRecord, write_generation

        with self.session.lock:
            if boundary != "start":
                self.session.generation += 1
            generation = self.session.generation
        members = self._elastic_members_live()
        rec = GenerationRecord(
            generation=generation, members=tuple(members), boundary=boundary,
            dead=tuple(dead), added=tuple(added), reason=reason,
            freed_host=freed_host, granted_host=granted_host,
        )
        write_generation(self.app_dir, rec)
        event = (
            EventType.ELASTIC_GROW if boundary == "grow"
            else EventType.ELASTIC_SHRINK
        )
        if boundary != "start":
            self.events.emit(
                event, generation=generation, members=members,
                dead=list(dead), added=list(added), reason=reason,
                freed_host=freed_host, granted_host=granted_host,
            )
        members_str = ",".join(str(m) for m in members)
        trace.instant(
            f"am.elastic_{boundary}", generation=generation,
            members=members_str,
        )
        log.warning(
            "elastic generation %d (%s): members=%s dead=%s added=%s",
            generation, boundary, members, list(dead), list(added),
        )

    def _elastic_detach(self, failed: list) -> list:
        """Handle lost members elastically; returns the tasks the normal
        failure policy must still judge (empty when fully absorbed).

        Falls back — whole, never partially — when the coordinator
        (member 0, the trainer) is among the dead or the survivors would
        drop below elastic.min_members: those cases need the cold
        restart.policy path (checkpoint resume), not a reshard.
        """
        ranks = self.session.rank_table()
        relaunch_failures = [
            t for t in failed if t.task_id in self._elastic_relaunching
        ]
        fresh = [t for t in failed if t.task_id not in self._elastic_relaunching]
        # a relaunch that died before its grow generation was declared
        # goes quietly back to detached — membership never included it,
        # and its grow lease is RETURNED (the next attempt grows again;
        # without the return a crash-looping relaunch leaks one lease
        # per retry until the store has nothing left to grant)
        for t in relaunch_failures:
            self._elastic_relaunching.discard(t.task_id)
            self._elastic_return_lease(t)
            self._requeue_detached(t)
            log.warning(
                "elastic relaunch of %s failed before rejoining; seat "
                "stays detached", t.task_id,
            )
        if not fresh:
            return []
        victims = [t for t in fresh if t.task_id in ranks]
        if any(ranks[t.task_id] == 0 for t in victims):
            return failed  # trainer lost: cold path
        live_after = [
            r for tid, r in ranks.items()
            if tid not in self._elastic_detached
            and tid not in {t.task_id for t in victims}
        ]
        if len(live_after) < max(self._elastic_min_members, 1):
            log.warning(
                "elastic shrink would leave %d member(s) < min_members %d; "
                "falling back to restart policy",
                len(live_after), self._elastic_min_members,
            )
            return failed
        dead_members = sorted(ranks[t.task_id] for t in victims)
        freed_hosts = []
        for t in victims:
            dead_host = t.host  # cleared by the requeue below
            self._elastic_detached[t.task_id] = ranks[t.task_id]
            self._requeue_detached(t)
            shrink = getattr(self.backend, "shrink_job_lease", None)
            if shrink is not None:
                spec = self.specs[t.job_name]
                freed = shrink(
                    Resource(spec.memory_mb, spec.cpus, spec.tpu_chips),
                    host=dead_host,
                )
                if freed:
                    freed_hosts.append(freed)
        self._elastic_declare(
            "shrink", dead=dead_members,
            reason="; ".join(sorted(t.task_id for t in victims)),
            freed_host=",".join(freed_hosts),
        )
        self._write_am_state()
        return [t for t in fresh if t not in victims]

    def _elastic_return_lease(self, t) -> None:
        """Hand back the lease a failed relaunch was granted (grow-back
        took one per attempt; the seat's next attempt grows afresh)."""
        shrink = getattr(self.backend, "shrink_job_lease", None)
        if shrink is None:
            return
        spec = self.specs[t.job_name]
        shrink(
            Resource(spec.memory_mb, spec.cpus, spec.tpu_chips), host=t.host
        )

    def _requeue_detached(self, t) -> None:
        """Reset a detached seat to PENDING-but-unscheduled: the attempt
        bump is the heartbeat fence (a surviving ghost of this member gets
        ABORT on its next beat), and the container release reaps the
        process group. Grow-back re-schedules it later."""
        with self.session.lock:
            cid = t.container_id
            t.state = TaskState.PENDING
            t.host, t.port = "", 0
            t.container_id = ""
            t.container_pid = 0
            t.exit_code = None
            t.attempt += 1
            t.last_heartbeat = 0.0
        if cid:
            self.backend.release(cid)

    def _elastic_tick(self) -> None:
        """Per-supervision-tick elastic upkeep: declare grow generations
        for relaunched members that registered, and retry capacity for
        detached seats (throttled)."""
        if not self._elastic_enabled:
            return
        # relaunched member back at the barrier -> it rejoins the
        # membership at the next generation boundary
        for tid in sorted(self._elastic_relaunching):
            t = self.session.tasks.get(tid)
            if t is None or t.state in (TaskState.PENDING, TaskState.ALLOCATED):
                continue
            if t.state in TERMINAL:
                # the relaunch died (or exited) before rejoining: the seat
                # goes back to detached — with its grow lease returned —
                # and the next tick tries again; it must not strand
                # half-promoted or leak a lease per retry
                self._elastic_relaunching.discard(tid)
                self._elastic_return_lease(t)
                self._requeue_detached(t)
                continue
            member = self._elastic_detached.pop(tid, None)
            self._elastic_relaunching.discard(tid)
            if member is None:
                continue
            self._elastic_declare(
                "grow", added=[member], reason=tid, granted_host=t.host,
            )
            self._write_am_state()
        # grow-back: re-lease capacity for seats still out
        if not self._elastic_grow_back:
            return
        waiting = [
            tid for tid in sorted(self._elastic_detached)
            if tid not in self._elastic_relaunching
        ]
        if not waiting:
            return
        now = time.monotonic()
        if now - self._elastic_last_grow < self._elastic_grow_retry_s:
            return
        self._elastic_last_grow = now
        grow = getattr(self.backend, "grow_job_lease", None)
        to_schedule = []
        for tid in waiting:
            t = self.session.tasks.get(tid)
            if t is None:
                continue
            if grow is not None:
                spec = self.specs[t.job_name]
                granted = grow(Resource(spec.memory_mb, spec.cpus, spec.tpu_chips))
                if granted is None:
                    log.info(
                        "elastic grow-back: no capacity for %s yet", tid
                    )
                    continue
            to_schedule.append(tid)
        if not to_schedule:
            return
        tasks_str = ",".join(to_schedule)
        log.warning("elastic grow-back: relaunching %s", tasks_str)
        trace.instant("am.elastic_relaunch", tasks=tasks_str)
        for tid in to_schedule:
            self._elastic_relaunch(tid)

    def _elastic_relaunch(self, tid: str) -> None:
        """Directly allocate ONE detached seat's container (the scheduler's
        schedule_all blocks until NO task is pending, which would wedge on
        sibling seats still waiting for capacity). Dependencies are moot —
        the gang is already running."""
        t = self.session.tasks.get(tid)
        if t is None:
            return
        spec = self.specs[t.job_name]
        req = self._make_request(spec, t.index)
        try:
            container = self.backend.allocate(req)
        except Exception:
            log.warning("elastic relaunch allocate failed for %s", tid,
                        exc_info=True)
            # hand the freshly-grown lease back; the next tick retries
            shrink = getattr(self.backend, "shrink_job_lease", None)
            if shrink is not None:
                shrink(Resource(spec.memory_mb, spec.cpus, spec.tpu_chips))
            return
        with self.session.lock:
            t.state = TaskState.ALLOCATED
            t.container_id = container.container_id
            t.host = container.host
            t.started_at = time.time()
        self._elastic_relaunching.add(tid)
        self._on_allocated(t.job_name, t.index, container, req.log_path)

    def Heartbeat(self, request, context):  # noqa: N802
        alive = self.session.touch(request.job_name, request.index, request.attempt)
        if not alive or self._killed.is_set():
            return pb.HeartbeatResponse(action=pb.HeartbeatResponse.ABORT)
        return pb.HeartbeatResponse(action=pb.HeartbeatResponse.NONE)

    def RegisterExecutionResult(self, request, context):  # noqa: N802
        self._notifications.put(
            ("result", (request.job_name, request.index, request.exit_code, request.attempt))
        )
        return pb.RegisterExecutionResultResponse(acknowledged=True)

    def RegisterTensorBoardUrl(self, request, context):  # noqa: N802
        self.session.tensorboard_url = request.url
        self.events.emit(EventType.METADATA, tensorboard_url=request.url)
        return pb.Empty()

    def PushMetrics(self, request, context):  # noqa: N802
        tid = f"{request.job_name}:{request.index}"
        samples = {s.name: s.value for s in request.samples}
        self._latest_metrics[tid] = samples
        self._record_series(tid, samples)
        # feed the history pipeline so the portal can chart them (the
        # reference embeds utilization in its avro events the same way).
        # samples nest under their own key (names are user-chosen and must
        # not collide with the event envelope), and emission is throttled
        # per task so long jobs don't grow the history file without bound.
        # a task's FIRST step-carrying sample bypasses the throttle: it is
        # the submit->first-step latency timestamp (north-star metric), and
        # a monitor rss sample arriving earlier must not eat its history
        # slot. Later step pushes obey the throttle — the unbounded-history
        # guard stays intact for long jobs.
        now = time.monotonic()
        first_step = "step" in samples and tid not in self._step_metric_seen
        if first_step:
            self._step_metric_seen.add(tid)
        if first_step or (
            now - self._last_metrics_event.get(tid, 0.0)
            >= self._metrics_event_min_interval_s
        ):
            self._last_metrics_event[tid] = now
            self.events.emit(EventType.METRICS, task=tid, samples=samples)
        return pb.Empty()

    def _record_series(self, tid: str, samples: dict[str, float]) -> None:
        """Fleet series aggregation off the existing metrics RPC: keep a
        bounded recent window per task and write the app-level rollup
        (throttled; best-effort — a full disk costs the rollup file, not
        the RPC). Runs on the RPC handler thread; the dict/list ops are
        cheap and the file write is throttled to one per interval."""
        ts = time.time()
        with self._series_lock:
            window = self._series_history.setdefault(tid, [])
            window.append({"ts": ts, **samples})
            if len(window) > 360:
                del window[: len(window) - 360]
            self._series_push_ts[tid] = ts
            now = time.monotonic()
            if (now - self._last_series_rollup
                    < self._series_rollup_min_interval_s):
                return
            self._last_series_rollup = now
        self._write_series_rollup()

    def _write_series_rollup(self) -> None:
        """Atomic ``<app_dir>/series/am_rollup.json``: per-task point
        windows with explicit staleness (age since the last push) — a
        dead host's frozen numbers must read as stale, never current.
        The payload snapshots under the series lock (pure dict copies);
        the file write happens outside it."""
        now = time.time()
        with self._series_lock:
            payload = {
                "ts": now,
                "tasks": {
                    tid: {
                        "last_ts": self._series_push_ts.get(tid, 0.0),
                        "age_s": round(
                            max(now - self._series_push_ts.get(tid, 0.0), 0.0),
                            1,
                        ),
                        "points": list(window)[-120:],
                    }
                    for tid, window in sorted(self._series_history.items())
                },
            }
        out_dir = os.path.join(self.app_dir, "series")
        path = os.path.join(out_dir, "am_rollup.json")
        # two RPC handler threads can race past the throttle: a unique tmp
        # name + atomic replace keeps the visible file whole without
        # holding any lock across file I/O (GL004 discipline)
        tmp = f"{path}.tmp{threading.get_native_id()}"
        try:
            os.makedirs(out_dir, exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:
            log.debug("could not write series rollup", exc_info=True)

    # --- RPC handlers (client-facing) ----------------------------------------

    def GetTaskInfos(self, request, context):  # noqa: N802
        return pb.GetTaskInfosResponse(tasks=self._task_infos())

    def GetApplicationStatus(self, request, context):  # noqa: N802
        state = self.session.state
        code = 0
        if state in (JobState.SUCCEEDED, JobState.FAILED, JobState.KILLED):
            code = self._client_exit_code()
        return pb.GetApplicationStatusResponse(
            state=state.value,
            exit_code=code,
            diagnostics=self.session.diagnostics,
            tensorboard_url=self.session.tensorboard_url,
            tasks=self._task_infos(),
        )

    def StartProfile(self, request, context):  # noqa: N802
        """Broadcast a bounded profile window to every process of the job
        (`tony profile <app_id>`; docs/OBS.md "Step anatomy"). The channel
        is the shared app dir: the request file lands atomically and each
        armed ProfileController picks it up on its poll — no per-executor
        RPC fan-out, and a worker mid-relaunch still sees the request when
        it arms (requests expire, so a stale one can never re-fire)."""
        steps = max(int(request.steps), 0)
        duration_s = max(float(request.duration_s), 0.0)
        if steps <= 0 and duration_s <= 0:
            return pb.StartProfileResponse(
                accepted=False, message="need steps > 0 or duration_s > 0"
            )
        max_steps = self.config.get_int(Keys.OBS_PROFILE_MAX_STEPS, 64)
        message = ""
        if steps > max_steps:
            message = f"steps clamped {steps} -> {max_steps} (obs.profile.max_steps)"
            steps = max_steps
        req = profile_mod.write_request(
            self.app_dir, steps=steps, duration_s=duration_s
        )
        self.events.emit(
            EventType.METADATA,
            profile_id=req.id, profile_steps=steps,
            profile_duration_s=duration_s,
        )
        trace.instant(
            "am.profile_requested", id=req.id, steps=steps,
            duration_s=duration_s,
        )
        log.info("profile %s broadcast (steps=%d duration_s=%.1f)",
                 req.id, steps, duration_s)
        return pb.StartProfileResponse(
            accepted=True, profile_id=req.id, message=message
        )

    def StopApplication(self, request, context):  # noqa: N802
        log.info("stop requested: %s", request.reason)
        self.session.diagnostics = request.reason or "stopped by client"
        self._killed.set()
        # unblock a schedule_all in flight (e.g. mid gang-restart) so the
        # stop is honoured now, not after allocation completes
        self.scheduler.stop()
        self._notifications.put(("stop", None))
        return pb.Empty()

    def _task_infos(self) -> list[pb.TaskInfo]:
        with self.session.lock:
            return [
                pb.TaskInfo(
                    job_name=t.job_name,
                    index=t.index,
                    host=t.host,
                    port=t.port,
                    state=t.state.value,
                    exit_code=t.exit_code or 0,
                    attempt=t.attempt,
                    log_path=t.log_path,
                )
                for t in self.session.tasks.values()
            ]

    # --- AM fault tolerance (am.retry_count) ---------------------------------

    def _am_state_path(self) -> str:
        return os.path.join(self.app_dir, "am.state.json")

    def _write_am_state(self) -> None:
        """Journal the minimum a successor AM attempt needs: which container
        process groups exist (to reap orphans) and the restart generation
        (so events/metrics stay monotonic across AM attempts)."""
        # refresh pids that were unknown at allocate time (a remote pid can
        # arrive after launch) so the journal never undercounts. The backend
        # query can block (ssh transport on remote backends), so collect the
        # stale tasks under the lock, query OUTSIDE it, write back under it —
        # an RPC handler must never wait on a remote host to touch the
        # session table (GL004 lock-discipline).
        with self.session.lock:
            stale = [
                (t.task_id, t.container_id)
                for t in self.session.tasks.values()
                if t.container_id and not t.container_pid and t.state not in TERMINAL
            ]
        pids = {
            task_id: (cid, self.backend.container_pid(cid))
            for task_id, cid in stale
        }
        with self.session.lock:
            for task_id, (cid, pid) in pids.items():
                t = self.session.tasks.get(task_id)
                # the task may have been restarted (new container) during
                # the unlocked backend query: only record the pid if it
                # still belongs to the container it was queried for
                if (t is not None and not t.container_pid
                        and t.container_id == cid and t.state not in TERMINAL):
                    t.container_pid = pid
            snap = {
                "am_attempt": self.am_attempt,
                "generation": self.session.generation,
                "containers": {
                    t.task_id: {
                        "pid": t.container_pid,
                        "host": t.host,
                        "attempt": t.attempt,
                    }
                    for t in self.session.tasks.values()
                    if t.container_pid
                },
            }
        path = self._am_state_path()
        # the write lock EXISTS to serialize this journal write between the
        # scheduler and supervise threads; holding it across the local file
        # I/O is its whole job, and no hot path ever waits on it
        with self._am_state_write_lock:
            with open(path + ".tmp", "w") as f:  # graft-lint: disable=GL004
                json.dump(snap, f)  # graft-lint: disable=GL004
            os.replace(path + ".tmp", path)  # graft-lint: disable=GL004

    def _recover_from_previous_attempt(self) -> None:
        """Attempt N+1 startup: reap the predecessor's orphaned container
        process groups, then carry the restart generation forward so the whole
        gang relaunches cleanly (fixed-topology barrier-restart semantics —
        the relaunched workers resume from the last checkpoint via the
        checkpoint.dir glue)."""
        try:
            with open(self._am_state_path()) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            return
        for tid, info in snap.get("containers", {}).items():
            pid = int(info.get("pid", 0))
            if pid <= 0:
                continue
            # route through the backend: for remote backends the pid is a
            # process group on another host, not a local one
            self.backend.kill_orphan(str(info.get("host", "")), pid)
            log.warning("reaped orphan container pg %d (%s)", pid, tid)
        with self.session.lock:
            self.session.generation = int(snap.get("generation", 0)) + 1
            # tasks start PENDING at attempt 0 in the fresh table; bump each
            # to one past the journalled attempt so any orphan that survived
            # the kill and still heartbeats is told to ABORT.
            for tid, info in snap.get("containers", {}).items():
                t = self.session.tasks.get(tid)
                if t is not None:
                    t.attempt = int(info.get("attempt", 0)) + 1
        self.events.emit(
            EventType.METADATA,
            am_attempt=self.am_attempt,
            recovered_generation=self.session.generation,
        )
        log.warning(
            "AM attempt %d recovered: generation -> %d",
            self.am_attempt, self.session.generation,
        )

    # --- backend callback ----------------------------------------------------

    def _on_container_completed(self, container: Container, code: int) -> None:
        self._notifications.put(
            ("container", (container.request.task_type, container.request.task_index,
                           container.container_id, code,
                           container.exit_authoritative))
        )

    # --- supervision loop -----------------------------------------------------

    def run(self) -> int:
        """Run the job to completion; returns the client exit code."""
        os.makedirs(os.path.join(self.app_dir, "logs"), exist_ok=True)
        self._run_span = trace.span("am.run", attempt=self.am_attempt)
        token = None
        if self.config.get_bool(Keys.APPLICATION_SECURITY_ENABLED, False):
            from tony_tpu.rpc.auth import read_token

            token = read_token(self.app_dir)
            if not token:
                raise RuntimeError(
                    "application.security.enabled but no app.token staged"
                )
        self._server, self.port = serve(
            self,
            port=self.config.get_int(Keys.AM_RPC_PORT, 0),
            max_workers=max(16, self.config.get_int(Keys.AM_CPUS, 1) * 8),
            token=token,
        )
        # The client discovers the AM address from this file (the YARN
        # application-report analogue).
        addr_path = os.path.join(self.app_dir, "am.addr")
        with open(addr_path + ".tmp", "w") as f:
            f.write(f"{self.backend.am_advertise_host()}:{self.port}")
        os.replace(addr_path + ".tmp", addr_path)
        self.events.emit(
            EventType.APPLICATION_INITED,
            specs={n: s.to_dict() for n, s in self.specs.items()},
            framework=self.config.get_str(Keys.APPLICATION_FRAMEWORK),
            queue=self.config.get_str(Keys.APPLICATION_QUEUE, "default"),
            tags=self.config.get_list(Keys.APPLICATION_TAGS),
        )
        if self.config.get_str(Keys.APPLICATION_FRAMEWORK) == "horovod":
            from tony_tpu.runtime.horovod_driver import RendezvousServer

            self._rendezvous = RendezvousServer().start()
            log.info("horovod gloo rendezvous serving on :%d", self._rendezvous.port)
        self.backend.set_completion_callback(self._on_container_completed)
        self.backend.start()
        self._start_lease_keeper()
        # The AM's own footprint consumes inventory, like a YARN AM container.
        self.backend.reserve(
            Resource(
                self.config.get_int(Keys.AM_MEMORY_MB, 2048),
                self.config.get_int(Keys.AM_CPUS, 1),
                0,
            )
        )
        self.session.state = JobState.RUNNING
        deadline = None
        timeout_s = self.config.get_int(Keys.APPLICATION_TIMEOUT_S, 0)
        if timeout_s > 0:
            deadline = time.monotonic() + timeout_s
        try:
            if self.am_attempt > 0:
                self._recover_from_previous_attempt()
            with trace.span("am.schedule", parent=self._run_span.sid or None,
                            generation=self.session.generation):
                self.scheduler.schedule_all(self.specs)
            if self._elastic_enabled:
                # baseline membership declaration: the record survivors'
                # journals and the post-mortem measure boundaries against
                self._elastic_declare("start")
            self._supervise(deadline)
        except Exception as e:
            log.exception("AM failed")
            self.session.state = JobState.FAILED
            self.session.diagnostics = f"{type(e).__name__}: {e}"
        finally:
            self._teardown()
        code = self._client_exit_code()
        self._write_status(code)
        self._run_span.end(state=self.session.state.value, exit_code=code)
        trace.flush()
        return code

    def _client_exit_code(self) -> int:
        """Exit code for the client, consistent between the status RPC and
        the final status file: task failures propagate their code; jobs
        failed for non-task reasons (timeout, scheduler error) report 1."""
        _, code = self.session.final_status()
        if self.session.state == JobState.KILLED:
            return 143
        if self.session.state == JobState.FAILED and code == 0:
            return 1
        return code

    def _start_lease_keeper(self) -> None:
        """Renew shared-RM lease TTLs from a DEDICATED thread, so that a
        hung store (a hard-mounted shared FS that partitions blocks
        forever in open()/flock, raising nothing) can never stall
        container supervision. The keeper posts a ``fence`` notification
        when renewal reports the leases lost; _supervise additionally
        fences on renewal STALENESS at half the TTL — the keeper being
        silently stuck is exactly the hang case the thread exists for,
        and fencing at ttl/2 keeps the owner ahead of survivors reaping
        at renewed_at + ttl on their own clocks."""
        renew = getattr(self.backend, "renew_leases", None)
        if renew is None:
            return
        self._lease_ttl = getattr(self.backend, "lease_ttl_s", lambda: 0.0)()
        if 0 < self._lease_ttl < 4 * self._heartbeat_interval_s:
            # make_backend clamps config-built stores; this catches
            # directly-constructed backends handed a mismatched pair
            log.warning(
                "lease TTL %.1fs is below 4x the heartbeat interval "
                "(%.2fs): renewal cadence is max(heartbeat, ttl/4), so a "
                "healthy cross-host owner can lapse between renewals and "
                "self-fence",
                self._lease_ttl, self._heartbeat_interval_s,
            )
        self._lease_ok_t = time.monotonic()

        def keeper():
            while not self._lease_keeper_stop.wait(self._heartbeat_interval_s):
                try:
                    ok = renew()
                except Exception:
                    log.exception("lease renewal raised (keeper carries on)")
                    continue
                if ok:
                    self._lease_ok_t = time.monotonic()
                else:
                    self._notifications.put(("fence", None))
                    return

        threading.Thread(target=keeper, daemon=True, name="lease-keeper").start()

    def _supervise(self, deadline: float | None) -> None:
        while True:
            # chaos seam: kill_am fires here (mid-run AM attempt death);
            # the per-point count makes "at supervision tick N" exact
            chaos_hook("am.tick", attempt=self.am_attempt)
            if self._killed.is_set():
                self.session.state = JobState.KILLED
                return
            if deadline is not None and time.monotonic() > deadline:
                self.session.diagnostics = "application timeout"
                self.session.state = JobState.FAILED
                return
            try:
                kind, payload = self._notifications.get(timeout=self._heartbeat_interval_s)
            except queue.Empty:
                kind, payload = "", None
            if kind == "stop":
                self.session.state = JobState.KILLED
                return
            if kind == "result":
                job_name, index, exit_code, attempt = payload
                task = self.session.task(job_name, index)
                if task is not None and attempt == task.attempt:
                    # executor-reported: its process group is exiting now
                    self._finish_task(job_name, index, exit_code, pid_dead=True)
            elif kind == "container":
                job_name, index, cid, code, authoritative = payload
                task = self.session.task(job_name, index)
                # Only meaningful if this is still the task's current
                # container and no result was reported (executor crash).
                if task is not None and task.container_id == cid and task.state not in TERMINAL:
                    self._finish_task(job_name, index, code, pid_dead=authoritative)
            self._check_heartbeats()
            # elastic upkeep: declare grow generations for members back at
            # the barrier, retry capacity for detached seats (throttled)
            self._elastic_tick()
            # Fence when the lease keeper says our leases are GONE, or
            # when it has been silently stuck (hung store) past the TTL:
            # either way survivors may re-lease the chips this job is
            # still running on — stop before that double-books.
            if kind == "fence" or (
                self._lease_ttl
                and time.monotonic() - self._lease_ok_t > self._lease_ttl / 2
            ):
                self.session.diagnostics = (
                    "shared-RM leases lost (TTL-reaped, operator release, "
                    "or store unreachable past the TTL); stopping to avoid "
                    "double-booking"
                )
                # the store is gone or unreachable: teardown must not call
                # release_app against it — the release would block in the
                # same flock the keeper is already hung in and the client
                # would never see this FAILED status (ADVICE round 5)
                fence = getattr(self.backend, "fence_leases", None)
                if fence is not None:
                    fence()
                self.session.state = JobState.FAILED
                return
            if self._apply_failure_policy():
                return
            if self.session.job_done():
                state, _ = self.session.final_status()
                self.session.state = state
                return

    def _finish_task(
        self, job_name: str, index: int, exit_code: int, *, pid_dead: bool = True
    ) -> None:
        self.session.on_task_completed(job_name, index, exit_code)
        t = self.session.task(job_name, index)
        if t is not None and pid_dead:
            # the container process group is provably gone; drop its pid from
            # the journal so a successor AM attempt never kill_orphan()s a
            # recycled pid. When the exit is NOT authoritative (an ssh
            # channel died, code 255), the pid stays journalled: the remote
            # group may still be alive and must remain reapable.
            t.container_pid = 0
        elif t is not None and t.container_pid:
            # best-effort reap NOW, before any restart relaunches on this
            # host — release() can't reach a group whose local channel
            # already exited, and waiting for a future AM attempt would let
            # a live orphan fight the replacement for the TPU devices
            log.warning(
                "non-authoritative exit for %s:%d; killing possible orphan "
                "pg %d on %s", job_name, index, t.container_pid, t.host,
            )
            try:
                self.backend.kill_orphan(t.host, t.container_pid)
            except Exception:
                log.exception("orphan kill failed (pid stays journalled)")
        self.events.emit(
            EventType.TASK_FINISHED,
            task=f"{job_name}:{index}",
            exit_code=exit_code,
            state=t.state.value if t else "",
        )
        self._write_am_state()
        trace.instant(
            "am.task_finished", task=f"{job_name}:{index}", exit_code=exit_code,
        )
        log.info("task %s:%d finished code=%d", job_name, index, exit_code)

    def _check_heartbeats(self) -> None:
        if self._max_missed <= 0:
            return
        cutoff = time.monotonic() - self._heartbeat_interval_s * self._max_missed
        with self.session.lock:
            stale = [
                t
                for t in self.session.tasks.values()
                if t.state in (TaskState.REGISTERED, TaskState.RUNNING)
                and t.last_heartbeat > 0
                and t.last_heartbeat < cutoff
            ]
        for t in stale:
            log.warning("task %s lost (missed heartbeats)", t.task_id)
            trace.instant("am.task_lost", task=t.task_id)
            self.session.on_task_lost(t.job_name, t.index)
            self.events.emit(EventType.TASK_FINISHED, task=t.task_id, state="LOST")
            if t.container_id:
                self.backend.release(t.container_id)
            # container_pid is intentionally KEPT: release() is best-effort
            # (an unreachable host ignores the kill), so a successor AM
            # attempt must still be able to reap this possible orphan.

    def _apply_failure_policy(self) -> bool:
        """Handle failed/lost tracked tasks. Returns True if the job is over."""
        failed = self.session.failed_tasks()
        if not failed:
            return False
        if self._elastic_enabled:
            # elastic-first: a lost member becomes a shrink generation, not
            # a restart — survivors keep training from in-memory state.
            # Whatever elastic cannot absorb (lost trainer, below
            # min_members) falls through to the cold policy below, whole.
            failed = self._elastic_detach(failed)
            if not failed:
                return False
        # chief semantics: a finished chief ends the job regardless of policy
        # — EXCEPT in an elastic job with a restart policy: there the chief
        # IS the trainer, the host most likely to be preempted, and the
        # documented fallback for losing it is the cold restart.policy path
        # (checkpoint resume), not a hard failure (docs/ELASTIC.md)
        if self.session.chief_type and any(
            t.job_name == self.session.chief_type for t in failed
        ):
            if not (self._elastic_enabled and self._restart_policy != "never"):
                self.session.state = JobState.FAILED
                self.session.diagnostics = "chief failed"
                return True
        if self._restart_policy == "never":
            self.session.state = JobState.FAILED
            self.session.diagnostics = (
                f"task(s) failed: {', '.join(t.task_id for t in failed)}"
            )
            return True
        over_budget = [t for t in failed if t.restarts >= self._max_restarts]
        if over_budget:
            self.session.state = JobState.FAILED
            self.session.diagnostics = (
                "restart budget exhausted for "
                + ", ".join(t.task_id for t in over_budget)
            )
            return True
        if self._restart_policy == "gang":
            self._gang_restart()
        elif self._rendezvous is not None:
            # gloo rendezvous is all-or-nothing: surviving ranks never
            # re-announce, so restarting only the failed task would strand
            # it polling forever — escalate to a full gang restart
            log.warning(
                "restart.policy=failed_only escalated to gang for the "
                "horovod rendezvous contract"
            )
            self._gang_restart()
        else:  # failed_only
            self._restart_tasks({t.job_name for t in failed}, only_failed=True)
        return False

    def _gang_restart(self) -> None:
        """Barrier-restart the whole gang (fixed-topology TPU slice semantics).

        Every container is released, every task reset to PENDING with a bumped
        attempt (stale executors get ABORT on their next heartbeat), and the
        scheduler re-launches the full job. User scripts resume from the last
        checkpoint (restart.resume_from_checkpoint glue in the trainer).
        """
        log.warning("gang restart (generation %d)", self.session.generation + 1)
        self.events.emit(EventType.GANG_RESTART, generation=self.session.generation + 1)
        with trace.span("am.gang_restart", parent=self._run_span.sid or None,
                        generation=self.session.generation + 1):
            with self.session.lock:
                cids = [t.container_id for t in self.session.tasks.values() if t.container_id]
            for cid in cids:
                self.backend.release(cid)
            self.session.reset_for_restart(None)
            if self._rendezvous is not None:
                self._rendezvous.clear()  # stale peer info must 404 after restart
            if self._elastic_enabled:
                # the cold path supersedes elastic bookkeeping: every seat
                # relaunches below, so nothing is detached any more — a
                # stale entry would double-allocate the seat on the next
                # grow tick AND exclude a live member from every future
                # generation. Declare a fresh full-membership baseline at
                # the restarted generation so relaunched trainers don't
                # fence on the pre-restart shrink record.
                self._elastic_detached.clear()
                self._elastic_relaunching.clear()
                self._elastic_declare("start", reason="gang restart")
            self._write_am_state()
            self._drain_notifications()
            self.scheduler.schedule_all(self.specs)

    def _restart_tasks(self, job_names: set[str], only_failed: bool) -> None:
        # reset the task table under the lock, release containers OUTSIDE
        # it (release can block on a remote backend, and RPC handlers need
        # the session lock to serve heartbeats meanwhile) — same collect-
        # then-release shape as _gang_restart and _check_heartbeats
        with self.session.lock:
            victims = [
                t
                for t in self.session.tasks.values()
                if t.job_name in job_names
                and (not only_failed or t.state in (TaskState.FAILED, TaskState.LOST))
            ]
            cids = [t.container_id for t in victims if t.container_id]
            for t in victims:
                t.state = TaskState.PENDING
                t.host, t.port = "", 0
                t.container_id = ""
                t.container_pid = 0
                t.exit_code = None
                t.attempt += 1
                t.restarts += 1
                t.last_heartbeat = 0.0
        for cid in cids:
            self.backend.release(cid)
        log.warning("restarting %s", ", ".join(t.task_id for t in victims))
        self._write_am_state()
        self.scheduler.schedule_all(self.specs)

    def _drain_notifications(self) -> None:
        """Drop queued notifications from superseded attempts after a restart."""
        try:
            while True:
                self._notifications.get_nowait()
        except queue.Empty:
            pass

    def _teardown(self) -> None:
        self._lease_keeper_stop.set()
        self.scheduler.stop()
        self.backend.stop()
        if self._rendezvous is not None:
            self._rendezvous.stop()
        self.events.emit(
            EventType.APPLICATION_FINISHED,
            state=self.session.state.value,
            diagnostics=self.session.diagnostics,
        )
        # registry snapshot into the job history (the AM's own counters —
        # served RPCs per method; portal /metrics re-renders it)
        try:
            from tony_tpu.obs.registry import write_snapshot

            proc = f"am_a{self.am_attempt}"
            write_snapshot(
                os.path.join(self.app_dir, "metrics", f"{proc}.json"), proc=proc
            )
        except Exception:
            log.debug("registry snapshot failed", exc_info=True)
        self.events.close()
        # Leave the RPC server up briefly so the client's final status poll
        # lands; the process exits right after run() returns anyway.

    def _write_status(self, code: int) -> None:
        status = {
            "app_id": self.app_id,
            "state": self.session.state.value,
            "exit_code": code,
            "diagnostics": self.session.diagnostics,
            "tensorboard_url": self.session.tensorboard_url,
            "queue": self.config.get_str(Keys.APPLICATION_QUEUE, "default"),
            "tags": self.config.get_list(Keys.APPLICATION_TAGS),
            "tasks": [
                {
                    "task": t.task_id,
                    "state": t.state.value,
                    "exit_code": t.exit_code,
                    "attempts": t.attempt + 1,
                    "log": t.log_path,
                }
                for t in self.session.tasks.values()
            ],
        }
        path = os.path.join(self.app_dir, "status.json")
        with open(path + ".tmp", "w") as f:
            json.dump(status, f, indent=2, sort_keys=True)
        os.replace(path + ".tmp", path)


def main() -> None:
    """AM process entry: ``python -m tony_tpu.am.app_master <app_dir>``."""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s AM %(levelname)s %(name)s: %(message)s",
    )
    app_dir = sys.argv[1]
    app_id = os.path.basename(app_dir.rstrip("/"))
    config = TonyConfig.from_json(
        open(os.path.join(app_dir, "config.json")).read()
    )
    # arm fault injection for THIS process only when the job asks for it
    # (chaos.enabled + a schedule); inert otherwise
    from tony_tpu.chaos import install_from_config

    install_from_config(config, role="am")
    am_attempt = int(os.environ.get("TONY_AM_ATTEMPT", "0"))
    # arm the trace spine for THIS process (on by default; trace.enabled
    # false disarms the whole job — container env is derived from this)
    trace.install_from_config(config, app_dir, app_id, proc=f"am_a{am_attempt}")
    am = ApplicationMaster(config, app_id, app_dir, am_attempt=am_attempt)
    code = am.run()
    trace.uninstall()  # flush + close the journal before exit
    # Give the client one status-poll interval to observe the final state.
    time.sleep(1.0)
    if am._server is not None:
        am._server.stop(0.5)
    sys.exit(code)


if __name__ == "__main__":
    main()

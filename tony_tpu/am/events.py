"""Event/history pipeline: the .jhist analogue.

The reference defines avro events (ApplicationInited / TaskStarted /
TaskFinished / ApplicationFinished / Metadata), written by an AM EventHandler
thread to an HDFS intermediate dir and moved to a finished dir on exit, where
the portal reads them (SURVEY.md sections 2, 3.5). Here events are JSONL (one
object per line, ``{"type": ..., "ts": ..., ...fields}``) in
``<history.intermediate_dir>/<app_id>.jhist.jsonl``, atomically moved to
``<history.finished_dir>`` at teardown; the bundled portal (obs/portal.py)
reads the finished dir.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Any


class EventType:
    APPLICATION_INITED = "APPLICATION_INITED"
    TASK_STARTED = "TASK_STARTED"
    TASK_REGISTERED = "TASK_REGISTERED"
    TASK_FINISHED = "TASK_FINISHED"
    GANG_RESTART = "GANG_RESTART"
    # elastic membership boundaries (docs/ELASTIC.md): the AM declared a
    # new cluster generation instead of cold-restarting the gang
    ELASTIC_SHRINK = "ELASTIC_SHRINK"
    ELASTIC_GROW = "ELASTIC_GROW"
    APPLICATION_FINISHED = "APPLICATION_FINISHED"
    METADATA = "METADATA"
    METRICS = "METRICS"


class EventWriter:
    """Async JSONL event writer (the EventHandler-thread analogue).

    Events are enqueued from RPC/monitor threads and drained by one writer
    thread, so event IO never blocks the control plane.
    """

    def __init__(self, app_id: str, intermediate_dir: str, finished_dir: str = ""):
        self.app_id = app_id
        self.intermediate_dir = intermediate_dir
        self.finished_dir = finished_dir or intermediate_dir
        self._q: queue.Queue[dict[str, Any] | None] = queue.Queue()
        self._path = ""
        self._thread: threading.Thread | None = None
        if intermediate_dir:
            os.makedirs(intermediate_dir, exist_ok=True)
            self._path = os.path.join(intermediate_dir, f"{app_id}.jhist.jsonl")
            self._thread = threading.Thread(
                target=self._drain, daemon=True, name="event-writer"
            )
            self._thread.start()

    def emit(self, event_type: str, **fields: Any) -> None:
        if not self._path:
            return
        self._q.put({"type": event_type, "ts": time.time(), "app_id": self.app_id, **fields})

    def _drain(self) -> None:
        with open(self._path, "a", encoding="utf-8") as f:
            while True:
                item = self._q.get()
                if item is None:
                    f.flush()
                    return
                f.write(json.dumps(item, sort_keys=True) + "\n")
                f.flush()

    def close(self) -> None:
        """Flush, stop the writer, and move the file to the finished dir."""
        if not self._path:
            return
        self._q.put(None)
        if self._thread:
            self._thread.join(timeout=10)
        if self.finished_dir != self.intermediate_dir:
            os.makedirs(self.finished_dir, exist_ok=True)
            dst = os.path.join(self.finished_dir, os.path.basename(self._path))
            try:
                os.replace(self._path, dst)
                self._path = dst
            except OSError:
                pass

    @property
    def path(self) -> str:
        return self._path


def submit_latency(app_dir: str) -> dict:
    """AM-submit -> first-training-step latency, with a phase breakdown.

    The north-star latency metric (SURVEY.md section 3.1: "the only
    latency-critical path is submit -> first-step"): wall time from the
    client's submit moment (written to ``<app_dir>/submitted_at`` before
    staging) to the AM's first METRICS event carrying a ``step`` sample
    (fit() pushes one after the very first optimizer step). Phases:

    - ``am_inited_s``    — staging + AM process boot (APPLICATION_INITED)
    - ``task_started_s`` — + container allocation/launch (first TASK_STARTED)
    - ``registered_s``   — + executor boot/registration (first TASK_REGISTERED)
    - ``first_step_s``   — + gang barrier, jax/dist init, compile, step 1
    - ``startup``        — fit()'s in-worker breakdown of that last gap
      (``compile_s`` / ``restore_s`` / ``first_batch_s``), when the job
      pushed one (overlapped phases, so they need not sum to the gap)

    Raises ``FileNotFoundError``/``ValueError`` when the app dir predates
    this instrumentation or no step metric was ever pushed.
    """
    with open(os.path.join(app_dir, "submitted_at")) as f:
        t0 = json.load(f)["ts"]
    events = read_history(_find_history_file(app_dir))
    out: dict[str, float] = {}

    def first(pred, key):
        for e in events:
            if pred(e):
                out[key] = round(e["ts"] - t0, 3)
                return e
    first(lambda e: e["type"] == EventType.APPLICATION_INITED, "am_inited_s")
    first(lambda e: e["type"] == EventType.TASK_STARTED, "task_started_s")
    first(lambda e: e["type"] == EventType.TASK_REGISTERED, "registered_s")
    first_step_event = first(
        lambda e: e["type"] == EventType.METRICS
        and e.get("samples", {}).get("step", 0) >= 1,
        "first_step_s",
    )
    if first_step_event is not None:
        # fit() attaches a startup-phase breakdown (compile vs restore vs
        # first-batch, as startup_* samples) to its first step push; surface
        # it so the latency bench shows where the first-step gap went
        phases = {
            k[len("startup_"):]: v
            for k, v in first_step_event["samples"].items()
            if k.startswith("startup_")
        }
        if phases:
            out["startup"] = phases
    if "first_step_s" not in out:
        raise ValueError(
            f"no step METRICS event in {app_dir} (job not using fit(), or "
            "it never completed a step)"
        )
    return out


def _find_history_file(app_dir: str) -> str:
    """Locate the app's .jhist.jsonl: the AM writes it to
    history.intermediate_dir (from the app's own config.json) and moves it
    to history.finished_dir on close, defaulting to <app_dir>/events —
    check all three so configured-portal jobs resolve too."""
    app_id = os.path.basename(os.path.abspath(app_dir).rstrip("/"))
    candidates = [os.path.join(app_dir, "events")]
    cfg_path = os.path.join(app_dir, "config.json")
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            cfg = json.load(f)
        for key in ("history.finished_dir", "history.intermediate_dir"):
            d = cfg.get(key)
            if d:
                candidates.insert(0, d)
    private = os.path.join(app_dir, "events")
    for d in candidates:
        path = os.path.join(d, f"{app_id}.jhist.jsonl")
        if os.path.exists(path):
            return path
        # Unknown app-id naming: fall back to "the single history file" —
        # but ONLY in the app-private default dir, where no other app can
        # have written. In a SHARED configured history dir the lone file
        # may belong to a different application entirely, and a latency
        # breakdown silently computed from someone else's events is worse
        # than the FileNotFoundError.
        if d == private and os.path.isdir(d):
            files = [f for f in os.listdir(d) if f.endswith(".jhist.jsonl")]
            if len(files) == 1:
                return os.path.join(d, files[0])
    raise FileNotFoundError(
        f"no history file for {app_id} under any of {candidates}"
    )


def read_history(path: str) -> list[dict[str, Any]]:
    """Parse a .jhist.jsonl file (portal read path)."""
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


__all__ = ["EventType", "EventWriter", "read_history", "submit_latency"]

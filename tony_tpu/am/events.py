"""Event/history pipeline: the .jhist analogue.

The reference defines avro events (ApplicationInited / TaskStarted /
TaskFinished / ApplicationFinished / Metadata), written by an AM EventHandler
thread to an HDFS intermediate dir and moved to a finished dir on exit, where
the portal reads them (SURVEY.md sections 2, 3.5). Here events are JSONL (one
object per line, ``{"type": ..., "ts": ..., ...fields}``) in
``<history.intermediate_dir>/<app_id>.jhist.jsonl``, atomically moved to
``<history.finished_dir>`` at teardown; the bundled portal (obs/portal.py)
reads the finished dir.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from typing import Any


class EventType:
    APPLICATION_INITED = "APPLICATION_INITED"
    TASK_STARTED = "TASK_STARTED"
    TASK_REGISTERED = "TASK_REGISTERED"
    TASK_FINISHED = "TASK_FINISHED"
    GANG_RESTART = "GANG_RESTART"
    APPLICATION_FINISHED = "APPLICATION_FINISHED"
    METADATA = "METADATA"
    METRICS = "METRICS"


class EventWriter:
    """Async JSONL event writer (the EventHandler-thread analogue).

    Events are enqueued from RPC/monitor threads and drained by one writer
    thread, so event IO never blocks the control plane.
    """

    def __init__(self, app_id: str, intermediate_dir: str, finished_dir: str = ""):
        self.app_id = app_id
        self.intermediate_dir = intermediate_dir
        self.finished_dir = finished_dir or intermediate_dir
        self._q: queue.Queue[dict[str, Any] | None] = queue.Queue()
        self._path = ""
        self._thread: threading.Thread | None = None
        if intermediate_dir:
            os.makedirs(intermediate_dir, exist_ok=True)
            self._path = os.path.join(intermediate_dir, f"{app_id}.jhist.jsonl")
            self._thread = threading.Thread(
                target=self._drain, daemon=True, name="event-writer"
            )
            self._thread.start()

    def emit(self, event_type: str, **fields: Any) -> None:
        if not self._path:
            return
        self._q.put({"type": event_type, "ts": time.time(), "app_id": self.app_id, **fields})

    def _drain(self) -> None:
        with open(self._path, "a", encoding="utf-8") as f:
            while True:
                item = self._q.get()
                if item is None:
                    f.flush()
                    return
                f.write(json.dumps(item, sort_keys=True) + "\n")
                f.flush()

    def close(self) -> None:
        """Flush, stop the writer, and move the file to the finished dir."""
        if not self._path:
            return
        self._q.put(None)
        if self._thread:
            self._thread.join(timeout=10)
        if self.finished_dir != self.intermediate_dir:
            os.makedirs(self.finished_dir, exist_ok=True)
            dst = os.path.join(self.finished_dir, os.path.basename(self._path))
            try:
                os.replace(self._path, dst)
                self._path = dst
            except OSError:
                pass

    @property
    def path(self) -> str:
        return self._path


def read_history(path: str) -> list[dict[str, Any]]:
    """Parse a .jhist.jsonl file (portal read path)."""
    events = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


__all__ = ["EventType", "EventWriter", "read_history"]

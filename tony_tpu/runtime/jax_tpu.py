"""JaxTpuRuntime: the first-class TPU-native framework runtime.

The north star (BASELINE.json): "TaskExecutor bootstraps
``jax.distributed.initialize`` with the AM-assigned coordinator address and
process_id instead of exporting TF_CONFIG/HOROVOD_*". The coordinator is the
rank-0 task's registered address (the executor reserved that port, so the JAX
coordination service in the rank-0 user process can bind it); the data plane
is XLA collectives over ICI/DCN — no NCCL/Gloo surface exists.

User scripts call :func:`initialize` (or just read the env themselves):

    import tony_tpu.runtime.jax_tpu as rt
    rt.initialize()          # no-op outside a tony-tpu job
    ... jax code; jax.process_index() == TONY_PROCESS_ID ...
"""

from __future__ import annotations

import os

from tony_tpu.config.config import TonyConfig
from tony_tpu.runtime.base import Runtime, TaskIdentity

ENV_COORDINATOR = "TONY_COORDINATOR_ADDR"
ENV_PROCESS_ID = "TONY_PROCESS_ID"
ENV_NUM_PROCESSES = "TONY_NUM_PROCESSES"


class JaxTpuRuntime(Runtime):
    name = "jax"

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        env = super().build_env(identity, config)
        # Also export JAX's own spellings so scripts that never import
        # tony_tpu still work: jax.distributed.initialize() with no args
        # reads JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID.
        env.update(
            {
                "JAX_COORDINATOR_ADDRESS": identity.coordinator_address,
                "JAX_NUM_PROCESSES": str(identity.num_processes),
                "JAX_PROCESS_ID": str(identity.process_id),
            }
        )
        return env


def in_tony_job() -> bool:
    return ENV_COORDINATOR in os.environ


def initialize(**kwargs) -> None:
    """Bootstrap jax.distributed from the tony-tpu env; no-op standalone.

    Safe to call unconditionally at the top of a training script: outside a
    tony-tpu job (or in a single-process job) it does nothing, so the same
    script runs under ``tony submit`` and bare ``python``.
    """
    if not in_tony_job():
        return
    num = int(os.environ[ENV_NUM_PROCESSES])
    if num <= 1:
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=os.environ[ENV_COORDINATOR],
        num_processes=num,
        process_id=int(os.environ[ENV_PROCESS_ID]),
        **kwargs,
    )


def process_id() -> int:
    return int(os.environ.get(ENV_PROCESS_ID, "0"))


def num_processes() -> int:
    return int(os.environ.get(ENV_NUM_PROCESSES, "1"))


__all__ = [
    "JaxTpuRuntime",
    "in_tony_job",
    "initialize",
    "num_processes",
    "process_id",
]

"""Runtime adapter interface.

Rebuild of the reference's per-framework ``Framework`` adapter interfaces
(AMAdapter / TaskExecutorAdapter; SURVEY.md section 2 "Runtime adapters"):
given the AM-assembled cluster spec and the task's own identity, a runtime
builds the environment its framework needs to self-organise — TF_CONFIG for
TensorFlow, MASTER_ADDR/RANK for PyTorch, HOROVOD_* for Horovod, and the
jax.distributed coordinator contract for JAX (the TPU-native first-class
path, BASELINE.json north star).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass

from tony_tpu.config.config import TonyConfig
from tony_tpu.config.keys import Keys


@dataclass(frozen=True)
class TaskIdentity:
    """Everything an executor knows about itself after the gang barrier."""

    job_name: str
    index: int
    cluster_spec: dict[str, list[str]]   # type -> ["host:port", ...]
    coordinator_address: str             # rank-0 "host:port"
    process_id: int                      # global rank (-1 for untracked types)
    num_processes: int
    generation: int = 0

    @property
    def own_address(self) -> str:
        return self.cluster_spec[self.job_name][self.index]

    @classmethod
    def from_cluster_spec_response(cls, job_name: str, index: int, resp) -> "TaskIdentity":
        return cls(
            job_name=job_name,
            index=index,
            cluster_spec=json.loads(resp.spec_json),
            coordinator_address=resp.coordinator_address,
            process_id=resp.process_id,
            num_processes=resp.num_processes,
            generation=resp.generation,
        )


class Runtime:
    """Base adapter: subclasses override hooks they need."""

    name = "generic"

    def validate(self, config: TonyConfig) -> None:
        """Raise on invalid config for this framework (AM-side, pre-schedule)."""

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        """Env exported into the user training process (executor-side)."""
        env = {
            "TONY_CLUSTER_SPEC": json.dumps(identity.cluster_spec, sort_keys=True),
            "TONY_JOB_NAME": identity.job_name,
            "TONY_TASK_INDEX": str(identity.index),
            "TONY_COORDINATOR_ADDR": identity.coordinator_address,
            "TONY_PROCESS_ID": str(identity.process_id),
            "TONY_NUM_PROCESSES": str(identity.num_processes),
            "TONY_GENERATION": str(identity.generation),
        }
        # Checkpoint/resume glue (milestone config #5): the job config drives
        # the trainer's checkpointing; fit() reads these as FitConfig defaults
        # so a gang restart resumes at the last orbax step without the user
        # script hardcoding paths.
        ckpt_dir = config.get_str(Keys.CHECKPOINT_DIR)
        if ckpt_dir:
            env["TONY_CHECKPOINT_DIR"] = ckpt_dir
            env["TONY_CHECKPOINT_INTERVAL_STEPS"] = str(
                config.get_int(Keys.CHECKPOINT_INTERVAL_STEPS, 0)
            )
            env["TONY_CHECKPOINT_KEEP"] = str(config.get_int(Keys.CHECKPOINT_KEEP, 3))
            env["TONY_RESUME_FROM_CHECKPOINT"] = (
                "true" if config.get_bool(Keys.RESTART_RESUME_FROM_CHECKPOINT, True)
                else "false"
            )
        # Persistent XLA compilation cache: the single biggest submit->
        # first-step lever (docs/PERF.md latency section) — resubmits and
        # elastic gang restarts of the same job skip compile entirely.
        # fit() applies it; default on, per-user shared dir.
        if config.get_bool(Keys.TRAIN_JAX_CACHE, True):
            env["TONY_JAX_CACHE_DIR"] = config.get_str(
                Keys.TRAIN_JAX_CACHE_DIR, ""
            ) or os.path.expanduser(os.path.join("~", ".tony-tpu", "jax_cache"))
        # One flag to get per-host traces (SURVEY.md section 5 "Tracing"):
        # the profiler server must live in the process doing the compute, so
        # the executor exports the intent and fit() starts it.
        if config.get_bool(Keys.PROFILER_ENABLED, False):
            env["TONY_PROFILER_PORT"] = str(config.get_int(Keys.PROFILER_PORT, 9999))
        # stack-trace collection for wedged jobs (obs.diagnostics glue)
        if config.get_bool(Keys.DIAGNOSTICS_ENABLED, False):
            env["TONY_TPU_DIAGNOSTICS"] = "1"
        return env

    def needs_data_port(self) -> bool:
        """Whether each task must reserve a data port for the cluster spec.

        True for frameworks whose processes listen on their spec address (TF
        parameter servers, the JAX coordinator); the executor bind-probes a
        free port before registering (reference: executor port allocation,
        SURVEY.md section 5).
        """
        return True


__all__ = ["Runtime", "TaskIdentity"]

"""AM-side Horovod gloo rendezvous server.

The reference's HorovodDriver spawns a python process on the AM hosting the
gloo rendezvous for workers (SURVEY.md section 3.4): gloo's HTTP store is a
plain key/value server — clients PUT their connectivity info under a scope
and poll GET until their peers' keys appear (a 404 means "not yet", the
client retries until its timeout).

This module is that server, stdlib-only so it also runs where horovod is not
installed (rank/size themselves come from the AM rank table via the
HOROVOD_* env, not from the store):

    PUT /<scope>/<key>   store the body           -> 200
    GET /<scope>/<key>   body if present          -> 200 | 404
    DELETE /<scope>      drop a scope's keys      -> 200

The ApplicationMaster starts it for framework == "horovod" jobs and exports
TONY_HOROVOD_RENDEZVOUS_PORT into containers; HorovodRuntime points
HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT at it.

Security note: gloo clients speak plain unauthenticated HTTP, so this store
cannot be behind the control plane's per-app token (the reference's horovod
rendezvous server is equally open — protocol parity). Run horovod jobs on a
trusted network segment; the store only exists for the job's lifetime.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class RendezvousServer:
    """Threaded HTTP KV store speaking the gloo rendezvous protocol."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._store: dict[str, bytes] = {}
        self._lock = threading.Lock()
        store, lock = self._store, self._lock

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _reply(self, code: int, body: bytes = b"") -> None:
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def do_PUT(self):  # noqa: N802 (stdlib casing)
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n) if n else b""
                with lock:
                    store[self.path] = body
                self._reply(200)

            def do_GET(self):  # noqa: N802
                with lock:
                    body = store.get(self.path)
                if body is None:
                    self._reply(404)  # gloo polls until the key appears
                else:
                    self._reply(200, body)

            def do_DELETE(self):  # noqa: N802
                prefix = self.path.rstrip("/")
                with lock:
                    # scope-exact: /job1 must not wipe /job10's keys
                    for key in [
                        k for k in store
                        if k == prefix or k.startswith(prefix + "/")
                    ]:
                        del store[key]
                self._reply(200)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="hvd-rendezvous"
        )

    def start(self) -> "RendezvousServer":
        self._thread.start()
        return self

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)

    def clear(self) -> None:
        """Drop every key — called on worker restart so a relaunched gang
        polls for FRESH peer info instead of reading the dead generation's
        connectivity records."""
        with self._lock:
            self._store.clear()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


__all__ = ["RendezvousServer"]

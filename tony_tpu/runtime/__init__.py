"""Framework runtimes: per-framework cluster bootstrap adapters."""

from tony_tpu.runtime.base import Runtime, TaskIdentity
from tony_tpu.runtime.frameworks import (
    ElasticRuntime,
    HorovodRuntime,
    MLGenericRuntime,
    MXNetRuntime,
    PyTorchRuntime,
    ServeRuntime,
    TFRuntime,
)
from tony_tpu.runtime.jax_tpu import JaxTpuRuntime, in_tony_job, initialize

_RUNTIMES = {
    cls.name: cls
    for cls in (
        JaxTpuRuntime, TFRuntime, PyTorchRuntime, HorovodRuntime,
        MXNetRuntime, MLGenericRuntime, ServeRuntime, ElasticRuntime,
    )
}


def make_runtime(framework: str) -> Runtime:
    """Runtime factory keyed by the ``application.framework`` config value."""
    try:
        return _RUNTIMES[framework]()
    except KeyError:
        raise ValueError(
            f"unknown framework {framework!r} (expected one of {sorted(_RUNTIMES)})"
        ) from None


__all__ = [
    "ElasticRuntime",
    "HorovodRuntime",
    "JaxTpuRuntime",
    "MLGenericRuntime",
    "MXNetRuntime",
    "PyTorchRuntime",
    "Runtime",
    "ServeRuntime",
    "TFRuntime",
    "TaskIdentity",
    "in_tony_job",
    "initialize",
    "make_runtime",
]

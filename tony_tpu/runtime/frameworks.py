"""TF / PyTorch / Horovod / generic runtime adapters.

Parity adapters for the reference's ``TFRuntime`` / ``PyTorchRuntime`` /
``HorovodRuntime`` / ``MLGenericRuntime`` (SURVEY.md sections 2, 3.2): the
contract is the *environment* each framework's own bootstrapping reads. The
data plane stays delegated (TF gRPC, c10d, Horovod controllers) exactly as in
the reference — on TPU deployments these exist for migration parity and
CPU-mode tests; the first-class path is JaxTpuRuntime.
"""

from __future__ import annotations

import json

from tony_tpu.config.config import TonyConfig
from tony_tpu.runtime.base import Runtime, TaskIdentity


class TFRuntime(Runtime):
    """Exports TF_CONFIG (reference: SURVEY.md section 3.2 step 3).

    ``{"cluster": {"ps": [...], "worker": [...]}, "task": {"type": ..., "index": ...}}``
    — consumed by tf.distribute (MultiWorkerMirrored / ParameterServerStrategy)
    and by bare tf.train.Server code.
    """

    name = "tensorflow"

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        env = super().build_env(identity, config)
        env["TF_CONFIG"] = json.dumps(
            {
                "cluster": identity.cluster_spec,
                "task": {"type": identity.job_name, "index": identity.index},
            },
            sort_keys=True,
        )
        return env


class PyTorchRuntime(Runtime):
    """Exports the torch.distributed env-var init contract.

    MASTER_ADDR/MASTER_PORT point at the rank-0 task's reserved address;
    RANK/WORLD_SIZE come from the AM rank table; LOCAL_RANK is 0 because the
    substrate schedules one process per container (the reference does the
    same — one executor per container).
    """

    name = "pytorch"

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        env = super().build_env(identity, config)
        host, _, port = identity.coordinator_address.rpartition(":")
        env.update(
            {
                "MASTER_ADDR": host,
                "MASTER_PORT": port,
                "RANK": str(identity.process_id),
                "WORLD_SIZE": str(identity.num_processes),
                "LOCAL_RANK": "0",
            }
        )
        return env


class HorovodRuntime(Runtime):
    """Horovod gloo env contract, backed by the AM's rendezvous store.

    The reference runs an AM-side python driver hosting a Gloo rendezvous
    server and exports HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT plus rank vars
    (SURVEY.md section 3.4). Same shape here: the AM serves the gloo HTTP
    KV store (runtime.horovod_driver.RendezvousServer) and advertises its
    port via TONY_HOROVOD_RENDEZVOUS_PORT; rank/size come straight from the
    AM rank table. On TPU the ring-allreduce itself is replaced by lax.psum
    over ICI (the BASELINE.json mapping) — this adapter is the migration
    lane for jobs still importing horovod in CPU/gloo mode.
    """

    name = "horovod"

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        import os

        env = super().build_env(identity, config)
        # the rendezvous store lives on the AM; fall back to the coordinator
        # address only if the AM didn't start one (no TONY_* env: unit tests)
        am_host = os.environ.get("TONY_AM_ADDR", "").rpartition(":")[0]
        rdv_port = os.environ.get("TONY_HOROVOD_RENDEZVOUS_PORT", "")
        host, _, port = identity.coordinator_address.rpartition(":")
        if am_host and rdv_port:
            host, port = am_host, rdv_port
        # one slot per container -> local size 1, cross size == world size
        env.update(
            {
                "HOROVOD_CONTROLLER": "gloo",
                "HOROVOD_CPU_OPERATIONS": "gloo",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": host,
                "HOROVOD_GLOO_RENDEZVOUS_PORT": port,
                "HOROVOD_RANK": str(identity.process_id),
                "HOROVOD_SIZE": str(identity.num_processes),
                "HOROVOD_LOCAL_RANK": "0",
                "HOROVOD_LOCAL_SIZE": "1",
                "HOROVOD_CROSS_RANK": str(identity.process_id),
                "HOROVOD_CROSS_SIZE": str(identity.num_processes),
                "HOROVOD_HOSTNAME": identity.own_address.rpartition(":")[0],
            }
        )
        return env


class MXNetRuntime(Runtime):
    """MXNet parameter-server (DMLC/kvstore) env contract.

    Reference parity for the MXNetRuntime adapter (SURVEY.md section 2
    "Runtime adapters"): DMLC processes find each other through the
    scheduler's address. Job types map directly: ``scheduler`` (1 instance),
    ``server``, ``worker``; the scheduler task doubles as the root URI.
    """

    name = "mxnet"

    def validate(self, config: TonyConfig) -> None:
        if "scheduler" not in config.job_types():
            raise ValueError("mxnet jobs need a [job.scheduler] with instances = 1")
        if config.task_spec("scheduler").instances != 1:
            raise ValueError("mxnet jobs need exactly one scheduler instance")

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        env = super().build_env(identity, config)
        schedulers = identity.cluster_spec.get("scheduler", [])
        if len(schedulers) != 1:
            raise ValueError(
                f"mxnet cluster spec needs exactly one scheduler, got {schedulers}"
            )
        host, _, port = schedulers[0].rpartition(":")
        env.update(
            {
                "DMLC_ROLE": identity.job_name,
                "DMLC_PS_ROOT_URI": host,
                "DMLC_PS_ROOT_PORT": port,
                "DMLC_NUM_SERVER": str(len(identity.cluster_spec.get("server", []))),
                "DMLC_NUM_WORKER": str(len(identity.cluster_spec.get("worker", []))),
            }
        )
        return env


class MLGenericRuntime(Runtime):
    """No framework assumptions: just the TONY_* cluster env (base class)."""

    name = "generic"

    def needs_data_port(self) -> bool:
        return True


class ServeRuntime(Runtime):
    """`tony serve` gang workers (serve/gang.py; docs/SERVE.md).

    The serving job type's contract: every decode host LISTENS on the
    data port the executor reserved and registered (the frontend
    discovers hosts at exactly those cluster-spec addresses through the
    AM task table), so the port is exported explicitly as
    TONY_SERVE_PORT; the ``serve.gang.*`` key group rides along as JSON
    (TONY_SERVE_GANG) — the AM -> executor -> worker export path every
    obs.* key group uses — so the worker needs no config-file reparse.
    """

    name = "serve"

    def validate(self, config: TonyConfig) -> None:
        from tony_tpu.config.keys import Keys

        gang_type = config.get_str(Keys.SERVE_GANG_JOB_TYPE, "decode")
        if gang_type not in config.job_types():
            raise ValueError(
                f"serve jobs need a [job.{gang_type}] section (or set "
                "serve.gang.job_type to the decode-host task type)"
            )

    def needs_data_port(self) -> bool:
        return True

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        # import-light on purpose: gang.py defers its engine (and jax)
        # imports, so the executor process stays a pure control-plane one
        from tony_tpu.serve.gang import ENV_SERVE_GANG, ENV_SERVE_PORT, GangSettings

        env = super().build_env(identity, config)
        env[ENV_SERVE_PORT] = identity.own_address.rpartition(":")[2]
        env[ENV_SERVE_GANG] = GangSettings.from_config(config).to_json()
        return env


__all__ = [
    "HorovodRuntime",
    "MLGenericRuntime",
    "MXNetRuntime",
    "PyTorchRuntime",
    "ServeRuntime",
    "TFRuntime",
]

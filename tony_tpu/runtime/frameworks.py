"""TF / PyTorch / Horovod / generic runtime adapters.

Parity adapters for the reference's ``TFRuntime`` / ``PyTorchRuntime`` /
``HorovodRuntime`` / ``MLGenericRuntime`` (SURVEY.md sections 2, 3.2): the
contract is the *environment* each framework's own bootstrapping reads. The
data plane stays delegated (TF gRPC, c10d, Horovod controllers) exactly as in
the reference — on TPU deployments these exist for migration parity and
CPU-mode tests; the first-class path is JaxTpuRuntime.
"""

from __future__ import annotations

import json

from tony_tpu.config.config import TonyConfig
from tony_tpu.runtime.base import Runtime, TaskIdentity


class TFRuntime(Runtime):
    """Exports TF_CONFIG (reference: SURVEY.md section 3.2 step 3).

    ``{"cluster": {"ps": [...], "worker": [...]}, "task": {"type": ..., "index": ...}}``
    — consumed by tf.distribute (MultiWorkerMirrored / ParameterServerStrategy)
    and by bare tf.train.Server code.
    """

    name = "tensorflow"

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        env = super().build_env(identity, config)
        env["TF_CONFIG"] = json.dumps(
            {
                "cluster": identity.cluster_spec,
                "task": {"type": identity.job_name, "index": identity.index},
            },
            sort_keys=True,
        )
        return env


class PyTorchRuntime(Runtime):
    """Exports the torch.distributed env-var init contract.

    MASTER_ADDR/MASTER_PORT point at the rank-0 task's reserved address;
    RANK/WORLD_SIZE come from the AM rank table; LOCAL_RANK is 0 because the
    substrate schedules one process per container (the reference does the
    same — one executor per container).
    """

    name = "pytorch"

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        env = super().build_env(identity, config)
        host, _, port = identity.coordinator_address.rpartition(":")
        env.update(
            {
                "MASTER_ADDR": host,
                "MASTER_PORT": port,
                "RANK": str(identity.process_id),
                "WORLD_SIZE": str(identity.num_processes),
                "LOCAL_RANK": "0",
            }
        )
        return env


class HorovodRuntime(Runtime):
    """Horovod gloo env contract, backed by the AM's rendezvous store.

    The reference runs an AM-side python driver hosting a Gloo rendezvous
    server and exports HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT plus rank vars
    (SURVEY.md section 3.4). Same shape here: the AM serves the gloo HTTP
    KV store (runtime.horovod_driver.RendezvousServer) and advertises its
    port via TONY_HOROVOD_RENDEZVOUS_PORT; rank/size come straight from the
    AM rank table. On TPU the ring-allreduce itself is replaced by lax.psum
    over ICI (the BASELINE.json mapping) — this adapter is the migration
    lane for jobs still importing horovod in CPU/gloo mode.
    """

    name = "horovod"

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        import os

        env = super().build_env(identity, config)
        # the rendezvous store lives on the AM; fall back to the coordinator
        # address only if the AM didn't start one (no TONY_* env: unit tests)
        am_host = os.environ.get("TONY_AM_ADDR", "").rpartition(":")[0]
        rdv_port = os.environ.get("TONY_HOROVOD_RENDEZVOUS_PORT", "")
        host, _, port = identity.coordinator_address.rpartition(":")
        if am_host and rdv_port:
            host, port = am_host, rdv_port
        # one slot per container -> local size 1, cross size == world size
        env.update(
            {
                "HOROVOD_CONTROLLER": "gloo",
                "HOROVOD_CPU_OPERATIONS": "gloo",
                "HOROVOD_GLOO_RENDEZVOUS_ADDR": host,
                "HOROVOD_GLOO_RENDEZVOUS_PORT": port,
                "HOROVOD_RANK": str(identity.process_id),
                "HOROVOD_SIZE": str(identity.num_processes),
                "HOROVOD_LOCAL_RANK": "0",
                "HOROVOD_LOCAL_SIZE": "1",
                "HOROVOD_CROSS_RANK": str(identity.process_id),
                "HOROVOD_CROSS_SIZE": str(identity.num_processes),
                "HOROVOD_HOSTNAME": identity.own_address.rpartition(":")[0],
            }
        )
        return env


class MXNetRuntime(Runtime):
    """MXNet parameter-server (DMLC/kvstore) env contract.

    Reference parity for the MXNetRuntime adapter (SURVEY.md section 2
    "Runtime adapters"): DMLC processes find each other through the
    scheduler's address. Job types map directly: ``scheduler`` (1 instance),
    ``server``, ``worker``; the scheduler task doubles as the root URI.
    """

    name = "mxnet"

    def validate(self, config: TonyConfig) -> None:
        if "scheduler" not in config.job_types():
            raise ValueError("mxnet jobs need a [job.scheduler] with instances = 1")
        if config.task_spec("scheduler").instances != 1:
            raise ValueError("mxnet jobs need exactly one scheduler instance")

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        env = super().build_env(identity, config)
        schedulers = identity.cluster_spec.get("scheduler", [])
        if len(schedulers) != 1:
            raise ValueError(
                f"mxnet cluster spec needs exactly one scheduler, got {schedulers}"
            )
        host, _, port = schedulers[0].rpartition(":")
        env.update(
            {
                "DMLC_ROLE": identity.job_name,
                "DMLC_PS_ROOT_URI": host,
                "DMLC_PS_ROOT_PORT": port,
                "DMLC_NUM_SERVER": str(len(identity.cluster_spec.get("server", []))),
                "DMLC_NUM_WORKER": str(len(identity.cluster_spec.get("worker", []))),
            }
        )
        return env


class MLGenericRuntime(Runtime):
    """No framework assumptions: just the TONY_* cluster env (base class)."""

    name = "generic"

    def needs_data_port(self) -> bool:
        return True


class ElasticRuntime(Runtime):
    """Elastic training gangs (tony_tpu/elastic/; docs/ELASTIC.md).

    The topology contract differs from the plain jax runtime: the gang is
    NOT one jax.distributed world (a fixed world cannot lose a member
    without wedging every survivor's collectives). Instead the
    coordinator (rank 0, the trainer) is a single-controller jax process
    over the live members' devices, and every other member's seat is held
    by a member agent (``python -m tony_tpu.elastic.member``) whose
    executor heartbeat is the liveness signal the membership protocol
    rides. So each member runs its OWN single-process jax world
    (TONY_NUM_PROCESSES = 1), and the member axis is exported through the
    TONY_ELASTIC* contract the trainer's fit() arms on.
    """

    name = "elastic"

    def validate(self, config: TonyConfig) -> None:
        from tony_tpu.config.keys import Keys

        specs = {
            name: config.task_spec(name) for name in config.job_types()
        }
        # the coordinator must be the chief: job completion follows the
        # trainer (member agents hold seats and never exit on their own),
        # and the rank table puts "chief" first so it is member 0
        if "chief" not in specs or specs["chief"].instances != 1:
            raise ValueError(
                "elastic jobs need a [job.chief] trainer with instances = 1 "
                "(member agents run python -m tony_tpu.elastic.member)"
            )
        tracked_types = sorted(
            name for name, s in specs.items() if not s.untracked
        )
        if tracked_types and tracked_types[0] != "chief":
            # member ranks come from the sorted-type rank table; the AM's
            # elastic path treats rank 0 as the trainer, so a member type
            # sorting before "chief" would silently swap those roles
            raise ValueError(
                f"elastic member type {tracked_types[0]!r} sorts before "
                "'chief': the trainer must be member 0 (rank table is "
                "sorted-type order) — rename the member type"
            )
        tracked = sum(
            s.instances for s in specs.values() if not s.untracked
        )
        min_members = config.get_int(Keys.ELASTIC_MIN_MEMBERS, 1)
        if tracked < 2:
            raise ValueError(
                "elastic jobs need >= 2 tracked member instances "
                f"(got {tracked}); a 1-member gang has nothing to shrink"
            )
        if not 1 <= min_members < tracked:
            raise ValueError(
                f"elastic.min_members={min_members} must be in "
                f"[1, {tracked - 1}] for a {tracked}-member gang"
            )

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        from tony_tpu.config.keys import Keys
        from tony_tpu.elastic.protocol import (
            ENV_ENABLED, ENV_MEMBER, ENV_MEMBERS, ENV_POLL, ENV_SHADOW,
        )

        env = super().build_env(identity, config)
        # each member is its own single-process jax world: the trainer
        # owns the live mesh, member agents own no devices at all
        env.update(
            {
                "TONY_NUM_PROCESSES": "1",
                "JAX_COORDINATOR_ADDRESS": "",
                "JAX_NUM_PROCESSES": "1",
                "JAX_PROCESS_ID": "0",
                ENV_ENABLED: "1",
                ENV_MEMBERS: str(identity.num_processes),
                ENV_MEMBER: str(max(identity.process_id, 0)),
                ENV_POLL: str(config.get_float(Keys.ELASTIC_POLL_S, 0.5)),
                ENV_SHADOW: str(
                    config.get_int(Keys.ELASTIC_SHADOW_STEPS, 16)
                ),
            }
        )
        return env


class ServeRuntime(Runtime):
    """`tony serve` gang workers (serve/gang.py; docs/SERVE.md).

    The serving job type's contract: every decode host LISTENS on the
    data port the executor reserved and registered (the frontend
    discovers hosts at exactly those cluster-spec addresses through the
    AM task table), so the port is exported explicitly as
    TONY_SERVE_PORT; the ``serve.gang.*`` key group rides along as JSON
    (TONY_SERVE_GANG) — the AM -> executor -> worker export path every
    obs.* key group uses — so the worker needs no config-file reparse.
    """

    name = "serve"

    def validate(self, config: TonyConfig) -> None:
        from tony_tpu.config.keys import Keys

        gang_type = config.get_str(Keys.SERVE_GANG_JOB_TYPE, "decode")
        if gang_type not in config.job_types():
            raise ValueError(
                f"serve jobs need a [job.{gang_type}] section (or set "
                "serve.gang.job_type to the decode-host task type)"
            )
        if config.get_int(Keys.SERVE_POOL_PREFILL_HOSTS, 0) > 0:
            ptype = config.get_str(Keys.SERVE_POOL_PREFILL_JOB_TYPE, "prefill")
            if ptype not in config.job_types():
                raise ValueError(
                    f"disaggregated serve jobs need a [job.{ptype}] section "
                    "for the prefill pool (serve.pool.prefill_hosts > 0)"
                )
            if ptype == gang_type:
                raise ValueError(
                    "serve.pool.prefill_job_type must differ from "
                    "serve.gang.job_type (the pools are distinct task types)"
                )

    def needs_data_port(self) -> bool:
        return True

    def build_env(self, identity: TaskIdentity, config: TonyConfig) -> dict[str, str]:
        # import-light on purpose: gang.py defers its engine (and jax)
        # imports, so the executor process stays a pure control-plane one
        from tony_tpu.serve.gang import ENV_SERVE_GANG, ENV_SERVE_PORT, GangSettings

        env = super().build_env(identity, config)
        env[ENV_SERVE_PORT] = identity.own_address.rpartition(":")[2]
        env[ENV_SERVE_GANG] = GangSettings.from_config(config).to_json()
        return env


__all__ = [
    "HorovodRuntime",
    "MLGenericRuntime",
    "MXNetRuntime",
    "PyTorchRuntime",
    "ServeRuntime",
    "TFRuntime",
]

"""Decode-host worker for `tony serve` gangs.

The marriage of the repo's two halves (ROADMAP open item 3): the AM
gang-schedules N containers of this worker — one continuous-batching
:class:`~tony_tpu.serve.engine.Engine` each — and the thin RPC frontend
(serve/frontend.py) routes requests across them. Every host builds the
SAME weights deterministically from ``serve.gang.seed``, so any request
can run (or, after a host death, *re-run*) on any host and, because the
engine gives each request its own rng stream keyed by the frontend's
``rng_seed``, the replay is draw-for-draw identical to the original.

Process shape: the engine is single-threaded by design (one jitted decode
step, host-side admission steering), so one dedicated **engine thread**
owns it exclusively. RPC handler threads never touch the engine; they
talk to the loop through a mailbox (submissions) and per-request output
queues (token streaming) — the same single-decision-maker discipline as
the AM supervision loop (GL004: nothing blocks under a lock; the RPC
seams are the queues).

Lifecycle: the worker binds the exact data port the executor registered
in the cluster spec (``utils.net.bind_with_retry`` closes the
pick-then-bind TOCTOU), serves until the executor forwards SIGTERM (job
teardown / AM abort), then closes the engine — the shutdown summary and
registry snapshot land in the app dir like any serve process. ``Drain``
implements the rolling-restart contract: stop admitting, finish the live
slots (KV state drains naturally as requests complete), optionally
recycle the engine (fresh KV cache) before taking traffic again.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import signal
import threading
import time
from dataclasses import dataclass
from typing import Callable

from typing import TYPE_CHECKING

from tony_tpu.chaos import chaos_hook
from tony_tpu.config.config import TonyConfig
from tony_tpu.config.keys import Keys
from tony_tpu.obs import trace
from tony_tpu.rpc import ServeRpcServicer, pb, serve_rpc

if TYPE_CHECKING:  # the engine (and jax) load lazily: the executor imports
    from tony_tpu.serve.engine import Engine  # this module via the runtime

log = logging.getLogger(__name__)

# env the serve runtime exports AM -> executor -> worker (runtime/frameworks
# ServeRuntime): the data port this host must serve on, and the serve.gang.*
# key group as JSON so the worker needs no config-file round trip
ENV_SERVE_PORT = "TONY_SERVE_PORT"
ENV_SERVE_GANG = "TONY_SERVE_GANG"


@dataclass(frozen=True)
class GangSettings:
    """Resolved ``serve.gang.*`` key group (docs/SERVE.md "Gang serving")."""

    hosts: int = 2
    job_type: str = "decode"
    model: str = "tiny"
    seed: int = 0
    slots: int = 4
    max_len: int = 0
    max_queue: int = 16
    shard: bool = False
    # chunked prefill (serve.chunk_tokens): prompts longer than this prefill
    # in block-aligned chunks, one per decode step; 0 = whole-prompt prefill
    chunk_tokens: int = 0
    # disaggregated pools (serve.pool.*): when prefill_hosts > 0 the gang is
    # heterogeneous — prefill_hosts containers of prefill_job_type run the
    # prefill pool and ship finished KV blocks to the decode pool
    prefill_hosts: int = 0
    prefill_job_type: str = "prefill"
    handoff_min_tokens: int = 64
    frontend_max_inflight: int = 64
    max_replays: int = 3
    ttft_budget_s: float = 0.0
    drain_timeout_s: float = 30.0
    autoscale_queue_high: int = 0
    autoscale_queue_low: int = 0
    autoscale_window_s: float = 10.0
    # cross-request prefix reuse (serve/prefix.py) + the frontend's
    # prefix-affinity routing over it (serve.prefix.* keys)
    prefix: bool = True
    prefix_budget_mb: float = 64.0
    prefix_affinity: bool = True
    prefix_fingerprint_tokens: int = 64
    # speculative decoding (serve/spec.py; serve.spec.* keys)
    spec: bool = False
    spec_max_draft: int = 4
    spec_draft_source: str = "auto"
    # quantized serving (serve.quant.* keys): block-scaled KV cache and
    # optionally int8 weight-only decode matmuls
    quant: bool = False
    quant_kv_dtype: str = "int8"
    quant_weights: bool = False

    @classmethod
    def from_config(cls, config: TonyConfig) -> "GangSettings":
        return cls(
            hosts=config.get_int(Keys.SERVE_GANG_HOSTS, 2),
            job_type=config.get_str(Keys.SERVE_GANG_JOB_TYPE, "decode"),
            model=config.get_str(Keys.SERVE_GANG_MODEL, "tiny"),
            seed=config.get_int(Keys.SERVE_GANG_SEED, 0),
            slots=config.get_int(Keys.SERVE_GANG_SLOTS, 4),
            max_len=config.get_int(Keys.SERVE_GANG_MAX_LEN, 0),
            max_queue=config.get_int(Keys.SERVE_GANG_MAX_QUEUE, 16),
            shard=config.get_bool(Keys.SERVE_GANG_SHARD, False),
            chunk_tokens=config.get_int(Keys.SERVE_CHUNK_TOKENS, 0),
            prefill_hosts=config.get_int(Keys.SERVE_POOL_PREFILL_HOSTS, 0),
            prefill_job_type=config.get_str(
                Keys.SERVE_POOL_PREFILL_JOB_TYPE, "prefill"
            ),
            handoff_min_tokens=config.get_int(
                Keys.SERVE_POOL_HANDOFF_MIN_TOKENS, 64
            ),
            frontend_max_inflight=config.get_int(
                Keys.SERVE_GANG_MAX_INFLIGHT, 64
            ),
            max_replays=config.get_int(Keys.SERVE_GANG_MAX_REPLAYS, 3),
            ttft_budget_s=config.get_float(Keys.SERVE_GANG_TTFT_BUDGET_S, 0.0),
            drain_timeout_s=config.get_float(
                Keys.SERVE_GANG_DRAIN_TIMEOUT_S, 30.0
            ),
            autoscale_queue_high=config.get_int(
                Keys.SERVE_GANG_AUTOSCALE_HIGH, 0
            ),
            autoscale_queue_low=config.get_int(Keys.SERVE_GANG_AUTOSCALE_LOW, 0),
            autoscale_window_s=config.get_float(
                Keys.SERVE_GANG_AUTOSCALE_WINDOW_S, 10.0
            ),
            prefix=config.get_bool(Keys.SERVE_PREFIX_ENABLED, True),
            prefix_budget_mb=config.get_float(
                Keys.SERVE_PREFIX_BUDGET_MB, 64.0
            ),
            prefix_affinity=config.get_bool(Keys.SERVE_PREFIX_AFFINITY, True),
            prefix_fingerprint_tokens=config.get_int(
                Keys.SERVE_PREFIX_FINGERPRINT_TOKENS, 64
            ),
            spec=config.get_bool(Keys.SERVE_SPEC_ENABLED, False),
            spec_max_draft=config.get_int(Keys.SERVE_SPEC_MAX_DRAFT, 4),
            spec_draft_source=config.get_str(
                Keys.SERVE_SPEC_DRAFT_SOURCE, "auto"
            ),
            quant=config.get_bool(Keys.SERVE_QUANT_ENABLED, False),
            quant_kv_dtype=config.get_str(
                Keys.SERVE_QUANT_KV_DTYPE, "int8"
            ),
            quant_weights=config.get_bool(Keys.SERVE_QUANT_WEIGHTS, False),
        )

    def to_json(self) -> str:
        from dataclasses import asdict

        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, blob: str) -> "GangSettings":
        return cls(**json.loads(blob))


def build_gang_engine(settings: GangSettings, pool: str = "decode") -> "Engine":
    """Deterministic per-host engine: same seed -> same weights on every
    replica, so routing (and replay) is host-agnostic. With
    ``serve.gang.shard`` the params shard over the host's local devices
    via the default mesh + the model's logical axes — the same
    parallel/mesh.py + parallel/sharding.py path the trainer uses."""
    import jax

    from tony_tpu.models.llama import LlamaConfig, init_params, logical_axes
    from tony_tpu.serve.engine import Engine, ServeConfig

    preset = getattr(LlamaConfig, settings.model, None)
    if preset is None or not callable(preset):
        raise ValueError(
            f"serve.gang.model {settings.model!r} is not a LlamaConfig preset"
        )
    cfg = preset()
    params = init_params(jax.random.key(settings.seed), cfg)
    if settings.shard and len(jax.devices()) > 1:
        from tony_tpu.parallel.mesh import build_mesh, default_shape
        from tony_tpu.parallel.sharding import tree_shardings

        n = len(jax.devices())
        mesh = build_mesh(default_shape(n, tp=n))
        params = jax.device_put(params, tree_shardings(logical_axes(cfg), mesh))
    return Engine(
        params, cfg,
        ServeConfig(
            slots=settings.slots, max_len=settings.max_len,
            max_queue=settings.max_queue, prefix=settings.prefix,
            prefix_budget_mb=settings.prefix_budget_mb,
            spec=settings.spec, spec_max_draft=settings.spec_max_draft,
            spec_draft_source=settings.spec_draft_source,
            quant_kv=settings.quant_kv_dtype if settings.quant else "",
            quant_weights=settings.quant and settings.quant_weights,
            chunk_tokens=settings.chunk_tokens,
            pool=pool,
        ),
    )


class DecodeHostService(ServeRpcServicer):
    """ServeRpc surface of one decode host (see module docstring).

    ``engine_factory`` defers engine construction to the engine thread
    (and rebuilds it on a recycling drain), so params/compiles never live
    on an RPC thread.
    """

    # engine-loop idle poll: long enough to sleep an idle host, short
    # enough that a fresh submission starts prefilling promptly
    _IDLE_WAIT_S = 0.05

    # serve-host series cadence over the AM metrics RPC: the fleet rollup
    # and `tony top`'s per-host rows come from these pushes (the same
    # heartbeat-path channel fit() uses), so a decode host is as visible
    # as a trainer
    _PUSH_INTERVAL_S = 2.0

    def __init__(self, engine_factory: Callable[[], Engine], host_id: str,
                 drain_timeout_s: float = 30.0, pool: str = "decode"):
        self._engine_factory = engine_factory
        self.host_id = host_id
        self.pool = pool
        self._drain_timeout_s = drain_timeout_s
        self._mailbox: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._draining = False
        self._started = threading.Event()
        self._start_error: BaseException | None = None
        # stats push AM-ward (obs/reporter.py: bounded queue + daemon
        # drain — an AM stall can never block the engine loop); inert
        # outside a tony job (no TONY_AM_ADDR)
        from tony_tpu.obs.reporter import MetricsReporter

        self._reporter = MetricsReporter()
        self._last_push = 0.0
        # live per-request plumbing, owned by the engine thread; the lock
        # only guards the dict shape (handler threads read membership for
        # stats), never any blocking work
        self._streams_lock = threading.Lock()
        self._streams: dict[int, "_StreamState"] = {}
        self.engine: Engine | None = None
        self._thread = threading.Thread(
            target=self._engine_loop, daemon=True, name="decode-engine"
        )
        self._thread.start()

    # --- engine thread --------------------------------------------------------

    def _engine_loop(self) -> None:
        try:
            self.engine = self._engine_factory()
        except BaseException as e:  # surface build failures to start()
            self._start_error = e
            self._started.set()
            raise
        self._started.set()
        eng = self.engine
        while not self._stop.is_set():
            eng = self._apply_mailbox(eng)
            with self._streams_lock:
                idle = not self._streams
            if idle and not (eng.queue_depth or eng.n_live):
                # nothing in flight: block on the mailbox instead of
                # spinning the decode step against an empty engine. The
                # stats push still ticks — an IDLE host must read as
                # fresh-and-empty on `tony top`, not as stale
                self._push_stats(eng)
                try:
                    item = self._mailbox.get(timeout=self._IDLE_WAIT_S)
                except queue.Empty:
                    continue
                eng = self._handle_item(eng, item)
                continue
            eng.step()
            self._publish(eng)
            self._push_stats(eng)
        eng.close()

    def _push_stats(self, eng: Engine, force: bool = False) -> None:
        """Throttled DecodeStats push to the AM + a series scrape
        (engine thread only). The scrape here is FORCED, not
        stride-counted: this path already ticks at the 2s push throttle,
        and a stride on top of it would let an idle-but-healthy host's
        journal age past `tony top`'s stale threshold (stride x
        throttle = ~32s > 30s) — an idle host must read as
        fresh-and-empty, never as stale."""
        now = time.monotonic()
        if not force and now - self._last_push < self._PUSH_INTERVAL_S:
            return
        self._last_push = now
        from tony_tpu.obs import series

        recorder = series.active_recorder()
        if recorder is not None:
            recorder.force_sample()
        if self._reporter.active:
            self._reporter.push(eng.stats_snapshot())

    def _apply_mailbox(self, eng: Engine) -> Engine:
        while True:
            try:
                item = self._mailbox.get_nowait()
            except queue.Empty:
                return eng
            eng = self._handle_item(eng, item)

    def _handle_item(self, eng: Engine, item: tuple) -> Engine:
        from tony_tpu.serve.engine import AdmissionRejected

        kind = item[0]
        if kind == "submit":
            _, req, stream = item
            try:
                erid = eng.submit(req)
            except AdmissionRejected as e:
                stream.reject("rejected", str(e))
                return eng
            except ValueError as e:
                # oversized prompt/budget: deterministic — the same request
                # fails on every host, so the frontend must not retry it
                stream.reject("invalid", str(e))
                return eng
            with self._streams_lock:
                self._streams[erid] = stream
        elif kind == "recycle":
            _, done = item
            log.warning("%s: recycling engine (fresh KV state)", self.host_id)
            eng.close()
            self.engine = eng = self._engine_factory()
            done.set()
        elif kind == "call":
            # generic engine-thread closure (handoff export/adopt): the RPC
            # handler blocks on `res`, the engine stays single-threaded
            _, fn, res = item
            try:
                res.put(("ok", fn(eng)))
            except BaseException as e:
                res.put(("err", e))
        return eng

    def _call_on_engine(self, fn, timeout_s: float = 120.0):
        """Run ``fn(engine)`` on the engine thread; raise what it raises.
        Handler-thread side of the "call" mailbox op."""
        res: queue.Queue = queue.Queue()
        self._mailbox.put(("call", fn, res))
        try:
            kind, val = res.get(timeout=timeout_s)
        except queue.Empty:
            raise TimeoutError("engine call timed out") from None
        if kind == "err":
            raise val
        return val

    def _publish(self, eng: Engine) -> None:
        """Push newly decoded tokens to each live stream; close finished
        ones. Runs on the engine thread right after each step."""
        with self._streams_lock:
            live = list(self._streams.items())
        finished = []
        for erid, stream in live:
            comp = eng.completion_of(erid)
            if comp is None:
                continue
            stream.push(comp)
            if comp.finish_reason:
                finished.append(erid)
        if finished:
            for erid in finished:
                eng.take_completion(erid)
            with self._streams_lock:
                for erid in finished:
                    self._streams.pop(erid, None)

    # --- RPC handlers (run on server threads; engine untouched) ---------------

    def start(self, timeout_s: float = 120.0) -> None:
        """Block until the engine thread built its engine (or raise its
        build error) — callers bind the RPC port first, so registration
        order stays executor-driven."""
        if not self._started.wait(timeout_s):
            raise TimeoutError("engine build did not finish in time")
        if self._start_error is not None:
            raise RuntimeError(
                f"engine build failed: {self._start_error!r}"
            ) from self._start_error

    def Generate(self, request, context):  # noqa: N802 (rpc casing)
        if self._draining or self._stop.is_set():
            yield pb.TokenChunk(
                rid=request.rid, done=True, finish_reason="draining",
                message=f"{self.host_id} is draining",
            )
            return
        from tony_tpu.serve.engine import Request

        req = Request(
            prompt=list(request.prompt),
            max_new_tokens=request.max_new_tokens or 32,
            temperature=request.temperature,
            top_k=request.top_k,
            top_p=request.top_p,
            eos_id=request.eos_id if request.eos_id >= 0 else None,
            rng=int(request.rng_seed),
        )
        # request.skip_tokens is deliberately ignored: the frontend always
        # replays the FULL stream so it can verify the regenerated prefix
        # against what it already delivered (the replay_consistent
        # evidence) — resume-without-verify would silently skip that check
        stream = _StreamState(request.rid)
        self._mailbox.put(("submit", req, stream))
        yield from stream.chunks(context)

    def DecodeStats(self, request, context):  # noqa: N802
        eng = self.engine
        with self._streams_lock:
            streaming = len(self._streams)
        pending = self._mailbox.qsize()
        if eng is None:
            return pb.DecodeStatsResponse(
                host_id=self.host_id, draining=self._draining,
                in_flight=pending, pool=self.pool,
            )
        # ONE stats surface (Engine.stats_snapshot): the RPC, the series
        # recorder, and the AM push all read the same snapshot — the RPC
        # never walks private engine state
        snap = eng.stats_snapshot()
        return pb.DecodeStatsResponse(
            host_id=self.host_id,
            slots=int(snap["slots"]),
            live_slots=int(snap["live_slots"]),
            queue_depth=int(snap["queue_depth"]) + pending,
            in_flight=streaming + pending,
            generated_tokens=int(snap["generated_tokens"]),
            rejected_total=int(snap["rejected_total"]),
            draining=self._draining,
            occupancy=snap["occupancy"],
            pool=self.pool,
        )

    def Prefill(self, request, context):  # noqa: N802
        """Disaggregated-prefill entry (frontend -> prefill host): run the
        prompt's prefill here, then ship the finished full blocks to the
        decode host named in ``request.target`` via ShipBlocks. The 1-token
        Generate both executes the prefill and registers the prompt in this
        host's prefix store, which is what export reads."""
        t0 = time.monotonic()
        if self._draining or self._stop.is_set():
            return pb.PrefillResponse(
                ok=False, message=f"{self.host_id} is draining"
            )
        from tony_tpu.serve.engine import Request

        req = Request(
            prompt=list(request.prompt), max_new_tokens=1,
            rng=int(request.rng_seed),
        )
        stream = _StreamState(request.rid)
        self._mailbox.put(("submit", req, stream))
        for chunk in stream.chunks(context):
            if chunk.done and chunk.finish_reason not in ("eos", "length"):
                return pb.PrefillResponse(
                    ok=False,
                    message=chunk.message or chunk.finish_reason,
                )
            if chunk.done:
                break
        out = self._call_on_engine(
            lambda eng: eng.export_prefix_blocks(list(request.prompt))
        )
        if out is None:
            return pb.PrefillResponse(
                ok=False, message="no full blocks to ship"
            )
        covered, payload = out
        from tony_tpu.serve.cache import pack_payload

        packed = pack_payload(payload)
        ship = pb.ShipBlocksRequest(
            rid=request.rid, src_host=self.host_id, tokens=list(covered),
            n_blocks=payload.n_blocks, block=int(payload.k.shape[3]),
            dtype=packed["dtype"], shape=packed["shape"],
            k=packed["k"], v=packed["v"],
            k_scale=packed.get("k_scale", b""),
            v_scale=packed.get("v_scale", b""),
        )
        # chaos seam: a fault here (die/hang) models a prefill host lost
        # mid-handoff — blocks exported but never adopted by the target
        chaos_hook("serve.handoff", rid=request.rid, target=request.target)
        from tony_tpu.rpc.service import ServeRpcClient

        try:
            with ServeRpcClient(request.target) as cli:
                resp = cli.ship_blocks(ship)
        except Exception as e:
            return pb.PrefillResponse(
                ok=False, shipped=payload.n_blocks,
                bytes=payload.nbytes,
                ms=(time.monotonic() - t0) * 1e3,
                message=f"ship to {request.target} failed: {e}",
            )
        return pb.PrefillResponse(
            ok=resp.ok, shipped=payload.n_blocks, adopted=resp.adopted,
            freed=resp.freed, bytes=payload.nbytes,
            ms=(time.monotonic() - t0) * 1e3, message=resp.message,
        )

    def ShipBlocks(self, request, context):  # noqa: N802
        """Adopt a shipped block payload into this host's pool + prefix
        store (decode side of the handoff). Malformed or mismatched
        payloads are refused — never adopted as garbage."""
        from tony_tpu.serve.cache import unpack_payload

        try:
            payload = unpack_payload(
                bytes(request.k), bytes(request.v), list(request.shape),
                request.dtype, bytes(request.k_scale), bytes(request.v_scale),
            )
        except ValueError as e:
            return pb.ShipBlocksResponse(ok=False, message=str(e))
        toks = [int(t) for t in request.tokens]
        try:
            adopted, freed = self._call_on_engine(
                lambda eng: eng.adopt_blocks(toks, payload)
            )
        except (ValueError, RuntimeError) as e:
            return pb.ShipBlocksResponse(ok=False, message=str(e))
        trace.instant(
            "serve.adopt", host=self.host_id, rid=request.rid,
            src=request.src_host, adopted=adopted, freed=freed,
        )
        return pb.ShipBlocksResponse(ok=True, adopted=adopted, freed=freed)

    def Drain(self, request, context):  # noqa: N802
        """Rolling-restart seam: stop admitting, let live slots finish
        (the KV state drains as requests complete), optionally recycle the
        engine, then return to service."""
        timeout_s = max(request.timeout_s or self._drain_timeout_s, 0.1)
        log.warning("%s: drain requested (timeout %.1fs, recycle=%s)",
                    self.host_id, timeout_s, request.recycle)
        self._draining = True
        trace.instant("serve.drain", host=self.host_id, recycle=request.recycle)
        deadline = time.monotonic() + timeout_s
        try:
            while time.monotonic() < deadline:
                with self._streams_lock:
                    streaming = len(self._streams)
                if streaming == 0 and self._mailbox.qsize() == 0:
                    break
                time.sleep(self._IDLE_WAIT_S)
            with self._streams_lock:
                remaining = len(self._streams)
            drained = remaining == 0 and self._mailbox.qsize() == 0
            if drained and request.recycle and not self._stop.is_set():
                done = threading.Event()
                self._mailbox.put(("recycle", done))
                drained = done.wait(timeout=max(deadline - time.monotonic(), 60.0))
        finally:
            self._draining = False
        return pb.DrainResponse(drained=drained, remaining=remaining)

    def shutdown(self) -> None:
        self._stop.set()
        with self._streams_lock:
            streams = list(self._streams.values())
            self._streams.clear()
        for s in streams:
            s.reject("error", "host shutting down")
        self._thread.join(timeout=30.0)
        self._reporter.close(timeout=2.0)


class _StreamState:
    """Bridge between the engine thread (producer) and one Generate RPC
    handler (consumer): tokens flow through a queue."""

    def __init__(self, rid: str):
        self.rid = rid
        self._sent = 0
        self._q: queue.Queue = queue.Queue()

    # producer side (engine thread)
    def push(self, comp) -> None:
        toks = comp.tokens[self._sent:]
        if toks:
            self._sent += len(toks)
            self._q.put(("tokens", list(toks), comp.prompt_len))
        if comp.finish_reason:
            self._q.put(("done", comp.finish_reason, comp.prompt_len))

    def reject(self, reason: str, message: str) -> None:
        self._q.put(("end", reason, message))

    # consumer side (RPC handler thread)
    def chunks(self, context):
        while True:
            try:
                item = self._q.get(timeout=300.0)
            except queue.Empty:
                yield pb.TokenChunk(
                    rid=self.rid, done=True, finish_reason="error",
                    message="decode stalled (no tokens for 300s)",
                )
                return
            kind = item[0]
            if kind == "tokens":
                _, toks, plen = item
                yield pb.TokenChunk(rid=self.rid, tokens=toks, prompt_len=plen)
            elif kind == "done":
                _, reason, plen = item
                yield pb.TokenChunk(
                    rid=self.rid, done=True, finish_reason=reason,
                    prompt_len=plen,
                )
                return
            else:  # "end": rejected / shutdown
                _, reason, message = item
                yield pb.TokenChunk(
                    rid=self.rid, done=True, finish_reason=reason,
                    message=message,
                )
                return


def _own_port() -> int:
    """The data port this host must serve on: the executor reserved it,
    registered it with the AM, and the serve runtime exported it — the
    frontend discovers us through the AM's task table at exactly this
    port, so serving anywhere else is serving nowhere."""
    port = os.environ.get(ENV_SERVE_PORT, "")
    if port:
        return int(port)
    spec = json.loads(os.environ.get("TONY_CLUSTER_SPEC", "{}"))
    job = os.environ.get("TONY_JOB_NAME", "")
    idx = int(os.environ.get("TONY_TASK_INDEX", "0"))
    try:
        return int(spec[job][idx].rpartition(":")[2])
    except (KeyError, IndexError, ValueError):
        return 0


def _load_settings() -> GangSettings:
    blob = os.environ.get(ENV_SERVE_GANG, "")
    if blob:
        return GangSettings.from_json(blob)
    app_dir = os.environ.get("TONY_APP_DIR", "")
    with open(os.path.join(app_dir, "config.json")) as f:
        return GangSettings.from_config(TonyConfig.from_json(f.read()))


def main() -> int:
    """Worker entry: ``python -m tony_tpu.serve.gang`` inside a container."""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s SERVE %(levelname)s %(name)s: %(message)s",
    )
    trace.install_from_env()
    # arm the coordinated-profiling watcher BEFORE the engine builds (model
    # init + first compiles can take minutes): a `tony profile` broadcast
    # issued meanwhile is picked up the moment decode steps start
    from tony_tpu.obs import profile

    profile.install_from_env()
    settings = _load_settings()
    job_name = os.environ.get("TONY_JOB_NAME", settings.job_type)
    host_id = f"{job_name}:{os.environ.get('TONY_TASK_INDEX', '0')}"
    # pool membership comes from the container's task type: a heterogeneous
    # gang launches prefill_job_type containers next to decode ones, and the
    # same worker binary serves either side of the handoff
    pool = "prefill" if job_name == settings.prefill_job_type else "decode"
    service = DecodeHostService(
        lambda: build_gang_engine(settings, pool=pool), host_id,
        drain_timeout_s=settings.drain_timeout_s, pool=pool,
    )
    port = _own_port()
    with trace.span("serve.host_start", host=host_id, port=port):
        # the registered port is load-bearing (see _own_port); bounded
        # bind-with-retry rides out TIME_WAIT from a recycled predecessor
        server, bound = serve_rpc(service, port=port, bind_attempts=8)
        service.start()
    log.info("%s serving on :%d (model=%s slots=%d shard=%s)",
             host_id, bound, settings.model, settings.slots, settings.shard)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    log.info("%s: SIGTERM — draining and shutting down", host_id)
    service.shutdown()
    server.stop(grace=1.0).wait(timeout=5.0)
    trace.flush()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Serving: slot-batched continuous decoding + multi-host inference gangs
(docs/SERVE.md). serve.gang / serve.frontend are imported directly by
their users (`tony serve`, the gang worker entrypoint) — not re-exported
here — so importing the engine surface stays jax-only."""

from tony_tpu.serve.cache import BlockKVCache, create_cache, grow_cache, shrink_cache
from tony_tpu.serve.engine import (
    AdmissionRejected, Completion, Engine, Request, ServeConfig,
)

__all__ = [
    "AdmissionRejected", "BlockKVCache", "Completion", "Engine", "Request",
    "ServeConfig", "create_cache", "grow_cache", "shrink_cache",
]

"""Serving: slot-batched continuous decoding over a paged, prefix-shared
KV cache + multi-host inference gangs (docs/SERVE.md). serve.gang /
serve.frontend are imported directly by their users (`tony serve`, the
gang worker entrypoint) — not re-exported here — so importing the engine
surface stays jax-only."""

from tony_tpu.serve.cache import (
    BlockPool, PagedKVCache, create_cache, grow_cache, shrink_cache,
)
from tony_tpu.serve.engine import (
    AdmissionRejected, Completion, Engine, Request, ServeConfig,
)
from tony_tpu.serve.prefix import PrefixStore

__all__ = [
    "AdmissionRejected", "BlockPool", "Completion", "Engine",
    "PagedKVCache", "PrefixStore", "Request", "ServeConfig",
    "create_cache", "grow_cache", "shrink_cache",
]

"""Serving: slot-batched continuous decoding (docs/SERVE.md)."""

from tony_tpu.serve.cache import BlockKVCache, create_cache, grow_cache, shrink_cache
from tony_tpu.serve.engine import Completion, Engine, Request, ServeConfig

__all__ = [
    "BlockKVCache", "Completion", "Engine", "Request", "ServeConfig",
    "create_cache", "grow_cache", "shrink_cache",
]

"""Slot-batched continuous decoding: the serving engine.

The reference orchestrates training jobs only; serving "heavy traffic"
(ROADMAP north star) needs an inference loop that never idles the chip.
generate.py's old loop was the opposite of that: a static batch occupied the
whole decode scan until its *slowest* row finished, attention walked the full
``max_len`` cache every step, and K/V were repeat-expanded to ``n_heads``
width. This engine replaces all three:

- **Slots, not batches.** A static-shape decode batch of ``S`` slots runs
  under ONE jitted step (static shapes, no per-request compiles). A request
  owns a slot only while it is decoding; the moment it finishes (EOS or its
  token budget) the slot is freed and the admission queue refills it — the
  continuous batching of Orca/vLLM, with XLA-friendly static shapes.
- **Bucketed prefill.** Admission pads each prompt to a small set of bucket
  lengths, so prefill compiles once per bucket (bounded compile count), and
  projects only the prompt's last position through ``lm_head``
  (``forward_with_cache(last_index=...)``).
- **Paged block cache + native-GQA attention.** The KV cache is the
  refcounted physical-block pool of serve/cache.py with per-slot block
  tables (the engine plans them on the host, the decode step reads K/V
  through them — ops/decode_attention.py's paged form), sized to the
  active block count and read at native ``n_kv_heads`` width with
  per-slot lengths — decode cost scales with what is written, not
  ``max_len``.
- **Cross-request prefix reuse.** Admission matches each prompt against
  the radix prefix store (serve/prefix.py): matched full blocks map
  shared into the slot's table (refcounted, never written), a mid-block
  match gets a private copy-on-write block, and prefill computes only
  the unshared tail — attending the cached prefix K/V gathered from the
  pool, so TTFT and prefill FLOPs scale with the tail, not the prompt.
  Tail prefill is bitwise-identical to a full prefill on the same
  backend (row-independent matmuls + exactly-zero masked softmax terms),
  so engine-vs-generate parity holds with sharing live
  (tests/test_prefix.py).
- **Per-slot state.** Position, EOS, sampling parameters, and an rng stream
  ride per-slot arrays inside the jitted step, so heterogeneous requests
  (different temperatures, eos ids, budgets) share one compiled step. A
  request's tokens depend only on its own rng key — the same request
  submitted alone or into a busy engine samples identically
  (tests/test_serve.py parity).

Throughput/latency counters feed ``obs.metrics.DecodeMetrics`` (decode
tokens/s/chip, TTFT, slot occupancy). docs/SERVE.md has the architecture
notes and knob guide.
"""

from __future__ import annotations

import functools
import logging
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tony_tpu.models.llama import LlamaConfig, Params, rms_norm, rope_freqs
from tony_tpu.obs import hbm, health, profile, series, slo, trace
from tony_tpu.obs import compiles as compile_ledger
from tony_tpu.obs.metrics import DecodeMetrics
from tony_tpu.obs.registry import HistogramWindow, Registry, snapshot_to_app_dir
from tony_tpu.ops.decode_attention import decode_attention
from tony_tpu.ops.quant_mm import quant_matmul, quantize_weights
from tony_tpu.serve.cache import (
    SCRATCH_BLOCK, BlockPayload, BlockPool, PagedKVCache, block_bytes,
    blocks_for, create_cache, dequantize_values, export_blocks, grow_cache,
    kv_quant_spec, payload_compatible, quant_scatter_span, scatter_block_kv,
    shrink_cache, write_block,
)
from tony_tpu.serve.prefix import MatchResult, PrefixStore
from tony_tpu.serve.spec import (
    DRAFT_SOURCES, propose_drafts, verify_and_accept,
)

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (docs/SERVE.md "Knobs")."""

    # concurrent decode slots (the static batch width of the jitted step)
    slots: int = 8
    # longest prompt+generation admitted; 0 -> model.max_seq_len
    max_len: int = 0
    # KV cache block size: capacity grows/shrinks in multiples of this and
    # the decode kernel tiles the sequence by it
    kv_block: int = 64
    # prefill pad lengths; () -> powers of two from 16 up to max_len.
    # Prefill compiles once per bucket (the compile-count bound).
    prefill_buckets: tuple[int, ...] = ()
    # decode attention kernel: 'scan' (pure XLA, default) | 'pallas'
    # (TPU kernel, interpreted on CPU) — ops/decode_attention.py
    decode_impl: str = "scan"
    # static top-k slice width for sampling: per-request top_k clamps to
    # this, and top-p-only requests use it as the bounded nucleus candidate
    # set (generate.DEFAULT_NUCLEUS_K semantics)
    max_top_k: int = 64
    # release cache blocks when the live maximum drops below half the
    # capacity (each capacity change recompiles the decode step once)
    shrink: bool = True
    # bounded admission: submit() raises AdmissionRejected once this many
    # requests are queued (0 = unbounded, the pre-gang legacy). This is the
    # backpressure seam the gang frontend leans on — a host whose queue is
    # full must say so NOW so the router can pick a survivor, not absorb
    # work it will serve tail-latency-late. Rejections count into the
    # tony_serve_rejected_total registry counter.
    max_queue: int = 0
    # cross-request prefix reuse (serve/prefix.py): admission matches each
    # prompt against the radix store and prefills only the unshared tail;
    # matched blocks are shared copy-on-write. Off = every request pays a
    # full prefill (the pre-store behaviour; the paged cache layout is the
    # same either way).
    prefix: bool = True
    # HBM the store may pin for prefixes no live slot references; LRU
    # leaves evict beyond it (serve.prefix.budget_mb). 0 = bound only by
    # allocation pressure (the pool cap).
    prefix_budget_mb: float = 64.0
    # speculative decoding (serve/spec.py): each slot drafts up to
    # spec_max_draft tokens per step (radix-store longest extension, or
    # n-gram prompt-lookup over its own context) and ONE widened decode
    # step verifies them all — accepted drafts multiply tokens/step with
    # draw-for-draw identical output (docs/SERVE.md "Speculative
    # decoding"). With spec on, finished requests also register their
    # generated tokens' blocks into the prefix store (the draft corpus).
    spec: bool = False
    # draft tokens per slot per step (k; the verify step scores k+1
    # positions). One extra decode signature per (k, pool, attended).
    spec_max_draft: int = 4
    # 'auto' (store first, n-gram fallback) | 'prefix' | 'ngram'
    spec_draft_source: str = "auto"
    # quantized KV cache (serve/cache.py "Quantized pools"): '' = bf16
    # pools (off), 'int8' | 'fp8_e4m3' = block-scaled quantized pools —
    # writes quantize against a running per-block-per-head scale, both
    # decode kernels dequantize inline, and the slot budget roughly
    # doubles (serve/capacity.py max_slots_quant measures it).
    quant_kv: str = ""
    # int8 weight-only decode matmuls (ops/quant_mm.py): the engine keeps
    # the bf16 master params for prefill and decodes through a quantized
    # copy with per-output-channel scales. Only meaningful with decode
    # traffic; requires quant_kv unset or set independently (orthogonal
    # knobs under one serve.quant.* config group).
    quant_weights: bool = False
    # chunked prefill (serve.chunk_tokens; docs/SERVE.md "Disaggregated
    # serving"): a prompt whose unshared tail exceeds this many tokens
    # prefills in chunk_tokens-sized chunks through the restartable
    # tail-prefill path, ONE chunk per engine step — a long prompt can no
    # longer stall co-resident decode streams for a whole prefill (TPOT
    # stays bounded, its own TTFT degrades gracefully). Must be a
    # multiple of kv_block (chunks start block-aligned, so tail-prefill
    # compile signatures stay the bounded per-bucket set). 0 = off.
    chunk_tokens: int = 0
    # pool label this engine serves in ('decode' | 'prefill'): pure
    # observability — stats_snapshot/series/`tony top` carry it so a
    # disaggregated gang's two pools stay distinguishable in rollups
    pool: str = "decode"


class AdmissionRejected(RuntimeError):
    """submit() refused: the admission queue is at ServeConfig.max_queue."""


@dataclass
class Request:
    """One generation request (a prompt row plus sampling parameters)."""

    prompt: Sequence[int] | np.ndarray | jax.Array
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    eos_id: int | None = None
    # int seed, typed jax key, or raw uint32 key data; None -> keyed by
    # request id (deterministic per submission order)
    rng: Any = None


@dataclass
class Completion:
    """Result of one request: generated tokens (EOS included when hit)."""

    rid: int
    tokens: list[int] = field(default_factory=list)
    prompt_len: int = 0
    finish_reason: str = ""  # 'eos' | 'length'
    ttft_s: float = 0.0


@dataclass
class _ChunkedPrefill:
    """Host-side progress of one slot's chunked prefill: the slot owns
    its blocks (planned at admission) and advances ``pos`` by one chunk
    per engine step until the final chunk samples the first token."""

    rid: int
    req: Request
    prompt: np.ndarray
    pos: int          # tokens already written (prefix match + done chunks)
    key: Any          # the request's sampling key (spent by the FINAL chunk)
    t0: float         # admission start (TTFT spans the whole chunked prefill)


class _SlotState(NamedTuple):
    """Per-slot device state threaded through the jitted decode step."""

    last_tok: jax.Array   # [S] int32 — token to feed this step
    rng: jax.Array        # [S, 2] uint32 — per-slot rng stream (raw keys)
    temp: jax.Array       # [S] float32
    top_k: jax.Array      # [S] int32
    top_p: jax.Array      # [S] float32
    eos: jax.Array        # [S] int32, -1 = no eos
    done: jax.Array       # [S] bool — row has emitted eos
    live: jax.Array       # [S] bool — slot owned by a request


def _as_raw_key(rng: Any, rid: int) -> jnp.ndarray:
    """Normalise a request rng (seed | typed key | raw data) to uint32[2]."""
    if rng is None:
        rng = rid
    if isinstance(rng, int):
        return jax.random.key_data(jax.random.key(rng)).astype(jnp.uint32)
    arr = jnp.asarray(rng)
    if jnp.issubdtype(arr.dtype, jax.dtypes.prng_key):
        return jax.random.key_data(arr).astype(jnp.uint32)
    return arr.astype(jnp.uint32)


def _weak_stats_source(engine: "Engine", recorder, key: str):
    """A series source that does not own the engine: the closure holds a
    weakref, so an engine dropped without close() (failed construction,
    abandoned bench sweep) is collectable — and the first scrape after
    collection detaches the dead source instead of erroring forever."""
    ref = weakref.ref(engine)

    def source() -> dict:
        eng = ref()
        if eng is None:
            recorder.detach(key)
            return {}
        return eng.stats_snapshot(windowed=True)

    return source


def _default_buckets(max_len: int) -> tuple[int, ...]:
    out, b = [], 16
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


class Engine:
    """Continuous-batching decode engine over a block KV cache.

    Typical use::

        engine = Engine(params, cfg, ServeConfig(slots=8))
        rid = engine.submit(Request(prompt=..., max_new_tokens=64))
        completions = engine.run()         # drain queue + live slots

    ``submit``/``step`` can interleave (a driver can feed arrivals between
    steps — bench.py's mixed-arrival trace does); ``run`` just steps until
    everything drains. Single-process, one model replica; scale-out is
    replica-per-chip above this layer.
    """

    def __init__(self, params: Params, cfg: LlamaConfig, serve: ServeConfig):
        if cfg.is_moe:
            # forward_with_cache (the prefill path) has no expert FFN —
            # reject loudly instead of crashing at the first admission
            raise NotImplementedError(
                "serving MoE configs is not supported yet (prefill has no "
                "expert dispatch)"
            )
        self.params = params
        self.cfg = cfg
        max_len = serve.max_len or cfg.max_seq_len
        buckets = tuple(sorted(serve.prefill_buckets)) or _default_buckets(max_len)
        cap = blocks_for(max_len, serve.kv_block) * serve.kv_block
        if buckets[-1] > cap:
            # an oversized bucket passes submit() validation but cannot be
            # inserted into a cache capped at max_len — reject at build time
            raise ValueError(
                f"prefill bucket {buckets[-1]} exceeds the cache capacity "
                f"ceiling {cap} (max_len {max_len} rounded up to kv_block)"
            )
        if serve.spec_draft_source not in DRAFT_SOURCES:
            raise ValueError(
                f"spec_draft_source {serve.spec_draft_source!r} not in "
                f"{DRAFT_SOURCES}"
            )
        if serve.spec and serve.spec_max_draft < 1:
            raise ValueError("spec_max_draft must be >= 1 with spec on")
        if serve.quant_kv:
            kv_quant_spec(serve.quant_kv)  # validate the knob at build time
        if serve.chunk_tokens and serve.chunk_tokens % serve.kv_block:
            raise ValueError(
                f"chunk_tokens {serve.chunk_tokens} must be a multiple of "
                f"kv_block {serve.kv_block} (chunks start block-aligned so "
                "tail-prefill signatures stay bounded)"
            )
        self.serve = ServeConfig(
            slots=serve.slots, max_len=max_len, kv_block=serve.kv_block,
            prefill_buckets=buckets, decode_impl=serve.decode_impl,
            max_top_k=serve.max_top_k, shrink=serve.shrink,
            max_queue=serve.max_queue, prefix=serve.prefix,
            prefix_budget_mb=serve.prefix_budget_mb, spec=serve.spec,
            spec_max_draft=serve.spec_max_draft,
            spec_draft_source=serve.spec_draft_source,
            quant_kv=serve.quant_kv, quant_weights=serve.quant_weights,
            chunk_tokens=serve.chunk_tokens, pool=serve.pool,
        )
        S = self.serve.slots
        try:
            # tokens/s/chip divides by the devices actually backing the
            # model (a sharded-params engine must not overreport per-chip)
            n_chips = max(1, len(jax.tree.leaves(params)[0].sharding.device_set))
        except Exception:
            n_chips = 1
        self.metrics = DecodeMetrics(n_chips=n_chips)
        # paged pool + per-slot block tables (serve/cache.py): the table is
        # planned on the host (np mirror) and uploaded as a [S, attended]
        # device slice only when it changed — steady-state decode reuses
        # the cached device copy
        B = self.serve.kv_block
        self._m_total = blocks_for(max_len, B)
        blk_bytes = block_bytes(cfg, B, quant_kv=self.serve.quant_kv)
        self._blk_bytes = blk_bytes
        self.metrics.kv_bytes_per_token = blk_bytes / B
        budget_bytes = int(self.serve.prefix_budget_mb * 2**20)
        budget_blocks = (
            max(1, -(-budget_bytes // blk_bytes)) if budget_bytes
            else S * self._m_total
        )
        # the pool never needs more than every slot at max_len plus the
        # store's budget (plus scratch) — growth stops here, eviction
        # takes over
        self._pool_cap = 1 + S * self._m_total + (
            budget_blocks if self.serve.prefix else 0
        )
        p0 = max(2, min(1 + S, self._pool_cap))
        self._p0 = p0
        self._pool = BlockPool(p0)
        self.cache = create_cache(cfg, S, p0, B, quant_kv=self.serve.quant_kv)
        # quantized pools: block ids whose scale rows need zeroing before
        # the next device write (allocation-time stale-scale reset — a
        # reused block must not inherit its previous tenant's scale)
        self._fresh_scale: list[int] = []
        # int8 weight-only decode: quantize ONCE at build; prefill keeps
        # the bf16 master params, decode/spec steps read the quantized copy
        self._qparams = (
            _quantize_decode_params(params) if self.serve.quant_weights
            else None
        )
        self._dec_params = self._qparams if self._qparams is not None else params
        self._store: PrefixStore | None = None
        if self.serve.prefix:
            self._store = PrefixStore(
                block=B, block_bytes=blk_bytes, budget_bytes=budget_bytes
            )
        self._table = np.zeros((S, self._m_total), np.int32)
        self._slot_blocks = [0] * S
        self._attended = 1
        self._table_dev = jnp.asarray(self._table[:, :1])
        self._table_dirty = False
        self._cow_copies = 0
        self.state = _SlotState(
            last_tok=jnp.zeros((S,), jnp.int32),
            rng=jnp.zeros((S, 2), jnp.uint32),
            temp=jnp.zeros((S,), jnp.float32),
            top_k=jnp.zeros((S,), jnp.int32),
            top_p=jnp.zeros((S,), jnp.float32),
            eos=jnp.full((S,), -1, jnp.int32),
            done=jnp.zeros((S,), bool),
            live=jnp.zeros((S,), bool),
        )
        self._queue: deque[tuple[int, Request]] = deque()
        # slots mid-chunked-prefill (slot -> progress): they hold their
        # blocks but stay out of the decode batch until the final chunk
        self._chunking: dict[int, _ChunkedPrefill] = {}
        self._completions: dict[int, Completion] = {}
        self._slot_rid: list[int | None] = [None] * S
        self._slot_remaining: list[int] = [0] * S
        self._slot_len: list[int] = [0] * S       # host mirror of lengths
        self._submit_t: dict[int, float] = {}
        self._next_rid = 0
        self._prefill_fns: dict[int, Any] = {}
        self._tail_fns: dict[tuple[int, int], Any] = {}
        self._decode_fns: dict[tuple[int, int], Any] = {}
        # speculative verify steps, same (pool, attended) signature ladder
        # at the engine's fixed draft width k (one extra signature per
        # ladder rung — the bounded-compile contract carries over)
        self._spec_fns: dict[tuple[int, int], Any] = {}
        # host token context per slot (prompt + every emitted token, the
        # next input token last) — the draft sources read it; maintained
        # only with spec on
        self._slot_ctx: list[list[int]] = [[] for _ in range(S)]
        # trace/metrics spine: join the job's trace from the AM-exported
        # env (no-op outside a traced tony-tpu job, idempotent when the
        # user script armed it already), then per-request span handles
        # (queued -> prefill -> decode -> finish) and the TTFT/TPOT/
        # step-time distributions the portal /metrics endpoint serves
        # (docs/OBS.md catalogue). Per-engine registry: a recreated engine
        # (restart, bench sweep) reports its own distributions, not a
        # blend with its predecessor's
        trace.install_from_env()
        # HBM observatory + compile ledger: sampled memory counter tracks
        # from the decode loop, AOT decode compiles journaled with their
        # measured memory plans (obs/hbm.py, obs/compiles.py)
        hbm.install_from_env()
        # numerics sentinel (obs/health.py): when armed, the decode step
        # fuses per-slot logits-nonfinite counts + sampling entropy and
        # the engine feeds them to the async rule engine with per-request
        # attribution; disarmed, none of it is compiled in
        health.install_from_env()
        self._monitors = health.active_sentinel() is not None
        # live time-series (obs/series.py): the engine publishes its
        # stats_snapshot() as a scrape source — queue depth, occupancy,
        # windowed TTFT/TPOT quantiles — so the recorder (and the SLO
        # engine riding it) never walks private engine state. The source
        # itself attaches at the END of __init__ (after the registry it
        # reads exists) and holds only a weakref: an engine abandoned
        # without close() must not be pinned — params + KV cache — by the
        # process-global recorder forever.
        series.install_from_env()
        # coordinated profiling (obs/profile.py): a `tony profile` window
        # broadcast by the AM captures this host's decode steps too — the
        # maybe_capture seam rides step()
        profile.install_from_env()
        self._series = series.active_recorder()
        self._snap_window = HistogramWindow()   # since-last-scrape quantiles
        self._snap_prev: dict[str, float] = {}  # counter deltas (error rate)
        self._series_key = f"engine@{id(self):x}"
        self._ledger = compile_ledger.get_ledger()
        self._compiles_t0 = self._ledger.backend_compiles
        # engine-scoped watermark mark: close() reports THIS engine's peak
        # via the attribution rule, never the process's cumulative counter
        # (a train-then-serve process must not inherit the trainer's peak)
        watch = hbm.active_watch()
        self._hbm_mark = watch.mark() if watch is not None else None
        self._init_registry()
        self._queued_spans: dict[int, Any] = {}
        self._decode_spans: dict[int, Any] = {}
        self._first_tok_t: dict[int, float] = {}
        if self._series is not None:
            self._series.attach(
                self._series_key,
                _weak_stats_source(self, self._series, self._series_key),
            )

    # --- public API -----------------------------------------------------------

    def submit(self, req: Request) -> int:
        """Queue a request; returns its id (the key into run()'s result)."""
        # np.shape reads metadata only — no device transfer for jax arrays
        shape = np.shape(req.prompt)
        plen = int(shape[-1]) if shape else 0
        if plen < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {req.max_new_tokens} "
                "(prefill always samples the first token)"
            )
        if plen >= self.serve.max_len:
            # explicit and FIRST: an over-long prompt must fail with the
            # real reason (max_len), deterministically, at submit time —
            # never reach admission where it would wedge a slot. The gang
            # worker maps ValueError to a terminal "invalid" chunk, so the
            # frontend finishes the request instead of replaying it.
            raise ValueError(
                f"prompt length {plen} must be shorter than max_len "
                f"{self.serve.max_len} (at least one generated token must fit)"
            )
        if plen > max(self.serve.prefill_buckets):
            raise ValueError(
                f"prompt length {plen} exceeds the largest prefill bucket "
                f"{max(self.serve.prefill_buckets)}"
            )
        if plen + req.max_new_tokens > self.serve.max_len:
            raise ValueError(
                f"prompt {plen} + max_new_tokens {req.max_new_tokens} "
                f"exceeds max_len {self.serve.max_len}"
            )
        if self.serve.max_queue and len(self._queue) >= self.serve.max_queue:
            # an explicit reject, never silent queueing past the bound: the
            # caller (gang frontend, a driver) owns the backpressure policy
            self._c_rejected.inc()
            raise AdmissionRejected(
                f"admission queue full ({len(self._queue)} >= max_queue "
                f"{self.serve.max_queue})"
            )
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append((rid, req))
        self._submit_t[rid] = time.perf_counter()
        self._g_queue.set(len(self._queue))
        tracer = trace.active_tracer()
        if tracer is not None:
            # queue-wait span: starts now, ends when the request is slotted
            self._queued_spans[rid] = tracer.span(
                "serve.queued", rid=rid, prompt_len=plen
            )
        return rid

    @property
    def n_live(self) -> int:
        return sum(1 for r in self._slot_rid if r is not None)

    @property
    def n_decoding(self) -> int:
        """Live slots actually in the decode batch (a slot mid-chunked-
        prefill holds its blocks but does not decode yet)."""
        return sum(
            1 for s, r in enumerate(self._slot_rid)
            if r is not None and s not in self._chunking
        )

    @property
    def queue_depth(self) -> int:
        """Requests admitted but not yet slotted."""
        return len(self._queue)

    @property
    def rejected_total(self) -> int:
        """Submissions refused by bounded admission since the last
        reset_metrics()."""
        return int(self._c_rejected.value)

    def stats_snapshot(self, windowed: bool = False) -> dict[str, float]:
        """Cheap host-side stats: queue depth, slot occupancy, token/
        request counters, and TTFT/TPOT/step-time quantiles. ONE public
        surface for every consumer — the series recorder, the gang
        ``DecodeStats`` RPC, and the gang worker's AM metrics push — so
        none of them walks private engine state, and none syncs a device
        (everything here is host counters).

        ``windowed=True`` reports quantiles *since the previous windowed
        call* (the series recorder's live view: p99 TTFT now, not blended
        with warmup); the default reports run-cumulative quantiles (the
        RPC/stats view). The windowed state is single-consumer by design
        — only the engine's own series source uses it."""
        snap: dict[str, float] = {
            "queue_depth": float(len(self._queue)),
            "live_slots": float(self.n_live),
            "slots": float(self.serve.slots),
            "occupancy": round(self.n_live / max(self.serve.slots, 1), 4),
            "generated_tokens": float(self._c_tokens.value),
            "requests_finished": float(self._c_finished.value),
            "rejected_total": float(self._c_rejected.value),
            # decode tokens emitted per decode step: 1.0 autoregressive,
            # > 1 when speculative drafts land (`tony top`'s tok/st)
            "tokens_per_step": round(self.metrics.tokens_per_step, 4),
            # HBM per cached token (block bytes / block positions): the
            # quantized-serving capacity win, live (`tony top`'s kvB/t)
            "kv_bytes_per_token": round(self.metrics.kv_bytes_per_token, 2),
            # pool label (disaggregated gangs): a string, so it rides the
            # series journal but the numeric AM metrics push drops it —
            # AM-rollup consumers derive the pool from the task type instead
            "pool": self.serve.pool,
        }
        if self._chunking:
            # slots mid-chunked-prefill: occupied but not decoding yet
            snap["chunking_slots"] = float(len(self._chunking))
        shipped = float(self._c_handoff_shipped.value)
        adopted = float(self._c_handoff_adopted.value)
        freed = float(self._c_handoff_freed.value)
        if shipped or adopted or freed:
            # blockwise handoff accounting: on a healthy host every
            # shipped block lands adopted or freed SOMEWHERE — the chaos
            # handoff-no-block-leak invariant audits the frontend's
            # per-request ledger view of these
            snap["handoff_shipped_blocks"] = shipped
            snap["handoff_adopted_blocks"] = adopted
            snap["handoff_freed_blocks"] = freed
        if self.serve.quant_kv:
            resident = float(self._pool.n_blocks * self._blk_bytes)
            snap["quant_pool_resident_bytes"] = resident
            self._g_quant_resident.set(resident)
        if self.serve.spec:
            snap["draft_accept_rate"] = round(
                self.metrics.draft_accept_rate, 4
            )
            snap["spec_rollbacks"] = float(self.metrics.spec_rollbacks)
        if self._store is not None:
            # cross-request reuse health (cumulative): hit rate feeds the
            # series recorder, the portal, and `tony top`'s hit% column
            snap.update(self._store.stats())
            snap["pool_blocks"] = float(self._pool.n_blocks)
        for hist, prefix in (
            (self._h_ttft, "ttft"),
            (self._h_tpot, "tpot"),
            (self._h_step, "decode_step"),
        ):
            if windowed:
                d = self._snap_window.delta(hist)
                if d["count"]:
                    snap[f"{prefix}_p50_s"] = round(d["p50"], 4)
                    snap[f"{prefix}_p99_s"] = round(d["p99"], 4)
                    snap[f"{prefix}_n"] = d["count"]
            elif hist.count:
                snap[f"{prefix}_p50_s"] = round(hist.quantile(0.5), 4)
                snap[f"{prefix}_p99_s"] = round(hist.quantile(0.99), 4)
                snap[f"{prefix}_n"] = float(hist.count)
        if windowed:
            # windowed serve error rate: explicit rejections over requests
            # resolved in the window (the slo.error_rate input); the
            # engine itself has no other error class — relay/transport
            # errors are the frontend ledger's to count
            rej = snap["rejected_total"] - self._snap_prev.get("rejected", 0.0)
            fin = snap["requests_finished"] - self._snap_prev.get("finished", 0.0)
            self._snap_prev["rejected"] = snap["rejected_total"]
            self._snap_prev["finished"] = snap["requests_finished"]
            if rej + fin > 0:
                snap["error_rate"] = round(rej / (rej + fin), 4)
        return snap

    def _init_registry(self) -> None:
        reg = self.registry = Registry()
        self._h_ttft = reg.histogram("tony_ttft_seconds",
                                     "request submit -> first sampled token")
        self._h_tpot = reg.histogram("tony_tpot_seconds",
                                     "mean per-token latency after the first")
        self._h_step = reg.histogram("tony_decode_step_seconds",
                                     "one engine decode step (all live slots)")
        self._g_queue = reg.gauge("tony_queue_depth",
                                  "requests admitted but not yet slotted")
        self._c_tokens = reg.counter("tony_generated_tokens_total",
                                     "tokens sampled (prefill + decode)")
        self._c_finished = reg.counter("tony_requests_finished_total",
                                       "requests completed (eos or budget)")
        self._c_rejected = reg.counter(
            "tony_serve_rejected_total",
            "submissions rejected by bounded admission (queue at max_queue)",
        )
        self._c_prefix_hit = reg.counter(
            "tony_serve_prefix_hit_tokens_total",
            "prompt tokens served from the prefix store (no re-prefill)",
        )
        self._c_prompt_tokens = reg.counter(
            "tony_serve_prompt_tokens_total",
            "prompt tokens admitted (the prefix hit-rate denominator)",
        )
        self._g_prefix_bytes = reg.gauge(
            "tony_serve_prefix_resident_bytes",
            "HBM pinned by prefix-store block references",
        )
        self._g_prefix_nodes = reg.gauge(
            "tony_serve_prefix_nodes", "radix nodes resident in the store",
        )
        self._c_draft_prop = reg.counter(
            "tony_serve_draft_proposed_total",
            "speculative draft tokens proposed (serve/spec.py)",
        )
        self._c_draft_acc = reg.counter(
            "tony_serve_draft_accepted_total",
            "speculative draft tokens accepted (target sample agreed)",
        )
        self._g_kv_bpt = reg.gauge(
            "tony_serve_kv_bytes_per_token",
            "HBM per cached token (quantized pools store int8/fp8 + scales)",
        )
        self._g_kv_bpt.set(self._blk_bytes / self.serve.kv_block)
        self._g_quant_resident = reg.gauge(
            "tony_serve_quant_pool_resident_bytes",
            "HBM resident in the quantized KV pool (payload + scale rows)",
        )
        self._c_handoff_shipped = reg.counter(
            "tony_serve_handoff_shipped_blocks_total",
            "physical blocks exported for a blockwise KV handoff",
        )
        self._c_handoff_adopted = reg.counter(
            "tony_serve_handoff_adopted_blocks_total",
            "shipped blocks adopted into this pool (prefix-store owned)",
        )
        self._c_handoff_freed = reg.counter(
            "tony_serve_handoff_freed_blocks_total",
            "shipped blocks freed on arrival (prefix already resident)",
        )

    def reset_metrics(self) -> None:
        """Fresh throughput/latency counters (e.g. after a warmup trace
        that paid the compiles); compile counts persist — they describe
        the engine, not the trace. The registry histograms reset too, or
        close()'s TTFT/TPOT quantiles and the job-history snapshot would
        blend warmup compile time into the measured trace."""
        self.metrics = DecodeMetrics(
            n_chips=self.metrics.n_chips,
            prefill_compiles=len(self._prefill_fns) + len(self._tail_fns),
            decode_compiles=len(self._decode_fns) + len(self._spec_fns),
            kv_bytes_per_token=self.metrics.kv_bytes_per_token,
        )
        self._init_registry()
        # windowed-snapshot baselines re-base with the counters: a stale
        # pre-reset baseline would report negative error-rate deltas
        # (HistogramWindow re-bases itself on the fresh histogram objects)
        self._snap_prev.clear()
        self._g_queue.set(len(self._queue))

    def close(self) -> dict:
        """Shutdown summary: log + return the final DecodeMetrics summary
        (TTFT, tokens/s/chip, and — the silent regression — the compile
        counts) so it is visible without reading the portal, and snapshot
        the metrics registry into the job history when running under a
        tony-tpu job. Quantiles come from the registry histograms.
        Requests still queued or mid-decode get their spans ended with
        reason=shutdown — a hung request must be visible in the trace."""
        for spans in (self._queued_spans, self._decode_spans):
            for sp in spans.values():
                sp.end(reason="shutdown")
            spans.clear()
        self._first_tok_t.clear()
        # a profile window still open at shutdown finalises (partial trace
        # + manifest land) instead of dying with the engine
        profile.finish_capture()
        s = self.metrics.summary()
        if self._h_ttft.count:
            s["ttft_p50_s"] = round(self._h_ttft.quantile(0.5), 4)
            s["ttft_p99_s"] = round(self._h_ttft.quantile(0.99), 4)
        if self._h_tpot.count:
            s["tpot_p50_s"] = round(self._h_tpot.quantile(0.5), 4)
        # ledger-sourced lines: XLA compiles this engine actually triggered
        # (the DecodeMetrics counts are per-signature intents; this is what
        # the backend really compiled) and the engine-scoped peak-HBM
        # watermark (marked at __init__, measured by the attribution rule)
        s["xla_compiles"] = self._ledger.backend_compiles - self._compiles_t0
        if self._store is not None:
            # prefix-store lifetime summary: hit rate is the reuse headline,
            # cow_copies the sharing-safety one (each is a block the store
            # protected from a would-be shared write)
            s["prefix"] = dict(self._store.stats())
            s["prefix"]["cow_copies"] = self._cow_copies
        sentinel = health.active_sentinel()
        if sentinel is not None:
            # drain so a trip on the final decode steps reaches the summary,
            # then export tony_health_* into this engine's registry (it is
            # snapshotted below) and persist the verdict file
            sentinel.drain()
            s["health_verdict"] = sentinel.verdict
            trips = sentinel.trip_counts()
            if trips:
                s["health_trips"] = trips
            sentinel.export(self.registry)
            sentinel.write_verdict()
        # live series + SLO teardown: final scrape drained, source
        # detached (a recreated engine must not leave a stale closure
        # scraping freed state), verdict persisted — `met` verdicts exist
        # on disk too, so absence stays distinguishable from success
        if self._series is not None:
            self._series.force_sample()
            self._series.drain()
            self._series.detach(self._series_key)
        slo_engine = slo.active_engine()
        if slo_engine is not None:
            s["slo_verdict"] = slo_engine.verdict
            trips = slo_engine.trip_counts()
            if trips:
                s["slo_trips"] = trips
            slo_engine.export(self.registry)
            slo_engine.write_verdict()
        watch = hbm.active_watch()
        if watch is not None and self._hbm_mark is not None:
            peak_gb, peak_exact = watch.peak_since(self._hbm_mark)
            if peak_gb:
                s["peak_hbm_gb"] = peak_gb
                s["peak_hbm_exact"] = peak_exact
            # gauges into THIS registry so tony_hbm_* lands in the
            # job-history snapshot the portal /metrics serves
            watch.export_gauges(self.registry)
        log.info("engine shutdown: %s", s)
        # suffixed so a train-then-serve user process cannot overwrite one
        # component's snapshot with the other's
        snapshot_to_app_dir(
            trace.default_proc_name("serve") + "_engine", self.registry
        )
        compile_ledger.snapshot_to_app_dir()
        return s

    def step(self) -> int:
        """Admit what fits, run one decode step; returns live-slot count."""
        # coordinated-profiling seam (one global load + None compare
        # disarmed): a broadcast window brackets decode steps exactly like
        # train steps, so `tony profile` anatomises serving hosts too
        profile.maybe_capture()
        # chunked-prefill interleave: slots already chunking advance ONE
        # chunk each per step (slots _admit parks into chunking below ran
        # their first chunk inside admission — advancing them again here
        # would burn two chunks in one step)
        pending = sorted(self._chunking)
        self._admit()
        for slot in pending:
            if slot in self._chunking:
                self._prefill_chunk(slot)
        if self.n_decoding:
            self._decode_once()
        return self.n_live

    def completion_of(self, rid: int) -> Completion | None:
        """Live view of a request's completion: ``tokens`` grows in place
        as the engine decodes and ``finish_reason`` lands when it ends.
        The gang worker's streaming seam (serve/gang.py) — callers must
        not mutate the returned object."""
        return self._completions.get(rid)

    def take_completion(self, rid: int) -> Completion | None:
        """Pop one finished completion (the incremental form of what
        run() does wholesale, so a long-lived streaming driver never
        accumulates every Completion forever)."""
        return self._completions.pop(rid, None)

    def run(self, requests: Sequence[Request] | None = None) -> dict[int, Completion]:
        """Submit ``requests`` (if given), drain queue and live slots, and
        return — and evict — every completion finished by this call (a
        long-lived engine must not accumulate one Completion per request
        forever; callers keep what run() hands them)."""
        if requests is not None:
            for r in requests:
                self.submit(r)
        self._run_loop()
        done, self._completions = self._completions, {}
        return done

    def _run_loop(self) -> None:
        """Drain queue + live slots under the runtime sanitizer when armed
        (GRAFT_SANITIZE=1): implicit D2H transfers and steady-state
        compiles raise (analysis/sanitize.py). A cold engine compiles per
        prefill bucket / cache capacity by design — sanitize a *warmed*
        engine, or budget via GRAFT_SANITIZE_MAX_COMPILES. A
        RESOURCE_EXHAUSTED escaping the loop dumps OOM forensics into the
        app dir before re-raising (obs/hbm.py)."""
        from tony_tpu.analysis import sanitize

        with hbm.oom_guard("engine.run"), \
                sanitize.sanitized_loop("decode") as watchdog:
            while self._queue or self.n_live:
                self.step()
                if watchdog is not None:
                    watchdog.check()

    # --- admission ------------------------------------------------------------

    def _admit(self) -> None:
        free = [s for s, r in enumerate(self._slot_rid) if r is None]
        while free and self._queue:
            self._admit_one(free.pop(0), *self._queue.popleft())

    def _bucket_for(self, plen: int) -> int:
        for b in self.serve.prefill_buckets:
            if b >= plen:
                return b
        raise AssertionError("submit() validated bucket coverage")

    def _admit_one(self, slot: int, rid: int, req: Request) -> None:
        t0 = time.perf_counter()
        qspan = self._queued_spans.pop(rid, None)
        if qspan is not None:
            qspan.end(slot=slot)
        self._g_queue.set(len(self._queue))
        # explicit D2H for device-array prompts (no-op for lists/np):
        # transfer-guard-clean under GRAFT_SANITIZE
        prompt = np.asarray(jax.device_get(req.prompt), np.int32).reshape(-1)
        plen = len(prompt)
        bucket = self._bucket_for(plen)
        # prefix match: pure host-side hashing on the admission path (no
        # device work, GL001-clean). A match is used only when it covers at
        # least one full block — shorter overlaps would pay a COW block
        # copy for near-zero prefill savings.
        match: MatchResult | None = None
        matched = 0
        if self._store is not None and plen > 1:
            m = self._store.match(prompt.tolist(), plen - 1)
            if m.full:
                match = self._trim_match(plen, m)
                matched = match.length
        ct = self.serve.chunk_tokens
        chunked = bool(ct) and plen - matched > ct
        if chunked and match is not None and match.partial is not None:
            # chunk starts must stay block-aligned (every chunk boundary
            # is matched + i*chunk_tokens): cut a mid-block COW match back
            # to its full blocks — at chunked-prompt lengths the lost
            # sub-block overlap is noise against the prefill itself
            match = MatchResult(
                len(match.full) * self.serve.kv_block, match.full, None
            )
            matched = match.length
        if self._store is not None and plen > 1:
            self._store.record_prompt(plen, matched)
            self._c_prompt_tokens.inc(plen)
            if matched:
                self._c_prefix_hit.inc(matched)
        self.metrics.record_prompt(plen, matched)
        key = _as_raw_key(req.rng, rid)
        if chunked:
            # chunked prefill: plan every prompt block now, then advance
            # one chunk per engine step (docs/SERVE.md "Disaggregated
            # serving" — co-resident decode streams never stall behind a
            # whole-prompt prefill). The slot stays out of the decode
            # batch (state.live False, decode writes scratch-steered)
            # until the final chunk samples the first token.
            with trace.span("serve.prefill", rid=rid, bucket=bucket,
                            slot=slot, matched=matched, chunked=1):
                self._plan_blocks(slot, plen, match)
            self._slot_rid[slot] = rid
            self._chunking[slot] = _ChunkedPrefill(
                rid=rid, req=req, prompt=prompt, pos=matched, key=key, t0=t0,
            )
            self._prefill_chunk(slot)  # first chunk rides the admission step
            return
        with trace.span("serve.prefill", rid=rid, bucket=bucket, slot=slot,
                        matched=matched):
            self._plan_blocks(slot, plen, match)
            if match is None:
                padded = np.zeros((1, bucket), np.int32)
                padded[0, :plen] = prompt
                # ledger attribution: a fresh bucket compile fired inside
                # this call journals under the prefill's name, not
                # anonymously
                with self._ledger.label(f"serve.prefill[{bucket}]"):
                    tok, carry, pk, pv = self._get_prefill(bucket)(
                        self.params, jnp.asarray(padded), jnp.int32(plen - 1),
                        jnp.float32(req.temperature), jnp.int32(req.top_k),
                        jnp.float32(req.top_p), key,
                    )
                self._scatter_prompt(slot, pk, pv, 0, plen)
            else:
                tok, carry = self._tail_prefill(slot, prompt, matched, req, key)
            # EXPLICIT sync: the sampled first token steers admission on
            # the host (transfer-guard-clean under GRAFT_SANITIZE)
            tok = int(jax.device_get(tok))
        self._activate_slot(slot, rid, req, prompt, tok, carry, t0)

    def _prefill_chunk(self, slot: int) -> None:
        """Advance one chunked-prefill slot by ONE chunk (at most
        chunk_tokens tokens through the restartable tail-prefill path).
        Intermediate chunks discard the sampled token (their last_index
        points mid-prompt); the final chunk's sample IS the request's
        first token — same logits, same key as an unchunked prefill, so
        chunking is draw-for-draw invisible in the output."""
        job = self._chunking[slot]
        plen = len(job.prompt)
        end = min(job.pos + self.serve.chunk_tokens, plen)
        final = 1 if end == plen else 0
        with trace.span("serve.prefill_chunk", rid=job.rid, slot=slot,
                        start=job.pos, end=end, final=final):
            tok, carry = self._tail_prefill(
                slot, job.prompt, job.pos, job.req, job.key, end=end
            )
            if final:
                tok = int(jax.device_get(tok))
        if not final:
            job.pos = end
            return
        del self._chunking[slot]
        self._activate_slot(
            slot, job.rid, job.req, job.prompt, tok, carry, job.t0
        )

    def _activate_slot(self, slot: int, rid: int, req: Request,
                       prompt: np.ndarray, tok: int, carry, t0: float) -> None:
        """Post-prefill activation: the sampled first token lands, TTFT is
        recorded, and the slot joins the decode batch."""
        plen = len(prompt)
        self._register_prompt(slot, prompt)
        now = time.perf_counter()
        self.metrics.record_prefill(now - t0, now - self._submit_t[rid])  # popped below
        self._h_ttft.observe(now - self._submit_t[rid])
        self._c_tokens.inc()
        self._first_tok_t[rid] = now
        tracer = trace.active_tracer()
        if tracer is not None:
            # decode-lifetime span: first token -> finish
            self._decode_spans[rid] = tracer.span("serve.decode", rid=rid, slot=slot)

        self._slot_len[slot] = plen
        if self.serve.spec:
            # draft context: prompt + every emitted token (input token
            # last) — what the host-side draft sources extend
            self._slot_ctx[slot] = [int(t) for t in prompt] + [tok]
        st = self.state
        eos = -1 if req.eos_id is None else int(req.eos_id)
        self.state = _SlotState(
            last_tok=st.last_tok.at[slot].set(tok),
            rng=st.rng.at[slot].set(carry),
            temp=st.temp.at[slot].set(req.temperature),
            top_k=st.top_k.at[slot].set(req.top_k),
            top_p=st.top_p.at[slot].set(req.top_p),
            eos=st.eos.at[slot].set(eos),
            done=st.done.at[slot].set(False),
            live=st.live.at[slot].set(True),
        )
        self._slot_rid[slot] = rid
        self._slot_remaining[slot] = req.max_new_tokens
        comp = Completion(
            rid=rid, tokens=[tok], prompt_len=plen,
            ttft_s=now - self._submit_t.pop(rid),
        )
        self._completions[rid] = comp
        self._slot_remaining[slot] -= 1
        if tok == eos:
            self._finish(slot, "eos")
        elif self._slot_remaining[slot] <= 0:
            self._finish(slot, "length")

    def _finish(self, slot: int, reason: str) -> None:
        rid = self._slot_rid[slot]
        comp = self._completions[rid]
        comp.finish_reason = reason
        if self._store is not None and self.serve.spec:
            # draft corpus: register the GENERATED tokens' full blocks
            # too (prompt blocks landed at admission), so future drafts
            # extend along observed generations — the radix tree caching
            # generated sequences, SGLang-style. The K/V'd sequence is
            # the context minus its last token (sampled, never fed).
            B = self.serve.kv_block
            seq = self._slot_ctx[slot][:self._slot_len[slot]]
            n_full = len(seq) // B
            if n_full:
                self._store.insert(
                    seq[:n_full * B],
                    self._table[slot, :n_full].tolist(), self._pool.retain,
                )
                self._store.evict_to_budget(self._pool.release)
                self._g_prefix_bytes.set(self._store.resident_bytes)
                self._g_prefix_nodes.set(self._store.n_nodes)
        self._slot_ctx[slot] = []
        self.metrics.requests_finished += 1
        self._c_finished.inc()
        t_first = self._first_tok_t.pop(rid, None)
        if t_first is not None and len(comp.tokens) > 1:
            # TPOT: decode-token cadence after the prefill-sampled first
            self._h_tpot.observe(
                (time.perf_counter() - t_first) / (len(comp.tokens) - 1)
            )
        dspan = self._decode_spans.pop(rid, None)
        if dspan is not None:
            dspan.end(tokens=len(comp.tokens), reason=reason)
        self._slot_rid[slot] = None
        self._slot_remaining[slot] = 0
        self._slot_len[slot] = 0
        st = self.state
        self.state = st._replace(
            live=st.live.at[slot].set(False),
            done=st.done.at[slot].set(False),
        )
        self.cache = self.cache._replace(
            lengths=self.cache.lengths.at[slot].set(0)
        )
        # a freed slot returns only the blocks whose refcount hits zero —
        # blocks the prefix store (or another slot's table) still
        # references stay resident
        row = self._table[slot]
        for bi in range(self._slot_blocks[slot]):
            self._pool.release(int(row[bi]))
        row[:self._slot_blocks[slot]] = SCRATCH_BLOCK
        self._slot_blocks[slot] = 0
        self._table_dirty = True
        self._maybe_shrink_pool()

    # --- block planning (host side of the paged cache) ------------------------

    @property
    def attended_positions(self) -> int:
        """Positions the decode step currently attends per slot (table
        width x kv_block) — the paged analogue of the old contiguous
        cache's ``capacity``."""
        return self._attended * self.serve.kv_block

    def _alloc_block(self) -> int:
        """One private physical block: free list, else grow the pool
        (doubling, device + host in lockstep), else evict LRU leaves from
        the prefix store until a block frees. The pool cap covers every
        slot at max_len plus the store budget, so the chain terminates."""
        pid = self._pool.alloc()
        while pid is None:
            if self._pool.n_blocks < self._pool_cap:
                new = min(max(2 * self._pool.n_blocks, 4), self._pool_cap)
                self.cache = grow_cache(self.cache, new)
                self._pool.grow(new)
            elif self._store is not None and \
                    self._store.evict_lru(self._pool.release) is not None:
                pass  # evicted; the release may or may not have freed HBM
            else:
                raise RuntimeError(
                    "block pool exhausted (live slots + store exceed the "
                    "pool cap — engine accounting bug)"
                )
            pid = self._pool.alloc()
        if self.cache.quantized:
            # a reused block carries its previous tenant's scale row —
            # queue it for the batched zeroing flush (scale 0 = nothing
            # real stored, so the first write fully defines the scale)
            self._fresh_scale.append(pid)
        return pid

    def _plan_blocks(self, slot: int, plen: int, match: MatchResult | None) -> None:
        """Fill the slot's table row for a prompt: matched full blocks map
        shared (one pool reference each, never written), a mid-block match
        gets a private copy-on-write block, the rest are fresh."""
        B = self.serve.kv_block
        row = self._table[slot]
        nb = blocks_for(plen, B)
        next_bi = 0
        if match is not None:
            for bi, pid in enumerate(match.full):
                self._pool.retain(pid)
                row[bi] = pid
            next_bi = len(match.full)
            if match.partial is not None:
                # COW: the unshared tail writes into this block — hand the
                # slot a private copy of the shared source first
                dst = self._alloc_block()
                if self.cache.quantized:
                    # the copy overwrites dst's scale row with src's — a
                    # later zeroing flush would erase it
                    self._fresh_scale.remove(dst)
                self.cache = _copy_block_fn(self.cache.quantized)(
                    self.cache, jnp.int32(match.partial), jnp.int32(dst)
                )
                row[next_bi] = dst
                next_bi += 1
                self._cow_copies += 1
        for bi in range(next_bi, nb):
            row[bi] = self._alloc_block()
        self._slot_blocks[slot] = nb
        self._table_dirty = True

    def _trim_match(self, plen: int, match: MatchResult) -> MatchResult:
        """Drop a mid-block (COW) match when the tail's ladder bucket
        would overrun the cache cap: with the match cut back to its full
        blocks the tail starts block-aligned, so the block-aligned tail
        width always fits — tail-prefill signatures stay multiples of
        kv_block instead of one per match length."""
        B = self.serve.kv_block
        if match.partial is None:
            return match
        tb = self._bucket_for(plen - match.length)
        if match.length + tb <= self._m_total * B:
            return match
        return MatchResult(len(match.full) * B, match.full, None)

    def _flush_fresh_scales(self) -> None:
        """Zero the scale rows of freshly allocated blocks in one batched
        device write (padded to a power-of-two id count with scratch so
        the jitted zeroing keeps a bounded signature set)."""
        if not self._fresh_scale:
            return
        pids = self._fresh_scale
        self._fresh_scale = []
        n = 1
        while n < len(pids):
            n *= 2
        padded = np.full(n, SCRATCH_BLOCK, np.int32)
        padded[:len(pids)] = pids
        self.cache = _zero_scales_fn()(self.cache, jnp.asarray(padded))

    def _scatter_prompt(self, slot: int, pk, pv, start: int, plen: int) -> None:
        """Write prefilled K/V (``[L, Hkv, W, hd]``, positions ``start +
        i``) into the slot's blocks; padded rows beyond ``plen`` steer to
        the scratch block. Quantized pools quantize the span in the same
        fused step (per-touched-block running-scale update)."""
        B = self.serve.kv_block
        row = self._table[slot]
        W = pk.shape[2]
        p = start + np.arange(W)
        valid = p < plen
        pids = np.where(valid, row[np.minimum(p // B, self._m_total - 1)],
                        SCRATCH_BLOCK).astype(np.int32)
        offs = np.where(valid, p % B, 0).astype(np.int32)
        if self.cache.quantized:
            self._flush_fresh_scales()
            # touched-block set at a STATIC width (the span covers at most
            # W//B + 1 blocks, plus scratch) so signatures stay per-bucket
            nU = W // B + 2
            ub = np.full(nU, SCRATCH_BLOCK, np.int32)
            uniq = np.unique(pids)
            ub[:len(uniq)] = uniq
            self.cache = _scatter_fn(self.serve.quant_kv)(
                self.cache, pk, pv, jnp.asarray(pids), jnp.asarray(offs),
                jnp.asarray(ub), jnp.int32(slot), jnp.int32(plen),
            )
            return
        self.cache = _scatter_fn()(
            self.cache, pk, pv, jnp.asarray(pids), jnp.asarray(offs),
            jnp.int32(slot), jnp.int32(plen),
        )

    def _tail_prefill(self, slot: int, prompt: np.ndarray, matched: int,
                      req: Request, key, end: int | None = None):
        """Prefill only the unshared tail: gather the matched prefix K/V
        from the pool (through the slot's own table, COW copy included)
        into a contiguous context, run the tail bucket through the model
        attending it, and scatter the tail K/V back into the slot's
        private blocks. FLOPs scale with the tail, not the prompt.

        ``end`` bounds the prefill to ``prompt[matched:end]`` — the
        chunked-prefill form (one chunk = one call with ``matched`` at the
        previous chunk's end). The restartable-tail contract makes the
        chained chunks bitwise-identical to one full prefill."""
        B = self.serve.kv_block
        plen = end if end is not None else len(prompt)
        tail_len = plen - matched
        cap = self._m_total * B
        tb = self._bucket_for(tail_len)
        if matched + tb > cap:
            # the ladder bucket overruns the cache cap (long match, coarse
            # ladder): fall back to the block-aligned minimum. _trim_match
            # guaranteed `matched` is block-aligned in this case, so
            # matched + ceil(tail/B)*B = ceil(plen/B)*B <= cap always —
            # signatures stay multiples of kv_block, never one per match
            tb = blocks_for(tail_len, B) * B
            assert matched % B == 0 and matched + tb <= cap, (matched, tb)
        # context width: enough for prefix + padded tail, rounded to a
        # power-of-two block count (bounded compile signatures)
        nC = blocks_for(max(plen, matched + tb), B)
        p2 = 1
        while p2 < nC:
            p2 *= 2
        nC = min(p2, self._m_total)
        C = nC * B
        row = self._table[slot]
        n_have = blocks_for(plen, B)
        gather = np.full(nC, SCRATCH_BLOCK, np.int32)
        gather[:min(n_have, nC)] = row[:min(n_have, nC)]
        ctx_k, ctx_v = _gather_fn(self.cache.quantized, self.cfg.dtype)(
            self.cache, jnp.asarray(gather)
        )
        tail = np.zeros((1, tb), np.int32)
        tail[0, :tail_len] = prompt[matched:plen]
        with self._ledger.label(f"serve.prefill_tail[{tb},{C}]"):
            tok, carry, tk, tv = self._get_tail_prefill(tb, C)(
                self.params, ctx_k, ctx_v, jnp.asarray(tail),
                jnp.int32(matched), jnp.int32(tail_len - 1),
                jnp.float32(req.temperature), jnp.int32(req.top_k),
                jnp.float32(req.top_p), key,
            )
        self._scatter_prompt(slot, tk, tv, matched, plen)
        return tok, carry

    def _register_prompt(self, slot: int, prompt: np.ndarray) -> None:
        """Insert the prompt's full blocks into the prefix store (each new
        radix node takes its own pool reference), then evict back under
        the HBM budget."""
        if self._store is None:
            return
        B = self.serve.kv_block
        n_full = len(prompt) // B
        if n_full:
            self._store.insert(
                prompt[:n_full * B].tolist(),
                self._table[slot, :n_full].tolist(), self._pool.retain,
            )
            if self._store.evict_to_budget(self._pool.release):
                self._maybe_shrink_pool()
        self._g_prefix_bytes.set(self._store.resident_bytes)
        self._g_prefix_nodes.set(self._store.n_nodes)

    # --- blockwise KV handoff (docs/SERVE.md "Disaggregated serving") ---------

    def export_prefix_blocks(
        self, tokens: Sequence[int]
    ) -> tuple[list[int], BlockPayload] | None:
        """Prefill-host side of the handoff: gather the store-resident
        full blocks covering ``tokens`` to the host as ``(covered_tokens,
        BlockPayload)`` — quantized payload and scale rows travel
        together. Each block is pinned (one extra pool reference) for the
        duration of the gather so LRU eviction cannot hand it away
        mid-export. None when nothing is resident."""
        if self._store is None:
            return None
        B = self.serve.kv_block
        toks = [int(t) for t in tokens]
        n_full = len(toks) // B
        if not n_full:
            return None
        m = self._store.match(toks, n_full * B)
        if not m.full:
            return None
        for pid in m.full:
            self._pool.retain(pid)
        try:
            payload = export_blocks(self.cache, list(m.full))
        finally:
            for pid in m.full:
                self._pool.release(pid)
        self._c_handoff_shipped.inc(len(m.full))
        return toks[:len(m.full) * B], payload

    def adopt_blocks(
        self, tokens: Sequence[int], payload: BlockPayload
    ) -> tuple[int, int]:
        """Decode-host side: adopt shipped blocks into THIS pool through
        the normal refcount rules. Every adopted block is freshly
        allocated (reallocation hands out only refcount-zero ids, so a
        handoff racing a slot-free can never corrupt a reallocated
        block), written payload + scale rows in one device store, and
        registered in the prefix store — which takes the owning
        reference. Blocks whose prefix is already resident are freed
        instead (the temp allocation releases). Every shipped block
        therefore ends adopted or freed — the handoff-no-block-leak
        contract the chaos checker audits. Returns (adopted, freed);
        raises ValueError on an incompatible payload (the gang worker
        maps it to an error response, never a corrupted pool)."""
        B = self.serve.kv_block
        nb = payload.n_blocks
        if len(tokens) != nb * B:
            raise ValueError(
                f"payload covers {nb} block(s) of {B} but {len(tokens)} "
                "tokens were named"
            )
        why = payload_compatible(self.cache, payload)
        if why:
            raise ValueError(f"incompatible handoff payload: {why}")
        toks = [int(t) for t in tokens]
        if self._store is None:
            # no store to own them — nothing adopts, nothing strands
            self._c_handoff_freed.inc(nb)
            return 0, nb
        have = len(self._store.match(toks, nb * B).full)
        new_pids: list[int] = []
        for bi in range(have, nb):
            pid = self._alloc_block()
            if self.cache.quantized:
                # the adopt write lands the shipped scale row verbatim —
                # a queued allocation-time scale zeroing would erase it
                self._fresh_scale.remove(pid)
            self.cache = write_block(self.cache, pid, payload, bi)
            new_pids.append(pid)
        phys = list(self._store.match(toks, nb * B).full)[:have] + new_pids
        created = self._store.insert(toks, phys, self._pool.retain)
        for pid in new_pids:
            self._pool.release(pid)
        if self._store.evict_to_budget(self._pool.release):
            self._maybe_shrink_pool()
        self._g_prefix_bytes.set(self._store.resident_bytes)
        self._g_prefix_nodes.set(self._store.n_nodes)
        self._c_handoff_adopted.inc(created)
        self._c_handoff_freed.inc(nb - created)
        return created, nb - created

    def _maybe_shrink_pool(self) -> None:
        """Halve the pool while the trailing half is entirely free — a
        block pinned high (prefix store or a long-lived slot) bounds the
        shrink, exactly the refcount contract shrink_cache documents."""
        if not self.serve.shrink:
            return
        new = self._pool.n_blocks
        target = self._pool.shrink_target(self._p0)
        while new // 2 >= target and new // 2 >= self._p0:
            new //= 2
        if new < self._pool.n_blocks:
            self.cache = shrink_cache(self.cache, new)
            self._pool.shrink(new)

    def _set_attended(self, need: int) -> None:
        """Size the decode step's table width to the live maximum: grow by
        doubling, shrink when the need halves (the old contiguous-capacity
        policy, now on the indirection table)."""
        cur = self._attended
        if need > cur:
            cur = min(max(need, 2 * cur), self._m_total)
        elif self.serve.shrink and need <= cur // 2:
            cur = max(need, 1)
        if cur != self._attended or self._table_dirty:
            self._attended = cur
            self._table_dev = jnp.asarray(self._table[:, :cur])
            self._table_dirty = False

    # --- jitted steps ---------------------------------------------------------

    def _get_prefill(self, bucket: int):
        if bucket not in self._prefill_fns:
            # AOT-compiled (module-wide cache) so the compile ledger holds
            # the prefill's measured cost_analysis FLOPs — the number the
            # bench/acceptance gate compares against the tail prefill's to
            # prove FLOPs scale with the unshared tail
            self._prefill_fns[bucket] = _aot_prefill(
                self.cfg, bucket, self.serve.max_top_k, self.params,
                self._ledger,
            )
            self.metrics.prefill_compiles = (
                len(self._prefill_fns) + len(self._tail_fns)
            )
        return self._prefill_fns[bucket]

    def _get_tail_prefill(self, tb: int, ctx: int):
        if (tb, ctx) not in self._tail_fns:
            self._tail_fns[(tb, ctx)] = _aot_tail_prefill(
                self.cfg, tb, ctx, self.serve.max_top_k, self.params,
                self._ledger,
            )
            self.metrics.prefill_compiles = (
                len(self._prefill_fns) + len(self._tail_fns)
            )
        return self._tail_fns[(tb, ctx)]

    def _get_decode(self, signature: tuple[int, int]):
        if signature not in self._decode_fns:
            # AOT-compiled per (model, kernel, shapes, sharding), shared
            # across engines module-wide (_aot_decode's cache — every
            # pool-size/table-width signature compiles once per process,
            # not once per Engine); the AOT executable is what lets the
            # ledger record the decode step's measured memory plan
            # (memory_analysis: params + temp + per-block KV bytes), which
            # the gqa_capacity slot budget is derived from. The per-engine
            # dict only counts the distinct signatures this engine entered.
            self._decode_fns[signature] = _aot_decode(
                self.cfg, self.serve.decode_impl, self.serve.kv_block,
                self.serve.max_top_k, self._dec_params, self.cache,
                self._table_dev, self.state, self._ledger,
                monitors=self._monitors, quant_kv=self.serve.quant_kv,
                quant_weights=self.serve.quant_weights,
            )
            self.metrics.decode_compiles = (
                len(self._decode_fns) + len(self._spec_fns)
            )
        return self._decode_fns[signature]

    def _get_spec_decode(self, signature: tuple[int, int]):
        """The speculative (G = spec_max_draft + 1)-position verify step.
        Same signature space as the 1-wide step — (pool blocks, attended
        table width) — at ONE fixed G per engine, so spec adds at most a
        bounded mirror of the plain ledger, never a per-draft-length
        signature family (short drafts pad to G with writes steered to
        the scratch block)."""
        if signature not in self._spec_fns:
            self._spec_fns[signature] = _aot_spec_decode(
                self.cfg, self.serve.decode_impl, self.serve.kv_block,
                self.serve.max_top_k, self.serve.spec_max_draft,
                self._dec_params, self.cache, self._table_dev, self.state,
                self._ledger, monitors=self._monitors,
                quant_kv=self.serve.quant_kv,
                quant_weights=self.serve.quant_weights,
            )
            self.metrics.decode_compiles = (
                len(self._decode_fns) + len(self._spec_fns)
            )
        return self._spec_fns[signature]

    # --- decode loop ----------------------------------------------------------

    def _propose_step_drafts(
        self, live: list[int]
    ) -> tuple[np.ndarray | None, list[int]]:
        """Host-side draft pass (spec on): ask the draft sources for up to
        k tokens per live slot, capped so the emitted count can never
        overrun the slot's token budget (``remaining - 1``: the bonus
        token always emits). Pure python — GL001-clean."""
        k_max = self.serve.spec_max_draft if self.serve.spec else 0
        dlens = [0] * self.serve.slots
        if not k_max:
            return None, dlens
        drafts = np.zeros((self.serve.slots, k_max), np.int32)
        for s in live:
            cap = min(k_max, self._slot_remaining[s] - 1)
            if cap <= 0:
                continue
            prop = propose_drafts(
                self._slot_ctx[s], self._store, cap,
                self.serve.spec_draft_source,
            )
            if prop:
                dlens[s] = len(prop)
                drafts[s, :len(prop)] = prop
        return drafts, dlens

    def _decode_once(self) -> None:
        # per-step block planning: a live row allocates blocks NOW to
        # cover every position this step may write (host-side, before
        # dispatch) — position pos autoregressively, pos..pos+draft_len
        # speculatively; the attended table width tracks the live maximum
        B = self.serve.kv_block
        live_before = [
            s for s, r in enumerate(self._slot_rid)
            if r is not None and s not in self._chunking
        ]
        drafts_np, dlens = self._propose_step_drafts(live_before)
        spec_step = any(dlens)
        need = 1
        for s in live_before:
            last = self._slot_len[s] + (dlens[s] if spec_step else 0)
            while self._slot_blocks[s] * B <= last:
                self._table[s, self._slot_blocks[s]] = self._alloc_block()
                self._slot_blocks[s] += 1
                self._table_dirty = True
            need = max(need, last // B + 1)
        if self.cache.quantized:
            self._flush_fresh_scales()
        self._set_attended(need)
        tracer = trace.active_tracer()
        sp = trace.NOOP_SPAN
        if tracer is not None:
            sp = tracer.sampled_span("serve.step", live=len(live_before))
        with sp:
            t0 = time.perf_counter()
            sig = (self.cache.n_blocks, self._attended)
            if spec_step:
                self.cache, self.state, toks, n_emit, hmon = \
                    self._get_spec_decode(sig)(
                        self._dec_params, self.cache, self._table_dev,
                        self.state, jnp.asarray(drafts_np),
                        jnp.asarray(np.asarray(dlens, np.int32)),
                    )
            else:
                # no live slot drafted: the plain 1-wide step (also the
                # only step compiled with spec off — same signatures as
                # the pre-spec engine)
                self.cache, self.state, toks, hmon = self._get_decode(sig)(
                    self._dec_params, self.cache, self._table_dev, self.state
                )
            # EXPLICIT per-step sync: continuous batching needs the sampled
            # tokens + done flags on host to steer admission — this is the
            # engine's one designed sync point per decode step
            toks_np = np.asarray(jax.device_get(toks))
            emit_np = np.asarray(jax.device_get(n_emit)) if spec_step else None
            done_np = jax.device_get(self.state.done)
            dt = time.perf_counter() - t0
        if spec_step:
            new_total = int(sum(int(emit_np[s]) for s in live_before))
            prop_total = sum(dlens[s] for s in live_before)
            acc_total = sum(max(int(emit_np[s]) - 1, 0) for s in live_before)
            self.metrics.record_spec(prop_total, acc_total)
            self._c_draft_prop.inc(prop_total)
            self._c_draft_acc.inc(acc_total)
        else:
            new_total = len(live_before)
        self.metrics.record_decode(
            dt, new_total, len(live_before), self.serve.slots
        )
        hbm.sample()  # stride-counted device-memory reading (no sync)
        if hmon:
            # stride-counted health sample: DEVICE references + the host
            # slot->request map for per-request trip attribution; the
            # device_get sync happens on the sentinel's worker thread
            slot_rids = list(self._slot_rid)
            health.sample(
                metrics=hmon, slot_rids=slot_rids, live_slots=live_before
            )
        series.sample()  # stride-counted scrape of the attached sources
        self._h_step.observe(dt)
        self._c_tokens.inc(new_total)
        for s in live_before:
            if spec_step:
                n = int(emit_np[s])
                new_toks = [int(t) for t in toks_np[s, :n]]
            else:
                n = 1
                new_toks = [int(toks_np[s])]
            self._slot_len[s] += n
            self._completions[self._slot_rid[s]].tokens.extend(new_toks)
            if self.serve.spec:
                self._slot_ctx[s].extend(new_toks)
            self._slot_remaining[s] -= n
            if done_np[s]:
                self._finish(s, "eos")
            elif self._slot_remaining[s] <= 0:
                self._finish(s, "length")

    def _decode_impl(self, params, cache: PagedKVCache, table, state: _SlotState):
        """One token for every slot (test/guard hook; the hot path goes
        through the module-level cache in :func:`_decode_fn`)."""
        return _decode_step(
            params, cache, table, state, cfg=self.cfg,
            decode_impl=self.serve.decode_impl,
            kv_block=self.serve.kv_block, max_top_k=self.serve.max_top_k,
            monitors=self._monitors, quant_kv=self.serve.quant_kv,
            quant_weights=self.serve.quant_weights,
        )


@functools.lru_cache(maxsize=512)
def _prefill_fn(cfg: LlamaConfig, bucket: int, max_top_k: int):
    """Jitted bucketed prefill, cached per (model config, bucket): engines
    with the same model share prefill compiles process-wide."""
    return jax.jit(partial(
        _prefill_step, cfg=cfg, bucket=bucket, max_top_k=max_top_k
    ))


@functools.lru_cache(maxsize=512)
def _tail_fn(cfg: LlamaConfig, tb: int, max_top_k: int):
    """Jitted tail prefill (prefix-matched admissions), cached per (model
    config, tail bucket); jit itself caches per context width."""
    return jax.jit(partial(
        _tail_prefill_step, cfg=cfg, tb=tb, max_top_k=max_top_k
    ))


@functools.lru_cache(maxsize=512)
def _decode_fn(cfg: LlamaConfig, decode_impl: str, kv_block: int,
               max_top_k: int, monitors: bool = False, quant_kv: str = "",
               quant_weights: bool = False):
    """Jitted decode step, cached per (model config, kernel knobs) — NOT
    per pool-size/table-width: jit itself caches per argument shape, so
    all engines with the same model reuse every compiled signature. The
    block table (arg 2) is NOT donated — it is reused across steps."""
    return jax.jit(
        partial(
            _decode_step, cfg=cfg, decode_impl=decode_impl,
            kv_block=kv_block, max_top_k=max_top_k, monitors=monitors,
            quant_kv=quant_kv, quant_weights=quant_weights,
        ),
        donate_argnums=(1, 3),
    )


# AOT executables shared module-wide: keyed by model/kernel knobs + the
# cache/state shapes + the params' sharding, so engines with the same
# model reuse every compiled signature (the lru_cache-on-jit property the
# lazy path had), while the AOT form exposes memory_analysis()/
# cost_analysis() to the compile ledger and serve/capacity.py
_aot_decode_cache: dict = {}
_aot_prefill_cache: dict = {}


def _aot_compile(fn, avals, key, name, ledger, cache=_aot_prefill_cache):
    """Shared AOT-with-ledger path: lower from ``avals`` (live arrays or
    ShapeDtypeStructs), journal the measured cost/memory plan under
    ``name``, fall back to lazy jit dispatch on any compile failure."""
    hit = cache.get(key)
    if hit is not None:
        return hit
    t0 = time.perf_counter()
    try:
        with ledger.label(name):
            compiled = fn.lower(*avals).compile()
        ledger.record_aot(name, compiled, time.perf_counter() - t0)
    except Exception:
        log.debug("AOT compile of %s failed; using lazy jit", name,
                  exc_info=True)
        compiled = fn
    if len(cache) < 512:
        cache[key] = compiled
    return compiled


def _aot_decode(cfg: LlamaConfig, decode_impl: str, kv_block: int,
                max_top_k: int, params, cache, table, state, ledger, *,
                monitors: bool = False, quant_kv: str = "",
                quant_weights: bool = False):
    fn = _decode_fn(cfg, decode_impl, kv_block, max_top_k, monitors,
                    quant_kv, quant_weights)
    try:
        shard = jax.tree.leaves(params)[0].sharding
        key = (cfg, decode_impl, kv_block, max_top_k, monitors,
               quant_kv, quant_weights,
               cache.k.shape, str(cache.k.dtype), table.shape,
               hash(shard), shard)
    except Exception:
        # unhashable sharding (exotic platform): lazy jit still works and
        # still shares compiles process-wide
        return fn
    name = (f"serve.decode[slots={state.last_tok.shape[0]},"
            f"blocks={cache.k.shape[1]},attended={table.shape[1]}]")
    return _aot_compile(
        fn, (params, cache, table, state), key, name, ledger,
        cache=_aot_decode_cache,
    )


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _aot_prefill(cfg: LlamaConfig, bucket: int, max_top_k: int, params,
                 ledger):
    """Full-prompt prefill, AOT so the ledger records its cost_analysis
    FLOPs (the full-prompt baseline the prefix store's tail-FLOPs are
    judged against)."""
    fn = _prefill_fn(cfg, bucket, max_top_k)
    try:
        shard = jax.tree.leaves(params)[0].sharding
        key = ("prefill", cfg, bucket, max_top_k, hash(shard), shard)
    except Exception:
        return fn
    # live params (their real shardings bake into the executable — a
    # sharded-params engine must not compile against default layouts),
    # avals for the per-call scalars
    avals = (
        params, _sds((1, bucket), jnp.int32), _sds((), jnp.int32),
        _sds((), jnp.float32), _sds((), jnp.int32), _sds((), jnp.float32),
        _sds((2,), jnp.uint32),
    )
    return _aot_compile(fn, avals, key, f"serve.prefill[{bucket}]", ledger)


def _aot_tail_prefill(cfg: LlamaConfig, tb: int, ctx: int, max_top_k: int,
                      params, ledger):
    fn = _tail_fn(cfg, tb, max_top_k)
    try:
        shard = jax.tree.leaves(params)[0].sharding
        key = ("tail", cfg, tb, ctx, max_top_k, hash(shard), shard)
    except Exception:
        return fn
    kv = _sds((cfg.n_layers, 1, ctx, cfg.n_kv_heads, cfg.head_dim), cfg.dtype)
    avals = (
        params, kv, kv, _sds((1, tb), jnp.int32), _sds((), jnp.int32),
        _sds((), jnp.int32), _sds((), jnp.float32), _sds((), jnp.int32),
        _sds((), jnp.float32), _sds((2,), jnp.uint32),
    )
    return _aot_compile(
        fn, avals, key, f"serve.prefill_tail[{tb},{ctx}]", ledger
    )


@functools.lru_cache(maxsize=4)
def _scatter_fn(quant_kv: str = ""):
    """Jitted position-wise KV scatter into the (DONATED) pool: position
    ``i`` of the prefilled span lands in physical block ``pids[i]`` at
    offset ``offs[i]``; masked rows steer to the scratch block. One
    in-place scatter instead of two whole-cache copies per admission.
    The quantized form additionally takes the touched-block set ``ub``
    and runs the per-block running-scale update + requantization
    (serve/cache.py quant_scatter_span, vmapped over layers)."""
    if quant_kv:
        _, qmax = kv_quant_spec(quant_kv)
        span = jax.vmap(
            partial(quant_scatter_span, qmax=qmax),
            in_axes=(0, 0, 0, None, None, None),
        )

        def insert_q(cache: PagedKVCache, pk, pv, pids, offs, ub, slot,
                     plen):
            k, ksc = span(cache.k, cache.k_scale, pk, pids, offs, ub)
            v, vsc = span(cache.v, cache.v_scale, pv, pids, offs, ub)
            lengths = lax.dynamic_update_slice(
                cache.lengths, plen[None], (slot,)
            )
            return PagedKVCache(k, v, lengths, ksc, vsc)

        return jax.jit(insert_q, donate_argnums=(0,))

    def insert(cache: PagedKVCache, pk, pv, pids, offs, slot, plen):
        # pk/pv [L, Hkv, W, hd]; advanced indices (pids axis 1, offs axis
        # 3) are non-adjacent, so the indexed result moves to the front:
        # [W, L, Hkv, hd] — match it by transposing the span
        k = cache.k.at[:, pids, :, offs, :].set(pk.transpose(2, 0, 1, 3))
        v = cache.v.at[:, pids, :, offs, :].set(pv.transpose(2, 0, 1, 3))
        lengths = lax.dynamic_update_slice(cache.lengths, plen[None], (slot,))
        return PagedKVCache(k, v, lengths)

    return jax.jit(insert, donate_argnums=(0,))


@functools.lru_cache(maxsize=2)
def _copy_block_fn(quant: bool = False):
    """Jitted copy-on-write block copy (DONATED pool): duplicate one
    physical block (all layers, K and V) so a slot about to write into a
    shared block writes into its private copy instead. A quantized pool
    copies the block's scale rows with it — the COW copy dequantizes to
    exactly what the shared source did."""
    def cp(cache: PagedKVCache, src, dst):
        kb = lax.dynamic_slice_in_dim(cache.k, src, 1, axis=1)
        vb = lax.dynamic_slice_in_dim(cache.v, src, 1, axis=1)
        k = lax.dynamic_update_slice_in_dim(cache.k, kb, dst, axis=1)
        v = lax.dynamic_update_slice_in_dim(cache.v, vb, dst, axis=1)
        if quant:
            ksb = lax.dynamic_slice_in_dim(cache.k_scale, src, 1, axis=1)
            vsb = lax.dynamic_slice_in_dim(cache.v_scale, src, 1, axis=1)
            ksc = lax.dynamic_update_slice_in_dim(
                cache.k_scale, ksb, dst, axis=1
            )
            vsc = lax.dynamic_update_slice_in_dim(
                cache.v_scale, vsb, dst, axis=1
            )
            return PagedKVCache(k, v, cache.lengths, ksc, vsc)
        return PagedKVCache(k, v, cache.lengths)

    return jax.jit(cp, donate_argnums=(0,))


@functools.lru_cache(maxsize=1)
def _zero_scales_fn():
    """Jitted batched scale-row reset (DONATED cache): freshly allocated
    blocks' K and V scale rows go to zero across all layers — the
    nothing-real-stored marker the first quantized write keys off."""
    def zero(cache: PagedKVCache, pids):
        return cache._replace(
            k_scale=cache.k_scale.at[:, pids, :].set(0.0),
            v_scale=cache.v_scale.at[:, pids, :].set(0.0),
        )

    return jax.jit(zero, donate_argnums=(0,))


@functools.lru_cache(maxsize=4)
def _gather_fn(quant: bool = False, out_dtype=None):
    """Jitted prefix gather: pool blocks ``pids`` -> one contiguous
    ``[L, 1, C, Hkv, hd]`` context cache for the tail prefill (read-only:
    the pool is NOT donated — the slot keeps serving from it). Quantized
    pools dequantize through the gathered blocks' scale rows into
    ``out_dtype`` — the tail prefill attends real-valued context."""
    def gat(cache: PagedKVCache, pids):
        def one(pool, scale):
            g = jnp.take(pool, pids, axis=1)           # [L, nC, Hkv, blk, hd]
            if quant:
                sc = jnp.take(scale, pids, axis=1)     # [L, nC, Hkv]
                g = dequantize_values(g, sc[..., None, None], out_dtype)
            L, nC, Hkv, blk, hd = g.shape
            return g.transpose(0, 1, 3, 2, 4).reshape(
                L, nC * blk, Hkv, hd
            )[:, None]                                 # [L, 1, C, Hkv, hd]
        return one(cache.k, cache.k_scale), one(cache.v, cache.v_scale)

    return jax.jit(gat)


_QUANT_WEIGHT_NAMES = ("wq", "wk", "wv", "wo", "w1", "w2", "w3")


def _quantize_decode_params(params: Params) -> dict:
    """One-time int8 copy of the decode-path weights (ops/quant_mm.py):
    every layer matmul and lm_head swap to ``<name>_q``/``<name>_s``
    pairs; norms and the embedding stay real-valued. The bf16 master
    params are untouched — prefill keeps using them."""
    layers = dict(params["layers"])
    for name in _QUANT_WEIGHT_NAMES:
        q, s = quantize_weights(layers.pop(name))
        layers[name + "_q"] = q
        layers[name + "_s"] = s
    out = {k: v for k, v in params.items() if k not in ("layers", "lm_head")}
    q, s = quantize_weights(params["lm_head"])
    out["layers"] = layers
    out["lm_head_q"] = q
    out["lm_head_s"] = s
    return out


def _prefill_step(params, prompt, last_index, temp, top_k, top_p, key, *,
                  cfg: LlamaConfig, bucket: int, max_top_k: int):
    from tony_tpu.models.generate import (
        KVCache, forward_with_cache, sample_tokens,
    )

    cache0 = KVCache.create(cfg, 1, bucket)
    logits, kv = forward_with_cache(
        params, prompt, cache0, jnp.int32(0), cfg, last_index=last_index
    )
    use, carry = jax.random.split(key)
    tok = sample_tokens(
        logits[:, 0], temp[None], top_k[None], top_p[None], use[None],
        max_k=max_top_k,
    )[0]
    # [L, 1, bucket, Hkv, hd] -> head-major [L, Hkv, bucket, hd]
    pk = kv.k[:, 0].transpose(0, 2, 1, 3)
    pv = kv.v[:, 0].transpose(0, 2, 1, 3)
    return tok, carry, pk, pv


def _tail_prefill_step(params, ctx_k, ctx_v, tail, start, last_index, temp,
                       top_k, top_p, key, *, cfg: LlamaConfig, tb: int,
                       max_top_k: int):
    """Prefill only the unshared tail of a prefix-matched prompt: the
    gathered prefix K/V (``[L, 1, C, Hkv, hd]``, positions ``[0, start)``
    valid) is the attention context, the tail bucket runs from absolute
    position ``start``, and only the prompt's true last position projects
    through lm_head. Bitwise-identical to the full prefill's logits —
    forward_with_cache masks by absolute position and every masked term is
    exactly zero."""
    from tony_tpu.models.generate import (
        KVCache, forward_with_cache, sample_tokens,
    )

    logits, kv = forward_with_cache(
        params, tail, KVCache(ctx_k, ctx_v), start, cfg,
        last_index=last_index,
    )
    use, carry = jax.random.split(key)
    tok = sample_tokens(
        logits[:, 0], temp[None], top_k[None], top_p[None], use[None],
        max_k=max_top_k,
    )[0]
    # the tail's K/V, head-major [L, Hkv, tb, hd], for the block scatter
    tk = lax.dynamic_slice_in_dim(kv.k[:, 0], start, tb, axis=1)
    tv = lax.dynamic_slice_in_dim(kv.v[:, 0], start, tb, axis=1)
    return tok, carry, tk.transpose(0, 2, 1, 3), tv.transpose(0, 2, 1, 3)


def _q_mm(h, lp, name, quant_weights, impl):
    """One decode matmul: the bf16 master weight, or its int8 copy through
    the fused dequant-matmul (ops/quant_mm.py) when quantized."""
    if quant_weights:
        return quant_matmul(h, lp[name + "_q"], lp[name + "_s"], impl=impl)
    return h @ lp[name]


def _decode_step(params, cache: PagedKVCache, table, state: _SlotState, *,
                 cfg: LlamaConfig, decode_impl: str, kv_block: int,
                 max_top_k: int, monitors: bool = False, quant_kv: str = "",
                 quant_weights: bool = False):
    """One token for every slot: write K/V at each row's position (into
    the physical block its table names — dead slots steer to the scratch
    block so a freed, possibly reallocated block can never be corrupted),
    attend over its written prefix through the table, sample with its own
    stream. ``monitors`` additionally returns the fused per-slot health
    monitors (logits nonfinite counts + sampling entropy, obs/health.py);
    the dict is empty when disarmed so the signature stays stable.

    ``quant_kv``: the pools are block-scaled quantized — writes fold into
    the running block scale and the attention kernels dequantize inline
    through the scale pools, which ride the layer scan next to their
    payloads. ``quant_weights``: the seven layer matmuls + lm_head read
    int8 weights through the fused dequant-matmul."""
    from tony_tpu.models.generate import sample_tokens

    qmax = kv_quant_spec(quant_kv)[1] if quant_kv else 0.0
    S = state.last_tok.shape[0]
    hd, H, Hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    x = params["tok_emb"][state.last_tok]                  # [S, D]
    pos = cache.lengths                                    # [S]
    ang = pos.astype(jnp.float32)[:, None] * rope_freqs(cfg)[None, :]
    cos = jnp.cos(ang)[:, None, :]                         # [S, 1, half]
    sin = jnp.sin(ang)[:, None, :]

    def rope(t):  # [S, H', hd], per-row angle
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [t1 * cos - t2 * sin, t2 * cos + t1 * sin], axis=-1
        ).astype(t.dtype)

    # paged write target: row s's position lands in physical block
    # table[s, pos // block] at offset pos % block
    bi = pos // kv_block
    off = pos % kv_block
    pid = jnp.where(
        state.live,
        jnp.take_along_axis(table, bi[:, None], axis=1)[:, 0],
        SCRATCH_BLOCK,
    )

    def block(x, layer):
        if quant_kv:
            lp, k_pool, v_pool, k_sc, v_sc = layer
        else:
            lp, k_pool, v_pool = layer
            k_sc = v_sc = None
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        mm = partial(_q_mm, quant_weights=quant_weights, impl=decode_impl)
        q = rope(mm(h, lp, "wq").reshape(S, H, hd))
        k_new = rope(mm(h, lp, "wk").reshape(S, Hkv, hd))
        v_new = mm(h, lp, "wv").reshape(S, Hkv, hd)
        # per-row scatter into the pool (advanced indices pid/off move the
        # row dim to the front: the slice value is [S, Hkv, hd] directly);
        # quantized pools fold the written amax into the block scale
        if quant_kv:
            k_pool, k_sc = scatter_block_kv(
                k_pool, k_new, pid, off, scale=k_sc, qmax=qmax
            )
            v_pool, v_sc = scatter_block_kv(
                v_pool, v_new, pid, off, scale=v_sc, qmax=qmax
            )
        else:
            k_pool = scatter_block_kv(k_pool, k_new, pid, off)
            v_pool = scatter_block_kv(v_pool, v_new, pid, off)
        attn = decode_attention(
            q, k_pool, v_pool, pos + 1, tables=table,
            impl=decode_impl, block=kv_block, k_scale=k_sc, v_scale=v_sc,
        )
        x = x + mm(attn.reshape(S, H * hd), lp, "wo")
        h2 = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
        delta = mm(jax.nn.silu(mm(h2, lp, "w1")) * mm(h2, lp, "w3"),
                   lp, "w2")
        pools = (k_pool, v_pool) if not quant_kv else (
            k_pool, v_pool, k_sc, v_sc
        )
        return x + delta, pools

    xs = (params["layers"], cache.k, cache.v)
    if quant_kv:
        xs = xs + (cache.k_scale, cache.v_scale)
    x, pools = lax.scan(block, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if quant_weights:
        logits = quant_matmul(
            x, params["lm_head_q"], params["lm_head_s"], impl=decode_impl
        ).astype(jnp.float32)                              # [S, V]
    else:
        logits = (x @ params["lm_head"]).astype(jnp.float32)   # [S, V]

    both = jax.vmap(jax.random.split)(state.rng)           # [S, 2, 2]
    nxt = sample_tokens(
        logits, state.temp, state.top_k, state.top_p, both[:, 0],
        max_k=max_top_k,
    )
    has_eos = state.eos >= 0
    nxt = jnp.where(state.done & has_eos, state.eos, nxt)
    done = state.done | (has_eos & (nxt == state.eos))
    lengths = cache.lengths + state.live.astype(jnp.int32)
    new_state = state._replace(last_tok=nxt, rng=both[:, 1], done=done)
    hmon = health.decode_monitors(logits) if monitors else {}
    return PagedKVCache(*pools[:2], lengths, *pools[2:]), new_state, nxt, hmon


def _spec_decode_step(params, cache: PagedKVCache, table, state: _SlotState,
                      drafts, draft_len, *, cfg: LlamaConfig,
                      decode_impl: str, kv_block: int, max_top_k: int,
                      draft_k: int, monitors: bool = False,
                      quant_kv: str = "", quant_weights: bool = False):
    """The speculative verify step: feed every row G = draft_k + 1 tokens
    (its last sampled token + its k drafts, short drafts padded), write
    their K/V at positions pos..pos+k, attend all G query positions in
    ONE widened forward (ops/decode_attention.py's multi-query form),
    then run the rejection rule (serve/spec.py) so the emitted prefix is
    draw-for-draw what the 1-wide step would have sampled. Rollback is
    free: ``lengths`` advance by exactly the emitted count, so rejected
    positions' K/V sit beyond every length mask and are overwritten by
    later steps; padding positions past a row's draft length steer to the
    scratch block and never touch real storage at all.

    Quantization (``quant_kv``/``quant_weights``) rides exactly as in
    :func:`_decode_step`. Rejected draft positions' amaxes stay folded
    into their blocks' running scales — scales only ever grow, so a
    rollback never leaves a block whose payload overflows its scale."""
    qmax = kv_quant_spec(quant_kv)[1] if quant_kv else 0.0
    S = state.last_tok.shape[0]
    G = draft_k + 1
    hd, H, Hkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    # fed tokens: [last_tok, d_1 .. d_k] — token j conditions position
    # pos + j and its logits score the candidate at pos + j + 1
    tokens_in = jnp.concatenate([state.last_tok[:, None], drafts], axis=1)
    x = params["tok_emb"][tokens_in]                       # [S, G, D]
    pos0 = cache.lengths                                   # [S]
    goff = jnp.arange(G, dtype=jnp.int32)
    pos = pos0[:, None] + goff[None, :]                    # [S, G]
    ang = pos.astype(jnp.float32)[..., None] * rope_freqs(cfg)[None, None, :]
    cos = jnp.cos(ang)[:, :, None, :]                      # [S, G, 1, half]
    sin = jnp.sin(ang)[:, :, None, :]

    def rope(t):  # [S, G, H', hd], per-position angle
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        return jnp.concatenate(
            [t1 * cos - t2 * sin, t2 * cos + t1 * sin], axis=-1
        ).astype(t.dtype)

    # paged write targets: position g of row s lands in physical block
    # table[s, (pos0+g) // block] at offset (pos0+g) % block; dead rows
    # and padding positions past the row's draft length steer to scratch
    bi = pos // kv_block
    off = pos % kv_block
    write_ok = state.live[:, None] & (goff[None, :] <= draft_len[:, None])
    M = table.shape[1]
    pid = jnp.where(
        write_ok,
        jnp.take_along_axis(table, jnp.minimum(bi, M - 1), axis=1),
        SCRATCH_BLOCK,
    )
    off = jnp.where(write_ok, off, 0)

    def block(x, layer):
        if quant_kv:
            lp, k_pool, v_pool, k_sc, v_sc = layer
        else:
            lp, k_pool, v_pool = layer
            k_sc = v_sc = None
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        mm = partial(_q_mm, quant_weights=quant_weights, impl=decode_impl)
        q = rope(mm(h, lp, "wq").reshape(S, G, H, hd))
        k_new = rope(mm(h, lp, "wk").reshape(S, G, Hkv, hd))
        v_new = mm(h, lp, "wv").reshape(S, G, Hkv, hd)
        if quant_kv:
            k_pool, k_sc = scatter_block_kv(
                k_pool, k_new, pid, off, scale=k_sc, qmax=qmax
            )
            v_pool, v_sc = scatter_block_kv(
                v_pool, v_new, pid, off, scale=v_sc, qmax=qmax
            )
        else:
            k_pool = scatter_block_kv(k_pool, k_new, pid, off)
            v_pool = scatter_block_kv(v_pool, v_new, pid, off)
        # multi-query paged attention: query g of row s sees positions
        # < pos0[s] + g + 1 (lengths arg = pos0 + G, kernel offsets per g)
        attn = decode_attention(
            q, k_pool, v_pool, pos0 + G, tables=table,
            impl=decode_impl, block=kv_block, k_scale=k_sc, v_scale=v_sc,
        )
        x = x + mm(attn.reshape(S, G, H * hd), lp, "wo")
        h2 = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
        delta = mm(jax.nn.silu(mm(h2, lp, "w1")) * mm(h2, lp, "w3"),
                   lp, "w2")
        pools = (k_pool, v_pool) if not quant_kv else (
            k_pool, v_pool, k_sc, v_sc
        )
        return x + delta, pools

    xs = (params["layers"], cache.k, cache.v)
    if quant_kv:
        xs = xs + (cache.k_scale, cache.v_scale)
    x, pools = lax.scan(block, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if quant_weights:
        logits = quant_matmul(
            x, params["lm_head_q"], params["lm_head_s"], impl=decode_impl
        ).astype(jnp.float32)                              # [S, G, V]
    else:
        logits = (x @ params["lm_head"]).astype(jnp.float32)   # [S, G, V]

    toks, n_emit, _n_acc, last_tok, new_rng, done = verify_and_accept(
        logits, drafts, draft_len, state, max_top_k=max_top_k,
    )
    live = state.live
    lengths = pos0 + n_emit * live.astype(jnp.int32)
    new_state = state._replace(
        last_tok=jnp.where(live, last_tok, state.last_tok),
        rng=jnp.where(live[:, None], new_rng, state.rng),
        done=jnp.where(live, done, state.done),
    )
    if monitors:
        # health rules judge the step by the LAST emitted position's
        # logits — the same autoregressive frontier the 1-wide step
        # reports, so accepted drafts can't trip entropy/nonfinite rules
        last_idx = jnp.maximum(n_emit - 1, 0)
        frontier = jnp.take_along_axis(
            logits, last_idx[:, None, None], axis=1
        )[:, 0]
        hmon = health.decode_monitors(frontier)
    else:
        hmon = {}
    return (
        PagedKVCache(*pools[:2], lengths, *pools[2:]),
        new_state, toks, n_emit, hmon,
    )


@functools.lru_cache(maxsize=512)
def _spec_decode_fn(cfg: LlamaConfig, decode_impl: str, kv_block: int,
                    max_top_k: int, draft_k: int, monitors: bool = False,
                    quant_kv: str = "", quant_weights: bool = False):
    """Jitted speculative verify step — same cache discipline as
    :func:`_decode_fn` (per model/kernel knobs, table not donated)."""
    return jax.jit(
        partial(
            _spec_decode_step, cfg=cfg, decode_impl=decode_impl,
            kv_block=kv_block, max_top_k=max_top_k, draft_k=draft_k,
            monitors=monitors, quant_kv=quant_kv,
            quant_weights=quant_weights,
        ),
        donate_argnums=(1, 3),
    )


def _aot_spec_decode(cfg: LlamaConfig, decode_impl: str, kv_block: int,
                     max_top_k: int, draft_k: int, params, cache, table,
                     state, ledger, *, monitors: bool = False,
                     quant_kv: str = "", quant_weights: bool = False):
    fn = _spec_decode_fn(cfg, decode_impl, kv_block, max_top_k, draft_k,
                         monitors, quant_kv, quant_weights)
    S = state.last_tok.shape[0]
    try:
        shard = jax.tree.leaves(params)[0].sharding
        key = ("spec", cfg, decode_impl, kv_block, max_top_k, draft_k,
               monitors, quant_kv, quant_weights,
               cache.k.shape, str(cache.k.dtype), table.shape,
               hash(shard), shard)
    except Exception:
        return fn
    name = (f"serve.decode_spec[slots={S},blocks={cache.k.shape[1]},"
            f"attended={table.shape[1]},k={draft_k}]")
    avals = (
        params, cache, table, state,
        _sds((S, draft_k), jnp.int32), _sds((S,), jnp.int32),
    )
    return _aot_compile(
        fn, avals, key, name, ledger, cache=_aot_decode_cache,
    )


__all__ = [
    "AdmissionRejected", "Completion", "Engine", "Request", "ServeConfig",
]

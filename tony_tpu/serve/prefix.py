"""Radix-tree prefix store: cross-request KV reuse over the paged cache.

At millions of users the dominant redundant serving work is re-prefilling
the shared system/template prefix on every request. This module is the
sharing policy over serve/cache.py's physical-block pool (SGLang
RadixAttention lineage, arXiv:2312.07104, over vLLM-style paged KV,
arXiv:2309.06180):

- the tree is keyed by **token blocks**: each node owns exactly one
  ``kv_block``-sized token chunk and the physical block holding that
  chunk's K/V across all layers; a path root -> node spells a prefix;
- **admission matching** walks full chunks by hash (dict lookup per
  block), then extends *into* the next block by longest common token
  prefix — so a match can end mid-block;
- matched full blocks are mapped **shared** into the slot's table (the
  slot takes a pool reference, never writes them — prefill starts at the
  match boundary and decode appends strictly beyond the prompt);
- a mid-block match is the **copy-on-write** case: the slot would write
  its unshared tail into that block, so admission hands it a private copy
  first (``MatchResult.partial`` names the source block to copy);
- after prefill the prompt's full blocks are **inserted**, each new node
  taking its own pool reference — the slot can finish and free, the
  prefix stays resident;
- unreferenced-by-slots nodes persist until **LRU-by-leaf eviction**
  under the ``serve.prefix.budget_mb`` HBM budget (or allocation
  pressure): leaves drop in last-use order, releasing their pool
  reference — a block still referenced by a live slot leaves the *index*
  but frees no HBM until that slot finishes.

The store is pure host-side bookkeeping: matching and hashing run on the
admission path in plain Python (GL001 — no host syncs in jitted code; the
device only ever sees block tables). ``_lock`` guards tree mutations
against concurrent stats readers (RPC threads calling
``Engine.stats_snapshot``); nothing blocking runs under it (GL004).
"""

from __future__ import annotations

import threading
from typing import NamedTuple, Sequence


class _Node:
    """One token block: ``chunk`` (the block's tokens), ``phys`` (the
    physical block id holding its K/V), children keyed by their full
    chunk tuple (hash lookup per block on the match walk)."""

    __slots__ = ("chunk", "phys", "parent", "children", "last_used", "hits")

    def __init__(self, chunk: tuple[int, ...], phys: int, parent: "_Node | None"):
        self.chunk = chunk
        self.phys = phys
        self.parent = parent
        self.children: dict[tuple[int, ...], _Node] = {}
        self.last_used = 0
        self.hits = 0


class MatchResult(NamedTuple):
    """Longest cached prefix of a prompt.

    ``length`` tokens matched; ``full`` — physical block ids covering the
    matched *full* blocks (safe to map shared); ``partial`` — physical id
    of the block a mid-block match ended in (the COW source: the slot
    must copy it before writing its tail), or None when the match ended
    exactly on a block boundary.
    """

    length: int
    full: tuple[int, ...]
    partial: int | None


class PrefixStore:
    """See module docstring. One instance per engine; ``block`` must be
    the engine's ``kv_block`` and ``block_bytes`` the HBM cost of one
    physical block (serve/cache.py:block_bytes)."""

    def __init__(self, block: int, block_bytes: int, budget_bytes: int = 0):
        self.block = int(block)
        self.block_bytes = int(block_bytes)
        # 0 = unbounded (tests); the engine passes serve.prefix.budget_mb
        self.budget_bytes = int(budget_bytes)
        self._root = _Node((), -1, None)
        self._lock = threading.Lock()
        self._clock = 0
        self._n_nodes = 0
        self.hit_tokens = 0      # tokens served from the store (cumulative)
        self.prompt_tokens = 0   # prompt tokens seen (hit-rate denominator)
        self.evicted_blocks = 0

    # --- stats ----------------------------------------------------------------

    @property
    def n_nodes(self) -> int:
        return self._n_nodes

    @property
    def resident_bytes(self) -> int:
        """HBM pinned by the tree's own references (one block per node)."""
        return self._n_nodes * self.block_bytes

    @property
    def hit_rate(self) -> float:
        if not self.prompt_tokens:
            return 0.0
        return self.hit_tokens / self.prompt_tokens

    def stats(self) -> dict[str, float]:
        with self._lock:
            return {
                "prefix_nodes": float(self._n_nodes),
                "prefix_resident_mb": round(self.resident_bytes / 2**20, 3),
                "prefix_hit_tokens": float(self.hit_tokens),
                "prefix_hit_rate": round(self.hit_rate, 4),
                "prefix_evicted_blocks": float(self.evicted_blocks),
            }

    # --- matching -------------------------------------------------------------

    def _walk_locked(
        self, tokens: Sequence[int], limit: int
    ) -> tuple[_Node, list[_Node], int, "_Node | None", int]:
        """THE radix walk (caller holds ``_lock``): full chunks by hash,
        then longest-common-prefix into the best child — shared by
        admission matching and the speculative draft source
        (``longest_extension``) so the two can never diverge. Returns
        ``(node, full_nodes, matched, best, best_cp)``: the deepest
        fully-matched node, the full-chunk chain under it, total tokens
        matched, and the partially-entered child (``best_cp`` of its
        chunk consumed) or None when the match ends on a boundary."""
        B = self.block
        node = self._root
        full_nodes: list[_Node] = []
        i = 0
        while i < limit:
            if limit - i >= B:
                child = node.children.get(tuple(tokens[i:i + B]))
                if child is not None:
                    node = child
                    full_nodes.append(node)
                    i += B
                    continue
            # no full-chunk match left: extend into the best child by
            # longest common token prefix (the mid-block / COW case)
            want = tuple(tokens[i:limit])
            best_cp = 0
            best: _Node | None = None
            for child in node.children.values():
                cp = _common_prefix(child.chunk, want)
                if cp > best_cp:
                    best_cp, best = cp, child
            return node, full_nodes, i + best_cp, best, best_cp
        return node, full_nodes, i, None, 0

    def match(self, tokens: Sequence[int], limit: int) -> MatchResult:
        """Longest cached prefix of ``tokens[:limit]``. ``limit`` is the
        admission cap (``plen - 1``: at least one token must remain for
        prefill to compute the first sampled logits). Accounts the hit
        into the hit-rate counters."""
        full: list[int] = []
        partial: int | None = None
        with self._lock:
            self._clock += 1
            node, full_nodes, i, best, _cp = self._walk_locked(tokens, limit)
            for n in full_nodes:
                n.last_used = self._clock
                n.hits += 1
                full.append(n.phys)
            if best is not None:
                best.last_used = self._clock
                best.hits += 1
                partial = best.phys
            # touch the matched chain so no ancestor is ever older than a
            # descendant (eviction is leaf-first, LRU by leaf)
            walk = node
            while walk is not self._root:
                walk.last_used = self._clock
                walk = walk.parent
        return MatchResult(i, tuple(full), partial)

    def longest_extension(self, tokens: Sequence[int], max_k: int) -> list[int]:
        """Up to ``max_k`` tokens the store predicts follow ``tokens``:
        walk the radix path the WHOLE context follows (the exact
        ``match`` semantics via ``_walk_locked`` — full chunks by hash,
        then longest common prefix into the best child, so a context may
        end mid-block), then read onward along the tree, descending into
        the most-hit child at each node boundary. Returns ``[]`` when the
        context leaves the tree — the store has never observed any
        continuation of it. The speculative draft source (serve/spec.py):
        pure host-side python (GL001), and read-only — drafting touches
        neither the LRU clock nor the hit counters, so it cannot perturb
        eviction order or the admission hit-rate."""
        if max_k <= 0:
            return []
        out: list[int] = []
        with self._lock:
            node, _full, matched, best, best_cp = self._walk_locked(
                tokens, len(tokens)
            )
            if matched != len(tokens):
                return []
            if best is not None:
                # mid-block end: the remainder of the partially-entered
                # chunk is the first (and already-ordered) continuation
                out.extend(best.chunk[best_cp:])
                node = best
            while len(out) < max_k and node.children:
                node = max(
                    node.children.values(),
                    key=lambda c: (c.hits, c.last_used),
                )
                out.extend(node.chunk)
        return out[:max_k]

    def record_prompt(self, plen: int, hit: int) -> None:
        """Hit-rate accounting: ``hit`` of ``plen`` prompt tokens were
        served from the store (the engine calls this per admission with
        the match length it actually *used*)."""
        with self._lock:
            self.prompt_tokens += int(plen)
            self.hit_tokens += int(hit)

    # --- insertion ------------------------------------------------------------

    def insert(self, tokens: Sequence[int], phys: Sequence[int], retain) -> int:
        """Register the full blocks of ``tokens`` (length must be a
        multiple of ``block``): walk existing nodes, create the rest with
        the slot's physical ids from ``phys``. Each *created* node calls
        ``retain(pid)`` — the tree's own pool reference, independent of
        the inserting slot's. Returns the number of nodes created."""
        B = self.block
        n_full = len(tokens) // B
        created = 0
        with self._lock:
            self._clock += 1
            node = self._root
            for bi in range(n_full):
                chunk = tuple(tokens[bi * B:(bi + 1) * B])
                child = node.children.get(chunk)
                if child is None:
                    child = _Node(chunk, int(phys[bi]), node)
                    retain(child.phys)
                    node.children[chunk] = child
                    self._n_nodes += 1
                    created += 1
                child.last_used = self._clock
                node = child
        return created

    # --- eviction -------------------------------------------------------------

    def evict_lru(self, release) -> int | None:
        """Drop the least-recently-used *leaf* and release its pool
        reference via ``release(pid)``. Returns the freed physical id, or
        None when the tree is empty. The block's HBM frees only when no
        live slot still references it (release returns False then — the
        index entry is gone either way)."""
        with self._lock:
            leaf = self._lru_leaf()
            if leaf is None:
                return None
            del leaf.parent.children[leaf.chunk]
            self._n_nodes -= 1
            self.evicted_blocks += 1
            pid = leaf.phys
        release(pid)
        return pid

    def evict_to_budget(self, release) -> int:
        """LRU-evict leaves until resident bytes fit the budget (0 =
        unbounded). Returns how many nodes were dropped."""
        if not self.budget_bytes:
            return 0
        dropped = 0
        while self.resident_bytes > self.budget_bytes:
            if self.evict_lru(release) is None:
                break
            dropped += 1
        return dropped

    def _lru_leaf(self) -> _Node | None:
        # walk the whole tree for the oldest leaf: tree sizes are bounded
        # by the block budget, so O(nodes) here beats carrying a heap
        # through every match/insert touch
        best: _Node | None = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif node is not self._root:
                if best is None or node.last_used < best.last_used:
                    best = node
        return best


def _common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


def fingerprint(tokens: Sequence[int], n: int) -> int | None:
    """Routing fingerprint of a prompt's leading ``n`` tokens (the
    frontend's prefix-affinity key, serve/frontend.py). None when the
    prompt is shorter than ``n`` — too little shared prefix to be worth
    pinning a host for."""
    if n <= 0 or len(tokens) < n:
        return None
    return hash(tuple(int(t) for t in tokens[:n]))


__all__ = ["MatchResult", "PrefixStore", "fingerprint"]

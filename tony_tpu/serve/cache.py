"""Paged block KV cache: a refcounted physical-block pool + per-slot
indirection tables.

The first engine cache was slot-owns-contiguous-blocks: ``[L, S, Hkv, T, hd]``
with slot ``s`` owning positions ``[0, T)`` of its own row. That layout
cannot share anything — two requests with the same system/template prefix
each pay a full prefill and hold duplicate K/V. Here the cache is a **pool**
of physical blocks plus an indirection map (vLLM's paged KV, arXiv:2309.06180,
as the substrate for SGLang-style radix prefix sharing, arXiv:2312.07104):

- buffers are ``[L, P, Hkv, block, hd]`` head-major — ``P`` physical blocks,
  each holding ``block`` token positions across ALL layers (one allocation =
  one refcount covering every layer's K and V for that token span);
- a per-slot **block table** (host-planned, device-threaded through
  ops/decode_attention.py's scan and pallas impls) maps logical block ``j``
  of slot ``s`` to a physical block id — slots no longer own contiguous
  storage, so a physical block can appear in many tables at once;
- physical block **0 is the scratch block**: never allocated, dead slots'
  decode writes are steered into it so a freed (and possibly reallocated)
  block can never be corrupted by a stale slot;
- :class:`BlockPool` carries the host-side refcounts — a block is shared by
  construction (live slots + the prefix store each hold a reference) and
  returns to the free list only when its refcount hits zero, which is what
  lets ``shrink`` free real HBM without ever reclaiming a block the prefix
  store still pins;
- the pool grows by doubling and shrinks by halving (bounded decode-step
  recompiles, one per pool size), and attention cost scales with the
  *table width* (active blocks per slot), not ``max_len`` — the
  tests/test_perf_guard.py contract carries over from the contiguous
  layout unchanged.

The sharing policy itself (which blocks are safe to share, copy-on-write,
eviction) lives in serve/prefix.py; this module only knows physical blocks
and reference counts.

**Quantized pools** (ROADMAP quantized serving): with ``quant_kv`` set the
K/V pools store int8 (or fp8 ``e4m3``) and a parallel *scale pool*
``[L, P, Hkv]`` float32 carries one scale per physical block per kv head —
block granularity because the block is already the unit of allocation,
sharing, and copy-on-write, so a shared block carries its scales with it
and a COW copy duplicates exactly one scale row. Writes quantize in place
(:func:`scatter_block_kv` with ``k_scale`` given): the written positions'
amax folds into the running block scale, and when the scale grows the
block's existing entries requantize by ``old/new`` (exactly a no-op when
the scale is unchanged — round(q * 1.0) == q). A scale of zero marks a
block with no real content (freshly allocated, never written): requantizing
by ``0/new`` zeroes whatever garbage a reused block carried, so the engine
only has to zero the scale row at allocation, never the block itself.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# physical block 0 is reserved: dead slots' writes land here, and table
# entries beyond a slot's allocation point at it (their tiles are masked
# by the per-row length, but the DMA still needs a valid index)
SCRATCH_BLOCK = 0

# kv_dtype knob values -> (storage dtype, largest representable magnitude).
# fp8 e4m3 is the stretch format behind the same knob; it only registers
# where the jax build carries the dtype (kv_quant_spec raises otherwise).
KV_QUANT_DTYPES = ("int8", "fp8_e4m3")


def kv_quant_spec(kv_dtype: str) -> tuple[jnp.dtype, float]:
    """Resolve a ``serve.quant.kv_dtype`` value to (storage dtype, qmax)."""
    if kv_dtype == "int8":
        return jnp.dtype(jnp.int8), 127.0
    if kv_dtype == "fp8_e4m3":
        if not hasattr(jnp, "float8_e4m3fn"):
            raise ValueError(
                "kv_dtype 'fp8_e4m3' needs a jax build with float8_e4m3fn"
            )
        return jnp.dtype(jnp.float8_e4m3fn), 448.0
    raise ValueError(
        f"unknown kv quant dtype {kv_dtype!r} (expected one of "
        f"{KV_QUANT_DTYPES})"
    )


class PagedKVCache(NamedTuple):
    """k/v: [L, P, Hkv, block, hd] physical-block pools; lengths: [S].

    Quantized pools additionally carry ``k_scale``/``v_scale``
    ``[L, P, Hkv]`` float32 — one dequantization scale per physical block
    per kv head (None on an unquantized cache)."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @property
    def n_blocks(self) -> int:
        """P — physical blocks currently backed (scratch included)."""
        return self.k.shape[1]

    @property
    def block(self) -> int:
        """Token positions per physical block."""
        return self.k.shape[3]

    @property
    def slots(self) -> int:
        return self.lengths.shape[0]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def create_cache(
    cfg, slots: int, n_blocks: int, block: int, dtype=None,
    quant_kv: str = "",
) -> PagedKVCache:
    """Fresh pool of ``n_blocks`` physical blocks (block 0 = scratch).
    With ``quant_kv`` ('int8' | 'fp8_e4m3') the pools store the quantized
    dtype plus zeroed per-block-per-head scale pools (scale 0 = block
    holds nothing real yet)."""
    shape = (cfg.n_layers, n_blocks, cfg.n_kv_heads, block, cfg.head_dim)
    if quant_kv:
        qdt, _ = kv_quant_spec(quant_kv)
        sc = (cfg.n_layers, n_blocks, cfg.n_kv_heads)
        return PagedKVCache(
            jnp.zeros(shape, qdt), jnp.zeros(shape, qdt),
            jnp.zeros((slots,), jnp.int32),
            jnp.zeros(sc, jnp.float32), jnp.zeros(sc, jnp.float32),
        )
    dt = dtype or cfg.dtype
    return PagedKVCache(
        jnp.zeros(shape, dt), jnp.zeros(shape, dt),
        jnp.zeros((slots,), jnp.int32),
    )


def grow_cache(cache: PagedKVCache, n_blocks: int) -> PagedKVCache:
    """Extend the pool to ``n_blocks`` physical blocks (zero-filled)."""
    extra = n_blocks - cache.n_blocks
    if extra <= 0:
        return cache
    pad = [(0, 0), (0, extra), (0, 0), (0, 0), (0, 0)]
    if cache.quantized:
        spad = pad[:3]
        return PagedKVCache(
            jnp.pad(cache.k, pad), jnp.pad(cache.v, pad), cache.lengths,
            jnp.pad(cache.k_scale, spad), jnp.pad(cache.v_scale, spad),
        )
    return PagedKVCache(
        jnp.pad(cache.k, pad), jnp.pad(cache.v, pad), cache.lengths
    )


def shrink_cache(cache: PagedKVCache, n_blocks: int) -> PagedKVCache:
    """Release physical blocks beyond ``n_blocks``. The caller guarantees
    every id >= ``n_blocks`` is FREE (``BlockPool.shrink_target`` reports
    the lowest safe size — a block pinned by the prefix store or a live
    slot bounds how far the pool can shrink)."""
    if n_blocks >= cache.n_blocks:
        return cache
    if cache.quantized:
        return PagedKVCache(
            cache.k[:, :n_blocks], cache.v[:, :n_blocks], cache.lengths,
            cache.k_scale[:, :n_blocks], cache.v_scale[:, :n_blocks],
        )
    return PagedKVCache(
        cache.k[:, :n_blocks], cache.v[:, :n_blocks], cache.lengths
    )


def blocks_for(length: int, block: int) -> int:
    """ceil(length / block), minimum 1."""
    return max(1, math.ceil(length / block))


def quantize_values(vals: jax.Array, scale: jax.Array, qmax: float,
                    qdtype) -> jax.Array:
    """``vals / scale`` clipped to the quantized range (rounded for integer
    storage; fp8 rounds in the cast). ``scale`` broadcasts against
    ``vals``; a zero scale maps everything to zero (nothing real stored)."""
    q = vals.astype(jnp.float32) / jnp.maximum(scale, 1e-30)
    q = jnp.clip(q, -qmax, qmax)
    # branch is on the STATIC storage dtype, not a traced value
    if jnp.issubdtype(jnp.dtype(qdtype), jnp.integer):  # graft-lint: disable=GL002
        q = jnp.round(q)
    return q.astype(qdtype)


def dequantize_values(q: jax.Array, scale: jax.Array, out_dtype) -> jax.Array:
    """Stored values back to real ones: ``q * scale`` (broadcast)."""
    return (q.astype(jnp.float32) * scale).astype(out_dtype)


def _rescale_stored(q: jax.Array, factor: jax.Array, qmax: float) -> jax.Array:
    """Requantize stored values by ``factor = old_scale / new_scale``
    (broadcast). factor == 1 is exact (round(q * 1.0) == q for every
    representable q); factor == 0 zeroes a block whose scale was 0 —
    garbage in a freshly allocated block never survives its first write."""
    f = q.astype(jnp.float32) * factor
    f = jnp.clip(f, -qmax, qmax)
    # branch is on the STATIC storage dtype, not a traced value
    if jnp.issubdtype(q.dtype, jnp.integer):  # graft-lint: disable=GL002
        f = jnp.round(f)
    return f.astype(q.dtype)


def _quant_write_rows(pool, scale, new, pids, offs, qmax):
    """One quantized position-per-row write: ``new [S, Hkv, hd]`` lands at
    ``(pids[s], offs[s])``. Gather the touched blocks + scales, fold the
    written amax into the running block scale, requantize the existing
    entries by old/new, insert the quantized row, scatter both back."""
    S = new.shape[0]
    blk = jnp.take(pool, pids, axis=0)                  # [S, Hkv, blk, hd]
    sc = jnp.take(scale, pids, axis=0)                  # [S, Hkv]
    amax = jnp.max(jnp.abs(new.astype(jnp.float32)), axis=-1)   # [S, Hkv]
    sc_new = jnp.maximum(sc, amax / qmax)
    factor = jnp.where(sc_new > 0, sc / jnp.maximum(sc_new, 1e-30), 0.0)
    blk = _rescale_stored(blk, factor[..., None, None], qmax)
    row = quantize_values(new, sc_new[..., None], qmax, pool.dtype)
    # advanced indices (rows on axis 0, offs on axis 2) are non-adjacent:
    # the indexed result moves them to the front — exactly row's layout
    blk = blk.at[jnp.arange(S), :, offs, :].set(row)
    # duplicate pids occur only for scratch-steered rows (dead slots,
    # padding) — scratch content is garbage by contract, any winner is fine
    return pool.at[pids].set(blk), scale.at[pids].set(sc_new)


def scatter_block_kv(pool: jax.Array, new: jax.Array, pids: jax.Array,
                     offs: jax.Array, scale: jax.Array | None = None,
                     qmax: float = 127.0):
    """Paged KV write into ONE layer's ``[P, Hkv, block, hd]`` pool.

    ``pids``/``offs`` name each new entry's physical block and in-block
    offset. With 1-D ``[S]`` indices ``new`` is ``[S, Hkv, hd]`` (the
    classic one-token decode step); with 2-D ``[S, G]`` indices it is
    ``[S, G, Hkv, hd]`` — the speculative multi-position write
    (serve/spec.py): row s's G draft positions land in one scatter.
    Entries that must not land anywhere real (dead slots, padding beyond
    a row's draft length) are the CALLER's job to steer to
    ``SCRATCH_BLOCK``. The advanced indices (``pids`` on axis 0, ``offs``
    on axis 2) are non-adjacent, so the indexed result moves the index
    dims to the front — exactly ``new``'s layout, no transpose needed.

    With ``scale`` given (a quantized pool's ``[P, Hkv]`` scale rows for
    this layer) the write QUANTIZES: the written positions' amax folds
    into the running block scale, existing entries requantize by
    old/new, and the return value is ``(pool, scale)``. The speculative
    2-D form applies the G positions as G sequential single-position
    passes (G is small and static) so two writes into the same block
    compound their scale updates correctly."""
    if scale is None:
        return pool.at[pids, :, offs, :].set(new)
    if pids.ndim == 1:
        return _quant_write_rows(pool, scale, new, pids, offs, qmax)
    for g in range(pids.shape[1]):
        pool, scale = _quant_write_rows(
            pool, scale, new[:, g], pids[:, g], offs[:, g], qmax
        )
    return pool, scale


def quant_scatter_span(pool, scale, new, pids, offs, ub, qmax):
    """Quantized prefill-span write into ONE layer's pool: position ``i``
    of ``new [Hkv, W, hd]`` lands at ``(pids[i], offs[i])``. ``ub`` is the
    touched-block id set (host-computed ``np.unique`` of ``pids``, padded
    with scratch to a static width) — the requantization pass runs once
    per touched block, not once per position. Scale updates use a
    scatter-max so many positions landing in one block fold their amaxes
    correctly in a single pass. Returns ``(pool, scale)``.

    Vmapped over layers by the engine's scatter step (serve/engine.py):
    the per-layer form keeps the gathered requant transient at one layer's
    touched blocks."""
    needed = jnp.max(jnp.abs(new.astype(jnp.float32)), axis=-1) / qmax
    # needed [Hkv, W] -> per-block running max via scatter-max (dup-safe)
    sc_new = scale.at[pids, :].max(needed.T)            # [P, Hkv]
    old_ub = jnp.take(scale, ub, axis=0)                # [nU, Hkv]
    new_ub = jnp.take(sc_new, ub, axis=0)
    factor = jnp.where(new_ub > 0, old_ub / jnp.maximum(new_ub, 1e-30), 0.0)
    blk = jnp.take(pool, ub, axis=0)                    # [nU, Hkv, blk, hd]
    blk = _rescale_stored(blk, factor[..., None, None], qmax)
    # duplicate ub entries are only the scratch padding — identical values
    pool = pool.at[ub].set(blk)
    sc_pos = jnp.take(sc_new, pids, axis=0)             # [W, Hkv]
    row = quantize_values(
        new.transpose(1, 0, 2), sc_pos[..., None], qmax, pool.dtype
    )                                                   # [W, Hkv, hd]
    return pool.at[pids, :, offs, :].set(row), sc_new


class BlockPayload(NamedTuple):
    """Host-side copy of whole physical blocks — the unit of the blockwise
    KV handoff (docs/SERVE.md "Disaggregated serving"). ``k``/``v`` are
    ``[L, n, Hkv, block, hd]`` in the pool's STORAGE dtype (quantized
    payload ships as stored, never dequantized), and on a quantized pool
    ``k_scale``/``v_scale`` ``[L, n, Hkv]`` float32 ride along — a block
    without its scale rows is not decodable, so they travel as one unit."""

    k: np.ndarray
    v: np.ndarray
    k_scale: np.ndarray | None = None
    v_scale: np.ndarray | None = None

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def nbytes(self) -> int:
        n = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n


def export_blocks(cache: PagedKVCache, pids) -> BlockPayload:
    """Gather physical blocks ``pids`` to the host as a BlockPayload.

    The gather pads the id list to a power of two with scratch (bounded
    device-gather signatures, same policy as the engine's context gather)
    and trims on the host. One explicit D2H per call — the handoff is a
    designed sync point on the prefill host, never on a decode step."""
    nb = len(pids)
    pad = 1
    while pad < nb:
        pad *= 2
    padded = np.full(pad, SCRATCH_BLOCK, np.int32)
    padded[:nb] = pids
    idx = jnp.asarray(padded)
    k, v = _gather_blocks_fn(cache.quantized)(cache, idx)
    if cache.quantized:
        (k, ks), (v, vs) = k, v
        return BlockPayload(
            np.asarray(jax.device_get(k))[:, :nb],
            np.asarray(jax.device_get(v))[:, :nb],
            np.asarray(jax.device_get(ks))[:, :nb],
            np.asarray(jax.device_get(vs))[:, :nb],
        )
    return BlockPayload(
        np.asarray(jax.device_get(k))[:, :nb],
        np.asarray(jax.device_get(v))[:, :nb],
    )


@functools.lru_cache(maxsize=None)
def _gather_blocks_fn(quant: bool = False):
    if quant:
        @jax.jit
        def gat_q(cache: PagedKVCache, idx):
            return (
                (jnp.take(cache.k, idx, axis=1),
                 jnp.take(cache.k_scale, idx, axis=1)),
                (jnp.take(cache.v, idx, axis=1),
                 jnp.take(cache.v_scale, idx, axis=1)),
            )
        return gat_q

    @jax.jit
    def gat(cache: PagedKVCache, idx):
        return jnp.take(cache.k, idx, axis=1), jnp.take(cache.v, idx, axis=1)
    return gat


def write_block(cache: PagedKVCache, pid: int, payload: BlockPayload,
                i: int) -> PagedKVCache:
    """Adopt block ``i`` of ``payload`` into physical block ``pid``: the
    decode-host side of the handoff. Payload dtype/shape must match the
    pool exactly (checked by the caller via :func:`payload_compatible`) —
    adoption is a raw store, scale rows included, so a shipped quantized
    block decodes bit-identically to the block the prefill host held."""
    if cache.quantized:
        return _write_block_fn(True)(
            cache, jnp.int32(pid),
            jnp.asarray(payload.k[:, i]), jnp.asarray(payload.v[:, i]),
            jnp.asarray(payload.k_scale[:, i]),
            jnp.asarray(payload.v_scale[:, i]),
        )
    return _write_block_fn(False)(
        cache, jnp.int32(pid),
        jnp.asarray(payload.k[:, i]), jnp.asarray(payload.v[:, i]),
    )


@functools.lru_cache(maxsize=None)
def _write_block_fn(quant: bool = False):
    if quant:
        @jax.jit
        def wr_q(cache: PagedKVCache, pid, kb, vb, ks, vs):
            return cache._replace(
                k=cache.k.at[:, pid].set(kb),
                v=cache.v.at[:, pid].set(vb),
                k_scale=cache.k_scale.at[:, pid].set(ks),
                v_scale=cache.v_scale.at[:, pid].set(vs),
            )
        return wr_q

    @jax.jit
    def wr(cache: PagedKVCache, pid, kb, vb):
        return cache._replace(
            k=cache.k.at[:, pid].set(kb), v=cache.v.at[:, pid].set(vb)
        )
    return wr


def payload_compatible(cache: PagedKVCache, payload: BlockPayload) -> str:
    """'' when ``payload`` can be adopted into ``cache`` verbatim, else
    the reason it cannot (dtype or geometry mismatch — a bf16 host must
    not adopt int8 blocks and silently decode garbage)."""
    want = cache.k.shape[:1] + cache.k.shape[2:]
    got = payload.k.shape[:1] + payload.k.shape[2:]
    if want != got:
        return f"block geometry {got} != pool {want}"
    if jnp.dtype(payload.k.dtype) != jnp.dtype(cache.k.dtype):
        return f"payload dtype {payload.k.dtype} != pool {cache.k.dtype}"
    if cache.quantized and payload.k_scale is None:
        return "quantized pool needs scale rows in the payload"
    if not cache.quantized and payload.k_scale is not None:
        return "unquantized pool cannot adopt scaled payload"
    return ""


def pack_payload(payload: BlockPayload) -> dict:
    """BlockPayload -> wire fields (raw bytes + shape + dtype name), the
    ShipBlocks request body. ``np.tobytes`` round-trips every storage
    dtype bit-exactly (bfloat16/fp8 via their ml_dtypes registrations)."""
    d = {
        "k": payload.k.tobytes(), "v": payload.v.tobytes(),
        "shape": list(payload.k.shape), "dtype": jnp.dtype(payload.k.dtype).name,
    }
    if payload.k_scale is not None:
        d["k_scale"] = np.ascontiguousarray(
            payload.k_scale, np.float32).tobytes()
        d["v_scale"] = np.ascontiguousarray(
            payload.v_scale, np.float32).tobytes()
    return d


def unpack_payload(k: bytes, v: bytes, shape, dtype: str,
                   k_scale: bytes = b"", v_scale: bytes = b"") -> BlockPayload:
    """Wire fields -> BlockPayload (the ShipBlocks server side). Raises
    ValueError on a malformed body — the RPC layer maps it to an error
    response instead of corrupting the pool."""
    shape = tuple(int(s) for s in shape)
    if len(shape) != 5:
        raise ValueError(f"payload shape {shape} is not [L, n, Hkv, blk, hd]")
    dt = jnp.dtype(dtype)
    n = int(np.prod(shape))
    if len(k) != n * dt.itemsize or len(v) != n * dt.itemsize:
        raise ValueError(
            f"payload bytes {len(k)}/{len(v)} do not match shape {shape} "
            f"dtype {dtype}"
        )
    ka = np.frombuffer(k, dtype=dt).reshape(shape)
    va = np.frombuffer(v, dtype=dt).reshape(shape)
    if not k_scale:
        return BlockPayload(ka, va)
    sshape = shape[:3]
    sn = int(np.prod(sshape)) * 4
    if len(k_scale) != sn or len(v_scale) != sn:
        raise ValueError(f"scale bytes do not match shape {sshape}")
    return BlockPayload(
        ka, va,
        np.frombuffer(k_scale, dtype=np.float32).reshape(sshape),
        np.frombuffer(v_scale, dtype=np.float32).reshape(sshape),
    )


def block_bytes(cfg, block: int, dtype=None, quant_kv: str = "") -> int:
    """HBM bytes one physical block costs (K + V across all layers).
    With ``quant_kv`` the payload is priced at the quantized dtype plus
    the block's two scale rows (K and V, float32 per layer per head)."""
    if quant_kv:
        qdt, _ = kv_quant_spec(quant_kv)
        payload = (2 * cfg.n_layers * cfg.n_kv_heads * block * cfg.head_dim
                   * qdt.itemsize)
        scales = 2 * cfg.n_layers * cfg.n_kv_heads * 4
        return payload + scales
    dt = jnp.dtype(dtype or cfg.dtype)
    return 2 * cfg.n_layers * cfg.n_kv_heads * block * cfg.head_dim * dt.itemsize


class BlockPool:
    """Host-side refcounted allocator over physical block ids.

    Pure bookkeeping — no device arrays, no locks (the engine thread is
    the only mutator; see serve/engine.py). A block id is *live* while its
    refcount is positive: live slots hold one reference per table entry,
    and the prefix store holds one per radix node. ``release`` returns a
    block to the free list only at refcount zero — a freed slot therefore
    returns only the blocks nothing else (the store, another slot) still
    references.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError("pool needs the scratch block plus one")
        self._ref = [0] * n_blocks
        # LIFO free list (reuse-warm blocks first); scratch never enters
        self._free = list(range(n_blocks - 1, SCRATCH_BLOCK, -1))

    @property
    def n_blocks(self) -> int:
        return len(self._ref)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        """Blocks with a positive refcount (scratch excluded)."""
        return self.n_blocks - 1 - self.n_free

    def alloc(self) -> int | None:
        """Pop a free block with refcount 1, or None when exhausted (the
        caller decides whether to grow the pool or evict from the store)."""
        if not self._free:
            return None
        pid = self._free.pop()
        self._ref[pid] = 1
        return pid

    def retain(self, pid: int) -> None:
        if pid == SCRATCH_BLOCK:
            raise ValueError("cannot retain the scratch block")
        if self._ref[pid] <= 0:
            raise ValueError(f"retain of free block {pid}")
        self._ref[pid] += 1

    def refcount(self, pid: int) -> int:
        return self._ref[pid]

    def release(self, pid: int) -> bool:
        """Drop one reference; True when the block returned to the free
        list (refcount hit zero)."""
        if pid == SCRATCH_BLOCK:
            raise ValueError("cannot release the scratch block")
        if self._ref[pid] <= 0:
            raise ValueError(f"release of free block {pid}")
        self._ref[pid] -= 1
        if self._ref[pid] == 0:
            self._free.append(pid)
            return True
        return False

    def grow(self, n_blocks: int) -> None:
        """Extend to ``n_blocks`` ids (mirrors :func:`grow_cache`)."""
        cur = self.n_blocks
        if n_blocks <= cur:
            return
        self._ref.extend([0] * (n_blocks - cur))
        self._free.extend(range(n_blocks - 1, cur - 1, -1))

    def shrink_target(self, floor: int = 2) -> int:
        """Lowest pool size every live block still fits in: one past the
        highest id with a positive refcount. A block pinned high (e.g. by
        the prefix store) bounds how far :func:`shrink_cache` may go."""
        for pid in range(self.n_blocks - 1, SCRATCH_BLOCK, -1):
            if self._ref[pid] > 0:
                return max(pid + 1, floor)
        return floor

    def shrink(self, n_blocks: int) -> None:
        """Drop ids beyond ``n_blocks`` (all must be free — mirrors
        :func:`shrink_cache`'s contract)."""
        if n_blocks >= self.n_blocks:
            return
        if any(self._ref[pid] > 0 for pid in range(n_blocks, self.n_blocks)):
            raise ValueError("shrink below a live block")
        del self._ref[n_blocks:]
        self._free = [pid for pid in self._free if pid < n_blocks]


__all__ = [
    "KV_QUANT_DTYPES",
    "SCRATCH_BLOCK",
    "BlockPayload",
    "BlockPool",
    "PagedKVCache",
    "block_bytes",
    "blocks_for",
    "create_cache",
    "dequantize_values",
    "export_blocks",
    "grow_cache",
    "kv_quant_spec",
    "pack_payload",
    "payload_compatible",
    "quant_scatter_span",
    "quantize_values",
    "scatter_block_kv",
    "shrink_cache",
    "unpack_payload",
    "write_block",
]

"""Paged block KV cache: a refcounted physical-block pool + per-slot
indirection tables.

The first engine cache was slot-owns-contiguous-blocks: ``[L, S, Hkv, T, hd]``
with slot ``s`` owning positions ``[0, T)`` of its own row. That layout
cannot share anything — two requests with the same system/template prefix
each pay a full prefill and hold duplicate K/V. Here the cache is a **pool**
of physical blocks plus an indirection map (vLLM's paged KV, arXiv:2309.06180,
as the substrate for SGLang-style radix prefix sharing, arXiv:2312.07104):

- buffers are ``[L, P, Hkv, block, hd]`` head-major — ``P`` physical blocks,
  each holding ``block`` token positions across ALL layers (one allocation =
  one refcount covering every layer's K and V for that token span);
- a per-slot **block table** (host-planned, device-threaded through
  ops/decode_attention.py's scan and pallas impls) maps logical block ``j``
  of slot ``s`` to a physical block id — slots no longer own contiguous
  storage, so a physical block can appear in many tables at once;
- physical block **0 is the scratch block**: never allocated, dead slots'
  decode writes are steered into it so a freed (and possibly reallocated)
  block can never be corrupted by a stale slot;
- :class:`BlockPool` carries the host-side refcounts — a block is shared by
  construction (live slots + the prefix store each hold a reference) and
  returns to the free list only when its refcount hits zero, which is what
  lets ``shrink`` free real HBM without ever reclaiming a block the prefix
  store still pins;
- the pool grows by doubling and shrinks by halving (bounded decode-step
  recompiles, one per pool size), and attention cost scales with the
  *table width* (active blocks per slot), not ``max_len`` — the
  tests/test_perf_guard.py contract carries over from the contiguous
  layout unchanged.

The sharing policy itself (which blocks are safe to share, copy-on-write,
eviction) lives in serve/prefix.py; this module only knows physical blocks
and reference counts.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

# physical block 0 is reserved: dead slots' writes land here, and table
# entries beyond a slot's allocation point at it (their tiles are masked
# by the per-row length, but the DMA still needs a valid index)
SCRATCH_BLOCK = 0


class PagedKVCache(NamedTuple):
    """k/v: [L, P, Hkv, block, hd] physical-block pools; lengths: [S]."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    @property
    def n_blocks(self) -> int:
        """P — physical blocks currently backed (scratch included)."""
        return self.k.shape[1]

    @property
    def block(self) -> int:
        """Token positions per physical block."""
        return self.k.shape[3]

    @property
    def slots(self) -> int:
        return self.lengths.shape[0]


def create_cache(
    cfg, slots: int, n_blocks: int, block: int, dtype=None
) -> PagedKVCache:
    """Fresh pool of ``n_blocks`` physical blocks (block 0 = scratch)."""
    shape = (cfg.n_layers, n_blocks, cfg.n_kv_heads, block, cfg.head_dim)
    dt = dtype or cfg.dtype
    return PagedKVCache(
        jnp.zeros(shape, dt), jnp.zeros(shape, dt),
        jnp.zeros((slots,), jnp.int32),
    )


def grow_cache(cache: PagedKVCache, n_blocks: int) -> PagedKVCache:
    """Extend the pool to ``n_blocks`` physical blocks (zero-filled)."""
    extra = n_blocks - cache.n_blocks
    if extra <= 0:
        return cache
    pad = [(0, 0), (0, extra), (0, 0), (0, 0), (0, 0)]
    return PagedKVCache(
        jnp.pad(cache.k, pad), jnp.pad(cache.v, pad), cache.lengths
    )


def shrink_cache(cache: PagedKVCache, n_blocks: int) -> PagedKVCache:
    """Release physical blocks beyond ``n_blocks``. The caller guarantees
    every id >= ``n_blocks`` is FREE (``BlockPool.shrink_target`` reports
    the lowest safe size — a block pinned by the prefix store or a live
    slot bounds how far the pool can shrink)."""
    if n_blocks >= cache.n_blocks:
        return cache
    return PagedKVCache(
        cache.k[:, :n_blocks], cache.v[:, :n_blocks], cache.lengths
    )


def blocks_for(length: int, block: int) -> int:
    """ceil(length / block), minimum 1."""
    return max(1, math.ceil(length / block))


def scatter_block_kv(pool: jax.Array, new: jax.Array, pids: jax.Array,
                     offs: jax.Array) -> jax.Array:
    """Paged KV write into ONE layer's ``[P, Hkv, block, hd]`` pool.

    ``pids``/``offs`` name each new entry's physical block and in-block
    offset. With 1-D ``[S]`` indices ``new`` is ``[S, Hkv, hd]`` (the
    classic one-token decode step); with 2-D ``[S, G]`` indices it is
    ``[S, G, Hkv, hd]`` — the speculative multi-position write
    (serve/spec.py): row s's G draft positions land in one scatter.
    Entries that must not land anywhere real (dead slots, padding beyond
    a row's draft length) are the CALLER's job to steer to
    ``SCRATCH_BLOCK``. The advanced indices (``pids`` on axis 0, ``offs``
    on axis 2) are non-adjacent, so the indexed result moves the index
    dims to the front — exactly ``new``'s layout, no transpose needed."""
    return pool.at[pids, :, offs, :].set(new)


def block_bytes(cfg, block: int, dtype=None) -> int:
    """HBM bytes one physical block costs (K + V across all layers)."""
    dt = jnp.dtype(dtype or cfg.dtype)
    return 2 * cfg.n_layers * cfg.n_kv_heads * block * cfg.head_dim * dt.itemsize


class BlockPool:
    """Host-side refcounted allocator over physical block ids.

    Pure bookkeeping — no device arrays, no locks (the engine thread is
    the only mutator; see serve/engine.py). A block id is *live* while its
    refcount is positive: live slots hold one reference per table entry,
    and the prefix store holds one per radix node. ``release`` returns a
    block to the free list only at refcount zero — a freed slot therefore
    returns only the blocks nothing else (the store, another slot) still
    references.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError("pool needs the scratch block plus one")
        self._ref = [0] * n_blocks
        # LIFO free list (reuse-warm blocks first); scratch never enters
        self._free = list(range(n_blocks - 1, SCRATCH_BLOCK, -1))

    @property
    def n_blocks(self) -> int:
        return len(self._ref)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        """Blocks with a positive refcount (scratch excluded)."""
        return self.n_blocks - 1 - self.n_free

    def alloc(self) -> int | None:
        """Pop a free block with refcount 1, or None when exhausted (the
        caller decides whether to grow the pool or evict from the store)."""
        if not self._free:
            return None
        pid = self._free.pop()
        self._ref[pid] = 1
        return pid

    def retain(self, pid: int) -> None:
        if pid == SCRATCH_BLOCK:
            raise ValueError("cannot retain the scratch block")
        if self._ref[pid] <= 0:
            raise ValueError(f"retain of free block {pid}")
        self._ref[pid] += 1

    def refcount(self, pid: int) -> int:
        return self._ref[pid]

    def release(self, pid: int) -> bool:
        """Drop one reference; True when the block returned to the free
        list (refcount hit zero)."""
        if pid == SCRATCH_BLOCK:
            raise ValueError("cannot release the scratch block")
        if self._ref[pid] <= 0:
            raise ValueError(f"release of free block {pid}")
        self._ref[pid] -= 1
        if self._ref[pid] == 0:
            self._free.append(pid)
            return True
        return False

    def grow(self, n_blocks: int) -> None:
        """Extend to ``n_blocks`` ids (mirrors :func:`grow_cache`)."""
        cur = self.n_blocks
        if n_blocks <= cur:
            return
        self._ref.extend([0] * (n_blocks - cur))
        self._free.extend(range(n_blocks - 1, cur - 1, -1))

    def shrink_target(self, floor: int = 2) -> int:
        """Lowest pool size every live block still fits in: one past the
        highest id with a positive refcount. A block pinned high (e.g. by
        the prefix store) bounds how far :func:`shrink_cache` may go."""
        for pid in range(self.n_blocks - 1, SCRATCH_BLOCK, -1):
            if self._ref[pid] > 0:
                return max(pid + 1, floor)
        return floor

    def shrink(self, n_blocks: int) -> None:
        """Drop ids beyond ``n_blocks`` (all must be free — mirrors
        :func:`shrink_cache`'s contract)."""
        if n_blocks >= self.n_blocks:
            return
        if any(self._ref[pid] > 0 for pid in range(n_blocks, self.n_blocks)):
            raise ValueError("shrink below a live block")
        del self._ref[n_blocks:]
        self._free = [pid for pid in self._free if pid < n_blocks]


__all__ = [
    "SCRATCH_BLOCK",
    "BlockPool",
    "PagedKVCache",
    "block_bytes",
    "blocks_for",
    "create_cache",
    "grow_cache",
    "scatter_block_kv",
    "shrink_cache",
]

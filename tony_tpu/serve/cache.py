"""Length-aware block KV cache for the decode engine.

generate.py's original ring cache is ``[L, B, max_len, Hkv, hd]``: every
decode step attends (and every attention DMA walks) the full ``max_len``
buffer no matter how little of it is written, and a batch admits a request
only by owning a whole row to ``max_len``. Here the cache is laid out in
fixed-size **blocks** along the sequence dim and sized to the *active*
block count:

- buffers are ``[L, S, Hkv, T, hd]`` head-major (the decode kernel's native
  layout — see ops/decode_attention.py) with ``T = n_blocks * block``;
- ``T`` tracks ``max(ceil(lengths / block))`` over live slots, not
  ``max_len``: attention cost and cache residency scale with what is
  actually written (tests/test_perf_guard.py asserts the compiled decode
  step's KV bytes scale with ``T``);
- the engine grows ``T`` by doubling when any row fills it (bounded
  recompiles of the decode step: one per capacity, O(log(max_len/block)))
  and shrinks it back when the rows holding the tail finish — freed rows
  return their blocks;
- per-slot ``lengths`` make the cache ragged-aware: slot ``s`` has valid
  positions ``[0, lengths[s])``; a freed slot is just ``lengths[s] = 0``
  (its stale contents are always overwritten before the attended prefix
  reaches them).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp


class BlockKVCache(NamedTuple):
    """k/v: [L, S, Hkv, T, hd] with T = n_blocks * block; lengths: [S]."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    @property
    def capacity(self) -> int:
        """T — positions currently backed per slot."""
        return self.k.shape[3]

    @property
    def slots(self) -> int:
        return self.k.shape[1]


def create_cache(
    cfg, slots: int, n_blocks: int, block: int, dtype=None
) -> BlockKVCache:
    """Fresh cache with ``n_blocks`` blocks per slot."""
    shape = (
        cfg.n_layers, slots, cfg.n_kv_heads, n_blocks * block, cfg.head_dim
    )
    dt = dtype or cfg.dtype
    return BlockKVCache(
        jnp.zeros(shape, dt), jnp.zeros(shape, dt),
        jnp.zeros((slots,), jnp.int32),
    )


def grow_cache(cache: BlockKVCache, n_blocks: int, block: int) -> BlockKVCache:
    """Extend every slot to ``n_blocks`` blocks (zero-filled tail)."""
    extra = n_blocks * block - cache.capacity
    if extra <= 0:
        return cache
    pad = [(0, 0), (0, 0), (0, 0), (0, extra), (0, 0)]
    return BlockKVCache(
        jnp.pad(cache.k, pad), jnp.pad(cache.v, pad), cache.lengths
    )


def shrink_cache(cache: BlockKVCache, n_blocks: int, block: int) -> BlockKVCache:
    """Release blocks beyond ``n_blocks`` (caller guarantees no live row
    extends past them — the engine shrinks to the live maximum)."""
    t = n_blocks * block
    if t >= cache.capacity:
        return cache
    return BlockKVCache(
        cache.k[:, :, :, :t], cache.v[:, :, :, :t], cache.lengths
    )


def blocks_for(length: int, block: int) -> int:
    """ceil(length / block), minimum 1."""
    return max(1, math.ceil(length / block))


__all__ = [
    "BlockKVCache", "blocks_for", "create_cache", "grow_cache", "shrink_cache",
]

"""Gang-serving frontend: admission, routing, replay, rolling restart.

The thin RPC frontend of a `tony serve` job (docs/SERVE.md "Gang
serving"). It discovers the decode hosts through the AM's task table (the
same GetTaskInfos the CLI uses), routes each request to the least-loaded
live host (keyed on the hosts' live ``DecodeStats``: slot occupancy +
queue depth), relays the token stream back, and owns the failure
semantics the gang exists for:

- **Bounded admission.** At ``serve.gang.frontend_max_inflight`` requests
  in flight, submit() rejects explicitly (``tony_serve_rejected_total``
  on the frontend registry) — backpressure propagates to the caller, it
  is never buried in a queue. Host-side rejections (the engine's
  ``max_queue`` seam) reroute to another host.
- **Prefix-affinity routing.** Requests sharing a prefix fingerprint
  (the leading ``serve.prefix.fingerprint_tokens`` tokens) route to the
  host whose prefix store already holds that prefix — the store is
  per-host, so scattering same-template traffic across the gang would
  re-prefill the prefix once per host instead of once per fleet. The
  affinity host is only *preferred*: dead, draining, excluded-by-replay,
  or clearly overloaded hosts fall back to least-loaded (and the
  fingerprint re-pins to wherever the request lands). Replay-on-host-
  death stays draw-for-draw identical — affinity changes WHERE a request
  runs, never its rng stream or sampling.
- **No request lost.** A decode host that dies mid-stream fails its
  relays with an RPC error; each such request is *re-queued* and
  *re-prefilled* on a survivor. Replay is draw-for-draw deterministic —
  every host builds identical weights from ``serve.gang.seed`` and the
  frontend assigns each request its ``rng_seed`` — so the frontend
  replays the FULL stream, verifies the regenerated prefix matches what
  it already delivered (``replay_consistent``, the evidence the
  ``serve-no-request-lost`` chaos invariant checks), and continues from
  the tail. The replay rides a ``serve.reprefill`` span parented on the
  original ``serve.request`` span, so the merged trace shows the
  recovery hanging off the request it rescued.
- **Rolling restart.** ``rolling_restart()`` drains hosts one at a time
  (stop admitting, live slots finish, KV state drains, engine recycles)
  while the rest keep serving.
- **Autoscale hooks.** Sustained aggregate queue depth feeds
  :class:`AutoscalePolicy`; with a lease store attached, a grow/shrink
  decision adjusts the job's gang reservation via
  ``LeaseStore.grow_gang``/``shrink_gang``.

Lock discipline (GL004): ``_lock`` guards the host/request tables only.
Every RPC, sleep, and queue wait happens outside it.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Sequence

import grpc

from tony_tpu.obs import series, trace
from tony_tpu.obs.registry import Registry, write_snapshot
from tony_tpu.rpc import ApplicationRpcClient, ServeRpcClient, pb
from tony_tpu.serve.gang import GangSettings
from tony_tpu.serve.prefix import fingerprint

log = logging.getLogger(__name__)


@dataclass
class GangCompletion:
    """What the frontend hands back per request (the gang-level analogue
    of engine.Completion, plus the recovery evidence)."""

    rid: str
    tokens: list[int] = field(default_factory=list)
    prompt_len: int = 0
    finish_reason: str = ""   # eos | length | rejected | error
    message: str = ""
    ttft_s: float = 0.0
    replays: int = 0
    replay_consistent: bool = True
    hosts: list[str] = field(default_factory=list)


class FrontendRejected(RuntimeError):
    """submit() refused: the gang is at frontend_max_inflight."""


class AutoscalePolicy:
    """Grow/shrink decisions from sustained queue depth, tracked PER POOL.

    ``observe()`` returns "grow" once a pool's depth has stayed at or
    above ``high`` for ``window_s`` continuously, "shrink" once it has
    stayed at or below ``low`` for the window, else None. Each decision
    resets that pool's window, so a persistent overload emits one grow
    per window — paced, not a thundering herd. Every ``pool`` (task
    type) gets its OWN window state: a saturated prefill pool grows
    without the decode pool's idle queue masking it, and vice versa.
    Callers that predate pools omit ``pool`` and get the single default
    window — the old gang-wide behavior. ``high`` <= 0 disables the
    policy.
    """

    def __init__(self, high: int, low: int, window_s: float):
        self.high = int(high)
        self.low = int(low)
        self.window_s = float(window_s)
        # pool -> [above_since, below_since] window state
        self._windows: dict[str, list[float | None]] = {}

    def observe(
        self, queue_depth: int, now: float | None = None,
        pool: str = "decode",
    ) -> str | None:
        if self.high <= 0:
            return None
        now = time.monotonic() if now is None else now
        w = self._windows.setdefault(pool, [None, None])
        if queue_depth >= self.high:
            w[1] = None
            if w[0] is None:
                w[0] = now
            elif now - w[0] >= self.window_s:
                w[0] = None
                return "grow"
        elif queue_depth <= self.low:
            w[0] = None
            if w[1] is None:
                w[1] = now
            elif now - w[1] >= self.window_s:
                w[1] = None
                return "shrink"
        else:
            w[0] = w[1] = None
        return None


@dataclass
class _Host:
    task_id: str
    address: str
    attempt: int
    client: ServeRpcClient
    stats: "pb.DecodeStatsResponse | None" = None
    assigned: int = 0        # frontend-routed, not yet finished there
    dead: bool = False
    draining: bool = False
    pool: str = "decode"     # "decode" | "prefill" (task type's pool)

    def load(self) -> float:
        """Routing key: the host's own in-flight view when fresh, plus
        what this frontend has routed but the stats poll has not seen."""
        base = self.stats.in_flight + self.stats.live_slots if self.stats else 0
        return base + self.assigned


class _Flight:
    """One in-flight request's frontend state + its relay thread plumbing."""

    def __init__(self, rid: str, req: "pb.InferenceRequest", span,
                 fp: int | None = None):
        self.rid = rid
        self.req = req
        self.span = span          # serve.request, open until completion
        self.fp = fp              # prefix-affinity fingerprint (or None)
        self.submit_t = time.perf_counter()
        self.result = GangCompletion(rid=rid)
        self.done = threading.Event()
        self.handoff_tried = False  # one handoff per request, never on replay


class GangFrontend:
    """See module docstring. One instance per serve job; ``close()``
    writes the request ledger the chaos invariants audit."""

    STATS_INTERVAL_S = 0.25
    NO_HOST_WAIT_S = 0.25
    # bounded patience for "no routable host": covers an AM relaunching a
    # failed decode task; beyond it the request errs out visibly
    NO_HOST_TIMEOUT_S = 60.0
    # how long an errored (task_id, address, attempt) entry stays barred
    # from rediscovery: the AM's task table keeps showing the DEAD
    # incarnation as RUNNING until the relaunch lands, and re-adding it
    # would bounce every route off a refused connection. The relaunched
    # incarnation (new attempt/port) is never barred; after the TTL a
    # transiently-unreachable live host gets retried.
    TOMBSTONE_TTL_S = 10.0

    def __init__(
        self,
        am_addr: str,
        settings: GangSettings | None = None,
        *,
        app_dir: str = "",
        token: str | None = None,
        proc: str = "frontend",
        lease_store=None,
        app_id: str = "",
        grow_ask=None,
        grow_asks: dict | None = None,
    ):
        self.settings = settings or GangSettings()
        self.app_dir = app_dir
        self.proc = proc
        # "" = static mode: no AM discovery; hosts come from add_host()
        self._am = ApplicationRpcClient(am_addr, token=token) if am_addr else None
        self._token = token
        self._lock = threading.Lock()
        self._hosts: dict[str, _Host] = {}
        # errored incarnations barred from rediscovery until expiry:
        # (task_id, address, attempt) -> monotonic expiry
        self._tombstones: dict[tuple[str, str, int], float] = {}
        self._flights: dict[str, _Flight] = {}
        # finished, not yet collected via result(); collection evicts so a
        # long-lived frontend holds only what callers have not read
        self._results: dict[str, GangCompletion] = {}
        self._done_events: dict[str, threading.Event] = {}
        self._ledger: list[dict] = []
        self._seq = 0
        self._closed = threading.Event()
        self.registry = Registry()
        self._c_submitted = self.registry.counter(
            "tony_serve_requests_total", "requests accepted by the frontend")
        self._c_rejected = self.registry.counter(
            "tony_serve_rejected_total",
            "requests rejected by frontend bounded admission")
        self._c_replays = self.registry.counter(
            "tony_serve_replays_total",
            "re-queued + re-prefilled requests after a host death")
        self._c_affinity = self.registry.counter(
            "tony_serve_affinity_routed_total",
            "requests routed to their prefix-affinity host")
        self._g_hosts = self.registry.gauge(
            "tony_serve_gang_hosts", "routable decode hosts")
        self._g_inflight = self.registry.gauge(
            "tony_serve_frontend_inflight", "requests in flight at the frontend")
        self._h_ttft = self.registry.histogram(
            "tony_ttft_seconds", "submit -> first relayed token (gang-level)")
        self.autoscaler = AutoscalePolicy(
            self.settings.autoscale_queue_high,
            self.settings.autoscale_queue_low,
            self.settings.autoscale_window_s,
        )
        self._lease_store = lease_store
        self._app_id = app_id
        # prefix-affinity map: fingerprint -> task_id of the host whose
        # store holds that prefix (bounded LRU; guarded by _lock)
        self._affinity: OrderedDict[int, str] = OrderedDict()
        self._affinity_cap = 4096
        # the GangAsk one more host of each pool costs — the REAL container
        # resources (memory/cpus/tpu_chips of that pool's task type), or a
        # grow that leases a token ask would leave the new host's chips
        # looking free to every other job in the store (double-booking).
        # ``grow_asks`` keys by pool; the legacy ``grow_ask`` is the decode
        # pool's (a heterogeneous ask must never grow the wrong pool)
        self._grow_asks: dict = dict(grow_asks or {})
        if grow_ask is not None:
            self._grow_asks.setdefault("decode", grow_ask)
        self.autoscale_actions: list[tuple[str, str]] = []  # (action, detail)
        # blockwise KV handoff records (prefill -> decode), audited by the
        # handoff-no-block-leak chaos invariant via the ledger
        self._handoffs: list[dict] = []
        self._c_handoffs = self.registry.counter(
            "tony_serve_handoffs_total",
            "completed prefill->decode block handoffs")
        self._depth_by_pool: dict[str, int] = {}
        # gang-level live series (obs/series.py): the frontend publishes
        # fleet aggregates — routable hosts, summed queue depth, inflight,
        # windowed gang TTFT — as a scrape source; the stats loop is its
        # sampling cadence (the frontend has no step loop)
        self._fleet_depth = 0
        self._series = series.active_recorder()
        self._series_key = f"frontend@{id(self):x}"
        if self._series is not None:
            from tony_tpu.obs.registry import HistogramWindow

            self._ttft_window = HistogramWindow()
            self._series.attach(self._series_key, self._series_source)
        self._stats_thread = threading.Thread(
            target=self._stats_loop, daemon=True, name="frontend-stats"
        )
        self._stats_thread.start()

    def _series_source(self) -> dict:
        out = {
            "gang_hosts": float(self._g_hosts.value),
            "queue_depth": float(self._fleet_depth),
            "inflight": float(self._g_inflight.value),
            "requests_total": float(self._c_submitted.value),
            "replays_total": float(self._c_replays.value),
            "rejected_total": float(self._c_rejected.value),
            "affinity_routed_total": float(self._c_affinity.value),
        }
        d = self._ttft_window.delta(self._h_ttft)
        if d["count"]:
            out["ttft_p50_s"] = round(d["p50"], 4)
            out["ttft_p99_s"] = round(d["p99"], 4)
            out["ttft_n"] = d["count"]
        # per-pool depth rollup (disaggregated gangs): the pool label rides
        # the series key, so portal/`tony top` can split the queues
        for pool, depth in self._depth_by_pool.items():
            out[f"queue_depth_{pool}"] = float(depth)
        if self._handoffs:
            out["handoffs_total"] = float(self._c_handoffs.value)
        return out

    # --- discovery / stats ----------------------------------------------------

    def add_host(self, task_id: str, address: str, attempt: int = 0,
                 pool: str = "decode") -> None:
        """Register a host explicitly (static deployments / tests);
        AM-discovered jobs never need this."""
        h = _Host(task_id, address, attempt,
                  ServeRpcClient(address, token=self._token), pool=pool)
        with self._lock:
            self._hosts[task_id] = h

    def refresh_hosts(self) -> int:
        """Sync the host table with the AM's task view. Returns the number
        of routable (live, non-draining) hosts."""
        if self._am is None:
            return self._routable_count()
        try:
            infos = self._am.get_task_infos().tasks
        except grpc.RpcError:
            return self._routable_count()
        # task type -> pool: a disaggregated gang contributes two types
        # (decode + prefill), a classic gang just the decode one
        pool_types = {self.settings.job_type: "decode"}
        if self.settings.prefill_hosts > 0:
            pool_types[self.settings.prefill_job_type] = "prefill"
        seen: dict[str, tuple[str, int, str]] = {}
        now = time.monotonic()
        with self._lock:
            self._tombstones = {
                k: exp for k, exp in self._tombstones.items() if exp > now
            }
            tombstoned = set(self._tombstones)
        for t in infos:
            if t.job_name not in pool_types or t.port <= 0:
                continue
            if t.state not in ("REGISTERED", "RUNNING"):
                continue
            task_id = f"{t.job_name}:{t.index}"
            address = f"{t.host}:{t.port}"
            if (task_id, address, t.attempt) in tombstoned:
                continue  # the dead incarnation the AM has not replaced yet
            seen[task_id] = (address, t.attempt, pool_types[t.job_name])
        stale: list[_Host] = []
        with self._lock:
            for task_id, h in list(self._hosts.items()):
                cur = seen.get(task_id)
                if cur is None or cur[:2] != (h.address, h.attempt):
                    # gone, restarted (new attempt), or moved: retire it —
                    # its relays fail over on their next RPC error
                    h.dead = True
                    stale.append(self._hosts.pop(task_id))
            known = set(self._hosts)
        for task_id, (address, attempt, pool) in seen.items():
            if task_id in known:
                continue
            h = _Host(
                task_id, address, attempt,
                ServeRpcClient(address, token=self._token), pool=pool,
            )
            with self._lock:
                self._hosts[task_id] = h
        for h in stale:
            try:
                h.client.close()
            except Exception:
                pass
        return self._routable_count()

    def _routable_count(self) -> int:
        with self._lock:
            return sum(
                1 for h in self._hosts.values() if not (h.dead or h.draining)
            )

    def wait_ready(self, n_hosts: int | None = None, timeout_s: float = 180.0) -> int:
        """Block until ``n_hosts`` (default: the configured gang size,
        both pools) hosts answer DecodeStats. Raises TimeoutError
        otherwise."""
        want = n_hosts or (
            self.settings.hosts + max(self.settings.prefill_hosts, 0)
        )
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.refresh_hosts()
            ready = 0
            for h in self._snapshot_hosts():
                try:
                    h.stats = h.client.decode_stats(timeout_s=2.0)
                    ready += 1
                except grpc.RpcError:
                    pass
            if ready >= want:
                self._g_hosts.set(ready)
                return ready
            time.sleep(0.25)
        raise TimeoutError(
            f"only {self._routable_count()} of {want} decode hosts became "
            f"reachable within {timeout_s:.0f}s"
        )

    def _snapshot_hosts(self) -> list[_Host]:
        with self._lock:
            return [h for h in self._hosts.values() if not h.dead]

    def _stats_loop(self) -> None:
        """Background poll: host discovery + per-host DecodeStats (the
        routing signal) + the autoscale policy tick. All RPCs outside the
        table lock."""
        while not self._closed.wait(self.STATS_INTERVAL_S):
            self.refresh_hosts()
            depth = 0
            by_pool: dict[str, int] = {}
            for h in self._snapshot_hosts():
                try:
                    h.stats = h.client.decode_stats(timeout_s=2.0)
                    h.draining = h.stats.draining
                    depth += h.stats.queue_depth
                    by_pool[h.pool] = by_pool.get(h.pool, 0) + h.stats.queue_depth
                except grpc.RpcError:
                    # unreachable != dead (it may be mid-restart); relays
                    # decide on their own stream errors
                    h.stats = None
            self._g_hosts.set(self._routable_count())
            self._fleet_depth = depth
            self._depth_by_pool = by_pool
            self.autoscale_tick(by_pool if by_pool else depth)
            series.sample()  # stride-counted gang-level series scrape

    # --- autoscale ------------------------------------------------------------

    def autoscale_tick(
        self, queue_depth: "int | dict[str, int]", now: float | None = None
    ) -> str | None:
        """Feed the sustained-queue-depth policy; apply a grow/shrink to
        the lease store when one is attached (the `tony serve` CLI passes
        the job's store + app id). Accepts the legacy int (decode-pool
        depth) or a per-pool ``{pool: depth}`` dict — each pool ticks its
        OWN policy window, and a grow leases that pool's own GangAsk (a
        heterogeneous ask must never grow the wrong pool). Always records
        decisions so tests and operators can see what WOULD have
        happened. Returns the last action taken (tests observe one pool
        at a time)."""
        depths = (
            queue_depth if isinstance(queue_depth, dict)
            else {"decode": int(queue_depth)}
        )
        last: str | None = None
        for pool in sorted(depths):
            depth = depths[pool]
            action = self.autoscaler.observe(depth, now, pool=pool)
            if action is None:
                continue
            # the decode pool keeps the pre-pool gang id so an upgraded
            # frontend keeps growing the reservation it already holds
            gang_id = (
                "serve-autoscale" if pool == "decode"
                else f"serve-autoscale-{pool}"
            )
            detail = f"pool={pool} queue_depth={depth}"
            if self._lease_store is not None and self._app_id:
                try:
                    if action == "grow":
                        ask = self._grow_asks.get(pool)
                        if ask is None:
                            detail += (
                                " -> no grow_ask configured for this pool "
                                "(pass its container GangAsk); decision "
                                "recorded only"
                            )
                        else:
                            host = self._lease_store.grow_gang(
                                self._app_id, gang_id, ask,
                            )
                            detail += (
                                f" -> leased {host}" if host
                                else " -> no capacity"
                            )
                    else:
                        freed = self._lease_store.shrink_gang(
                            self._app_id, gang_id
                        )
                        detail += (
                            f" -> freed {freed}" if freed
                            else " -> nothing to free"
                        )
                except Exception as e:
                    detail += f" -> store error {e}"
            log.warning("autoscale %s (%s)", action, detail)
            trace.instant(
                "serve.autoscale", action=action, pool=pool, detail=detail
            )
            self.autoscale_actions.append((action, detail))
            last = action
        return last

    # --- submission / routing -------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 32,
        *,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        eos_id: int | None = None,
    ) -> str:
        """Admit one request; returns its rid. Raises FrontendRejected at
        the in-flight bound. The per-request rng seed is assigned HERE —
        frontend-owned seeds are what make a replay on a different host
        regenerate the identical stream."""
        with self._lock:
            if len(self._flights) >= self.settings.frontend_max_inflight:
                reject = True
            else:
                reject = False
                self._seq += 1
                seq = self._seq
        if reject:
            self._c_rejected.inc()
            raise FrontendRejected(
                f"frontend at max_inflight "
                f"{self.settings.frontend_max_inflight}"
            )
        rid = f"r{seq}"
        req = pb.InferenceRequest(
            rid=rid,
            prompt=list(int(t) for t in prompt),
            max_new_tokens=int(max_new_tokens),
            temperature=float(temperature),
            top_k=int(top_k),
            top_p=float(top_p),
            eos_id=-1 if eos_id is None else int(eos_id),
            rng_seed=self.settings.seed * 1_000_003 + seq,
        )
        plen = len(req.prompt)  # precomputed: disarmed span() must stay cheap
        span = trace.span("serve.request", rid=rid, prompt_len=plen)
        fp = None
        if self.settings.prefix_affinity and self.settings.prefix:
            fp = fingerprint(req.prompt, self.settings.prefix_fingerprint_tokens)
        flight = _Flight(rid, req, span, fp=fp)
        with self._lock:
            self._flights[rid] = flight
            self._done_events[rid] = flight.done
        self._c_submitted.inc()
        self._g_inflight.set(len(self._flights))
        threading.Thread(
            target=self._relay, args=(flight,), daemon=True,
            name=f"relay-{rid}",
        ).start()
        return rid

    def _pick_host(self, exclude: set[str], fp: int | None = None) -> _Host | None:
        """Prefix-affinity host when ``fp`` names one that is routable and
        not clearly overloaded, else least-loaded (occupancy + queue depth
        via the stats poll, plus locally assigned work); ``exclude`` skips
        hosts this request already failed on — unless they are the only
        ones left (a restarted task reuses its task_id). The chosen host
        becomes (or stays) the fingerprint's affinity — after a failover
        the prefix re-pins to wherever the replay re-prefilled it."""
        with self._lock:
            alive = [
                h for h in self._hosts.values()
                if not (h.dead or h.draining) and h.pool == "decode"
            ]
            preferred = [h for h in alive if h.task_id not in exclude] or alive
            if not preferred:
                return None
            best = min(preferred, key=lambda h: h.load())
            if fp is not None:
                tid = self._affinity.get(fp)
                if tid is not None:
                    cand = next(
                        (h for h in preferred if h.task_id == tid), None
                    )
                    # overload fallback: pinning is worthless if the
                    # affinity host is saturated while another sits idle —
                    # re-prefilling the prefix there is cheaper than
                    # queueing behind a full host. A host whose stats poll
                    # is failing (stale entry, wedged process) gets the
                    # configured slot count as its estimate, so its
                    # locally-assigned backlog still bounds the pile-up.
                    if cand is not None:
                        slots_est = (
                            cand.stats.slots if cand.stats is not None
                            else self.settings.slots
                        )
                        if cand.load() < 2 * max(slots_est, 1):
                            best = cand
                            self._c_affinity.inc()
                self._affinity[fp] = best.task_id
                self._affinity.move_to_end(fp)
                while len(self._affinity) > self._affinity_cap:
                    self._affinity.popitem(last=False)
            best.assigned += 1
            return best

    def _pick_prefill_host(self) -> _Host | None:
        """Least-loaded live prefill-pool host (no affinity: prefill work
        is one-shot, the blocks leave with the handoff)."""
        with self._lock:
            alive = [
                h for h in self._hosts.values()
                if not (h.dead or h.draining) and h.pool == "prefill"
            ]
            if not alive:
                return None
            best = min(alive, key=lambda h: h.load())
            best.assigned += 1
            return best

    def _handoff(self, flight: _Flight, decode_host: _Host) -> None:
        """Disaggregated prefill: route the prompt through a prefill host,
        which ships the finished KV blocks to ``decode_host`` before the
        Generate lands there (its admission then sees a prefix hit).
        Failure is deliberately non-fatal — the decode host re-prefills
        whatever never arrived, correctness never depends on the handoff.
        Every attempt is recorded in the ledger; the handoff-no-block-leak
        chaos invariant audits shipped == adopted + freed post-mortem."""
        ph = self._pick_prefill_host()
        if ph is None:
            return
        rec = {
            "rid": flight.rid, "prefill_host": ph.task_id,
            "decode_host": decode_host.task_id, "shipped": 0, "adopted": 0,
            "freed": 0, "bytes": 0, "ms": 0.0, "ok": False, "message": "",
        }
        hop = trace.span(
            "serve.handoff", parent=flight.span.sid or None, rid=flight.rid,
            prefill=ph.task_id, decode=decode_host.task_id,
        )
        try:
            with hop:
                resp = ph.client.prefill(
                    flight.rid, list(flight.req.prompt), decode_host.address,
                    rng_seed=int(flight.req.rng_seed), timeout_s=600.0,
                )
                rec.update(
                    shipped=int(resp.shipped), adopted=int(resp.adopted),
                    freed=int(resp.freed), bytes=int(resp.bytes),
                    ms=round(resp.ms, 3), ok=bool(resp.ok),
                    message=resp.message,
                )
                hop.set(ok=resp.ok, shipped=resp.shipped)
        except grpc.RpcError as e:
            # prefill host lost mid-handoff: tombstone it and move on — the
            # decode host re-prefills, and the unadopted export dies with
            # the dead host's pool (nothing strands on a survivor)
            rec["message"] = (
                f"prefill host lost: {getattr(e, 'code', lambda: e)()}"
            )
            log.warning(
                "%s: handoff via %s failed (%s); decode host re-prefills",
                flight.rid, ph.task_id, rec["message"],
            )
            self._host_errored(ph)
        finally:
            with self._lock:
                ph.assigned = max(ph.assigned - 1, 0)
                self._handoffs.append(rec)
            if rec["ok"]:
                self._c_handoffs.inc()

    def _relay(self, flight: _Flight) -> None:
        """One request's life: route -> stream -> (on host death: re-queue
        + re-prefill on a survivor, verify the replayed prefix) -> finish.

        Two budgets, deliberately separate: ``max_replays`` is consumed
        only by attempts that made PROGRESS and then broke (a genuine
        mid-stream death); no-progress episodes — no routable host, a
        stale table entry refusing connections while the AM relaunches the
        task, admission rejections — are paced at NO_HOST_WAIT_S and
        bounded by one NO_HOST_TIMEOUT_S patience clock instead, so a
        restart window can never burn the replay budget in milliseconds.
        """
        res = flight.result
        stalled_since: float | None = None  # current no-progress episode
        try:
            while True:
                if (
                    stalled_since is not None
                    and time.monotonic() - stalled_since > self.NO_HOST_TIMEOUT_S
                ):
                    res.finish_reason = "error"
                    res.message = res.message or (
                        "no decode host made progress within "
                        f"{self.NO_HOST_TIMEOUT_S:.0f}s"
                    )
                    return
                failed: set[str] = set(res.hosts)
                host = self._pick_host(failed, flight.fp)
                if host is None:
                    stalled_since = stalled_since or time.monotonic()
                    time.sleep(self.NO_HOST_WAIT_S)
                    continue
                delivered = len(res.tokens)
                is_replay = bool(delivered or res.hosts)
                if (
                    not is_replay and not flight.handoff_tried
                    and self.settings.prefill_hosts > 0
                    and len(flight.req.prompt)
                    >= max(self.settings.handoff_min_tokens, 1)
                ):
                    # disaggregated prefill BEFORE the Generate is routed:
                    # the decode host is already chosen, so the blocks ship
                    # exactly where the request will decode. Never retried
                    # on replay — a replay re-prefills on the survivor,
                    # which is the correctness path the gang guarantees
                    flight.handoff_tried = True
                    self._handoff(flight, host)
                if is_replay:
                    # parented on the ORIGINAL request span: the merged
                    # trace shows the re-prefill hanging off the request
                    # the dead host dropped
                    hop = trace.span(
                        "serve.reprefill", parent=flight.span.sid or None,
                        rid=flight.rid, host=host.task_id,
                        delivered=delivered, replay=res.replays + 1,
                    )
                else:
                    hop = trace.span(
                        "serve.route", parent=flight.span.sid or None,
                        rid=flight.rid, host=host.task_id,
                    )
                res.hosts.append(host.task_id)
                outcome = ""
                try:
                    with hop:
                        outcome = self._stream_from(host, flight, delivered)
                        hop.set(outcome=outcome)
                except grpc.RpcError as e:
                    self._host_errored(host)
                    if len(res.tokens) > delivered:
                        # the re-queue moment: host died mid-stream;
                        # survivors re-prefill it
                        log.warning(
                            "%s: stream from %s failed mid-flight (%s); "
                            "re-queueing", flight.rid, host.task_id,
                            getattr(e, "code", lambda: e)(),
                        )
                        outcome = "host-lost"
                    else:
                        # connection-level failure before ANY progress: a
                        # stale table entry / relaunching host — a routing
                        # miss under the patience clock, not a replay
                        log.info(
                            "%s: %s unreachable before first token; "
                            "rerouting", flight.rid, host.task_id,
                        )
                        outcome = "unreachable"
                finally:
                    with self._lock:
                        host.assigned = max(host.assigned - 1, 0)
                if outcome in ("rejected", "draining", "unreachable"):
                    # unwind this hop and try elsewhere after a beat
                    res.hosts.pop()
                    stalled_since = stalled_since or time.monotonic()
                    time.sleep(self.NO_HOST_WAIT_S)
                    continue
                stalled_since = None  # the attempt streamed: progress
                if is_replay:
                    res.replays += 1
                    self._c_replays.inc()
                if outcome == "finished":
                    return
                if res.replays >= self.settings.max_replays:
                    res.finish_reason = "error"
                    res.message = (
                        f"replay budget exhausted after {res.replays} replays"
                    )
                    return
        finally:
            self._finish(flight)

    def _stream_from(self, host: _Host, flight: _Flight, delivered: int) -> str:
        """Relay one Generate stream. Returns 'finished' | 'rejected' |
        'draining' | 'stalled'. Raises grpc.RpcError on a broken stream
        (the caller's re-queue trigger). On replay (``delivered`` > 0) the
        FULL stream is requested and the regenerated prefix is verified
        against what was already delivered — the determinism evidence."""
        res = flight.result
        got: list[int] = []
        for chunk in host.client.generate(flight.req, timeout_s=600.0):
            if chunk.finish_reason in ("rejected", "draining"):
                return chunk.finish_reason
            if chunk.finish_reason == "invalid":
                # deterministic validation failure: identical on every
                # host — finish now instead of burning the replay budget
                res.finish_reason = "rejected"
                res.message = chunk.message
                return "finished"
            if chunk.finish_reason == "error":
                res.message = chunk.message
                return "stalled"
            if chunk.prompt_len:
                res.prompt_len = chunk.prompt_len
            got.extend(chunk.tokens)
            if not res.ttft_s and got:
                res.ttft_s = time.perf_counter() - flight.submit_t
                self._h_ttft.observe(res.ttft_s)
            if len(got) > delivered:
                if delivered and got[:delivered] != res.tokens[:delivered]:
                    # deterministic replay broken: record it loudly; the
                    # serve-no-request-lost invariant will flag the run
                    res.replay_consistent = False
                    log.error(
                        "%s: replay on %s diverged from the delivered "
                        "prefix", flight.rid, host.task_id,
                    )
                res.tokens = list(got)
                delivered = len(got)
            if chunk.done:
                if delivered and got[:delivered] != res.tokens[:delivered]:
                    res.replay_consistent = False
                res.finish_reason = chunk.finish_reason
                return "finished"
        # stream ended without a done chunk: the server went away between
        # chunks without an RPC error surfacing — treat as host loss
        raise grpc.RpcError()

    def _host_errored(self, host: _Host) -> None:
        with self._lock:
            host.dead = True
            self._hosts.pop(host.task_id, None)
            self._tombstones[(host.task_id, host.address, host.attempt)] = (
                time.monotonic() + self.TOMBSTONE_TTL_S
            )
        try:
            host.client.close()
        except Exception:
            pass

    def _finish(self, flight: _Flight) -> None:
        res = flight.result
        if not res.finish_reason:
            res.finish_reason = "error"
            res.message = res.message or "relay exited without a result"
        flight.span.end(
            reason=res.finish_reason, tokens=len(res.tokens),
            replays=res.replays,
        )
        with self._lock:
            self._flights.pop(flight.rid, None)
            self._results[flight.rid] = res
            inflight = len(self._flights)
        self._g_inflight.set(inflight)
        self._ledger.append({
            "rid": res.rid,
            "prompt_len": res.prompt_len or len(flight.req.prompt),
            "max_new_tokens": flight.req.max_new_tokens,
            "seed": int(flight.req.rng_seed),
            "tokens": len(res.tokens),
            "finish_reason": res.finish_reason,
            "message": res.message,
            "ttft_s": round(res.ttft_s, 4),
            "replays": res.replays,
            "replay_consistent": res.replay_consistent,
            "hosts": list(res.hosts),
        })
        flight.done.set()

    # --- results / restart ----------------------------------------------------

    def result(self, rid: str, timeout_s: float = 600.0) -> GangCompletion:
        """Block for one request's completion and collect (evict) it."""
        with self._lock:
            event = self._done_events.get(rid)
        if event is None:
            raise KeyError(f"unknown or already-collected rid {rid!r}")
        if not event.wait(timeout_s):
            raise TimeoutError(f"request {rid} still in flight")
        with self._lock:
            self._done_events.pop(rid, None)
            return self._results.pop(rid)

    def run(
        self, prompts: Sequence[Sequence[int]], max_new_tokens: int = 32, **kw
    ) -> dict[str, GangCompletion]:
        """Submit a batch and wait for every completion (driver sugar)."""
        rids = [self.submit(p, max_new_tokens, **kw) for p in prompts]
        return {rid: self.result(rid) for rid in rids}

    def rolling_restart(self, recycle: bool = True, timeout_s: float = 0.0) -> list[str]:
        """Drain + recycle hosts ONE at a time; the rest keep serving.
        Returns the task ids restarted. A host that fails to drain in its
        budget is skipped (and reported), never force-killed — that is
        the chaos schedule's job, not the restart path's."""
        done = []
        for h in self._snapshot_hosts():
            log.warning("rolling restart: draining %s", h.task_id)
            with self._lock:
                h.draining = True
            try:
                resp = h.client.drain(timeout_s=timeout_s, recycle=recycle)
                if resp.drained:
                    done.append(h.task_id)
                else:
                    log.error(
                        "rolling restart: %s kept %d in flight; skipping",
                        h.task_id, resp.remaining,
                    )
            except grpc.RpcError as e:
                log.error("rolling restart: drain of %s failed: %s", h.task_id, e)
            finally:
                with self._lock:
                    h.draining = False
        return done

    # --- shutdown -------------------------------------------------------------

    def ledger(self) -> dict:
        with self._lock:
            pending = [f.rid for f in self._flights.values()]
            handoffs = list(self._handoffs)
        return {
            "proc": self.proc,
            "ttft_budget_s": self.settings.ttft_budget_s,
            "rejected": int(self._c_rejected.value),
            "pending": pending,  # accepted but unfinished at ledger time
            "requests": list(self._ledger),
            "handoffs": handoffs,  # prefill->decode block-handoff records
        }

    def write_ledger(self) -> str | None:
        """Persist the request ledger under ``<app_dir>/serve/`` — the
        artifact the serve chaos invariants audit post-mortem."""
        if not self.app_dir:
            return None
        out_dir = os.path.join(self.app_dir, "serve")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"requests_{trace.sanitize_proc(self.proc)}.json")
        with open(path + ".tmp", "w") as f:
            json.dump(self.ledger(), f, indent=1, sort_keys=True)
        os.replace(path + ".tmp", path)
        return path

    def close(self, wait_s: float = 5.0) -> dict:
        """Wait briefly for in-flight work, persist the ledger, snapshot
        the registry into the app dir (portal fleet /metrics), and drop
        every channel. Returns the ledger."""
        deadline = time.monotonic() + wait_s
        while time.monotonic() < deadline:
            with self._lock:
                flights = list(self._flights.values())
            if not flights:
                break
            flights[0].done.wait(min(0.25, max(deadline - time.monotonic(), 0)))
        self._closed.set()
        with self._lock:
            open_flights = list(self._flights.values())
        for f in open_flights:
            f.span.end(reason="shutdown")
        ledger = self.ledger()
        self.write_ledger()
        if self.app_dir:
            try:
                write_snapshot(
                    os.path.join(
                        self.app_dir, "metrics",
                        f"{trace.sanitize_proc(self.proc)}.json",
                    ),
                    self.registry, proc=self.proc,
                )
            except OSError:
                log.debug("frontend registry snapshot failed", exc_info=True)
        self._stats_thread.join(timeout=2.0)
        if self._series is not None:
            self._series.force_sample()
            self._series.drain()
            self._series.detach(self._series_key)
        for h in self._snapshot_hosts():
            try:
                h.client.close()
            except Exception:
                pass
        if self._am is not None:
            try:
                self._am.close()
            except Exception:
                pass
        return ledger


__all__ = [
    "AutoscalePolicy",
    "FrontendRejected",
    "GangCompletion",
    "GangFrontend",
]

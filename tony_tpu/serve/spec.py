"""Speculative decoding: model-free drafts, one-step batched verification.

Decode emits one token per forward because each token conditions on the
last — but a decode forward is memory-bound, so verifying G positions in
one step costs barely more wall time than verifying one. Speculative
decoding (Leviathan et al., arXiv:2211.17192) exploits that: a cheap
draft proposes the next k tokens, the target model scores all of them in
ONE widened forward, and a rejection rule keeps exactly the prefix the
target itself would have produced — output is *distributionally
unchanged*.

This engine needs no draft model. Both draft sources are deterministic
host-side lookups (pure python, GL001 — no device work on the draft
path):

- **radix-trie longest extension** (serve/prefix.py): the prefix store
  is a trie over every sequence the engine has served. If a slot's
  context (prompt + emitted tokens) follows a stored path, the path's
  continuation is the draft — repeated or templated traffic drafts at
  near-100% accept (the SGLang-lineage observation that the radix cache
  doubles as a predictor);
- **n-gram prompt-lookup** (the "prompt lookup decoding" trick): the
  longest trailing n-gram of the slot's own context that occurred
  earlier in it predicts the tokens that followed that earlier
  occurrence — summarisation/extraction workloads copy their input.

Verification is exact, not approximate. For a *deterministic* draft the
Leviathan accept/resample rule collapses to something stronger than
distributional equality: unroll the engine's per-step rng-split chain
over the G = k+1 scored positions (split -> sample with key 0 -> carry
key 1, exactly what the 1-wide step does once), sample the target at
every position, and emit the longest prefix where the target's own
sample agrees with the draft, plus the first disagreeing sample as the
correction/bonus token. Every emitted token is the token the
autoregressive engine would have sampled with the same keys — output is
**draw-for-draw identical** to spec-off decoding (greedy and sampled;
tests/test_spec.py), not merely same-distribution.

Rollback is free by construction: the verify step writes position
``pos + j``'s K/V from fed token j of ``[last_tok, d_1..d_k]``, and the
accepted prefix covers exactly the positions the advanced ``lengths``
expose — rejected positions' K/V lie beyond every row's length, masked
out of attention, and overwritten by later steps (serve/engine.py
``_spec_decode_step``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

DRAFT_SOURCES = ("auto", "prefix", "ngram")

# n-gram prompt-lookup window: try the longest trailing n-gram first
_NGRAM_MAX = 3
_NGRAM_MIN = 1


def ngram_propose(ctx: Sequence[int], max_k: int,
                  max_n: int = _NGRAM_MAX, min_n: int = _NGRAM_MIN) -> list[int]:
    """Prompt-lookup draft: find the most recent earlier occurrence of the
    context's trailing n-gram (longest n first) and propose the tokens
    that followed it. Pure host-side python on the slot's own context —
    no model, no device work."""
    L = len(ctx)
    if max_k <= 0 or L < min_n + 1:
        return []
    for n in range(min(max_n, L - 1), min_n - 1, -1):
        suffix = list(ctx[L - n:])
        for start in range(L - n - 1, -1, -1):
            if list(ctx[start:start + n]) == suffix:
                lo = start + n
                return [int(t) for t in ctx[lo:min(lo + max_k, L)]]
    return []


def propose_drafts(ctx: Sequence[int], store, max_k: int,
                   source: str = "auto") -> list[int]:
    """Draft up to ``max_k`` tokens for a slot whose context is ``ctx``
    (prompt + every emitted token, the next input token last). Tries the
    radix store's ``longest_extension`` first (cross-request knowledge),
    then the slot's own n-gram lookup — ``source`` pins one of them.
    Host-side only (GL001): the device never sees a draft until the
    engine uploads the per-step ``[S, k]`` draft batch."""
    if max_k <= 0:
        return []
    out: list[int] = []
    if source in ("auto", "prefix") and store is not None:
        out = store.longest_extension(ctx, max_k)
    if not out and source in ("auto", "ngram"):
        out = ngram_propose(ctx, max_k)
    return out[:max_k]


def verify_and_accept(logits: jax.Array, drafts: jax.Array,
                      draft_len: jax.Array, state, *, max_top_k: int):
    """The rejection rule, as the unrolled rng chain (module docstring).

    ``logits [S, G, V]`` are the target's distributions at the G = k+1
    fed positions; ``drafts [S, k]`` the proposed tokens (``draft_len
    [S]`` of them real per row); ``state`` the engine's ``_SlotState``.
    Samples the target at every position with the exact per-step key
    chain the 1-wide step would burn, then accepts the longest
    draft-agreeing prefix plus one correction/bonus token. EOS semantics
    mirror the 1-wide step: an emitted eos truncates emission and marks
    the row done; a row already done sticks at eos.

    Returns ``(toks [S, G], n_emit [S], n_acc [S], last_tok [S],
    new_rng [S, 2], done [S])`` — per row, the first ``n_emit`` of
    ``toks`` are the emitted tokens, ``last_tok`` feeds the next step,
    and ``new_rng`` is the carry after exactly ``n_emit`` splits (the
    autoregressive stream position)."""
    from tony_tpu.models.generate import sample_tokens

    S, G, _V = logits.shape
    has_eos = state.eos >= 0
    carry = state.rng
    toks, carries = [], [carry]
    for g in range(G):
        both = jax.vmap(jax.random.split)(carry)               # [S, 2, 2]
        toks.append(sample_tokens(
            logits[:, g], state.temp, state.top_k, state.top_p, both[:, 0],
            max_k=max_top_k,
        ))
        carry = both[:, 1]
        carries.append(carry)
    T = jnp.stack(toks, axis=1)                                # [S, G]
    R = jnp.stack(carries, axis=1)                             # [S, G+1, 2]
    # a row that already emitted eos sticks at eos (1-wide step rule)
    T = jnp.where((state.done & has_eos)[:, None], state.eos[:, None], T)
    if G > 1:
        gi = jnp.arange(G - 1, dtype=jnp.int32)[None, :]
        agree = (T[:, :G - 1] == drafts) & (gi < draft_len[:, None])
        n_acc = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1), axis=1)
    else:
        n_acc = jnp.zeros((S,), jnp.int32)
    n_emit = n_acc + 1                            # accepted drafts + bonus
    # eos truncation: emission stops AT the first emitted eos, inclusive
    is_eos = has_eos[:, None] & (T == state.eos[:, None])
    emitted = jnp.arange(G, dtype=jnp.int32)[None, :] < n_emit[:, None]
    eos_hit = is_eos & emitted
    any_eos = jnp.any(eos_hit, axis=1)
    first_eos = jnp.argmax(eos_hit, axis=1).astype(jnp.int32)
    n_emit = jnp.where(any_eos, first_eos + 1, n_emit).astype(jnp.int32)
    n_acc = jnp.minimum(n_acc, n_emit - 1)
    done = state.done | any_eos
    last_tok = jnp.take_along_axis(T, (n_emit - 1)[:, None], axis=1)[:, 0]
    new_rng = jnp.take_along_axis(R, n_emit[:, None, None], axis=1)[:, 0]
    return T, n_emit, n_acc, last_tok, new_rng, done


__all__ = [
    "DRAFT_SOURCES",
    "ngram_propose",
    "propose_drafts",
    "verify_and_accept",
]

"""Measured decode-slot budgets from the compiled step's memory plan.

bench.py's ``gqa_capacity`` used to size the slot budget as
``hbm * 0.92 - param_bytes`` — a hard-coded fragmentation guess standing in
for everything XLA actually allocates. This module replaces the guess with
XLA's own numbers: the decode step is AOT-lowered from shape avals (no
array is ever allocated) at two slot counts, and ``memory_analysis()``
splits the footprint into

- **param/argument bytes** — resident weights + cache + slot state,
- **fixed temp** — per-step scratch independent of the slot count,
- **per-slot temp** — the marginal scratch one more slot costs (measured
  as the slot-count difference, so fused/fused-out buffers price
  themselves),
- **generated code** — the executable itself.

The slot budget is then arithmetic, not a fudge factor::

    slots = (hbm - params - fixed_temp - code) // (kv_per_slot + temp_per_slot)

ROADMAP items 4 (quantized serving) and 5 (elastic resize) size against
the same numbers — change the cache dtype or layout and the budget moves
because the *measured plan* moved.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from tony_tpu.models.llama import LlamaConfig, init_params
from tony_tpu.obs.compiles import aot_analysis
from tony_tpu.serve.cache import PagedKVCache, blocks_for, kv_quant_spec


def _param_avals(cfg: LlamaConfig):
    return jax.eval_shape(partial(init_params, cfg=cfg), jax.random.key(0))


def _tree_bytes(tree) -> int:
    return sum(
        int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(tree)
    )


def _cache_avals(cfg: LlamaConfig, slots: int, capacity: int,
                 kv_block: int, quant_kv: str = "") -> tuple[PagedKVCache, Any]:
    """Paged pool + table avals sized so every slot reaches ``capacity``
    positions privately (scratch block included) — the worst case the
    budget must cover; prefix sharing only ever reduces it. With
    ``quant_kv`` the pools carry the quantized storage dtype plus the
    per-block-per-head float32 scale pools, so the measured plan prices
    exactly what the quantized engine allocates."""
    blocks = blocks_for(capacity, kv_block)
    n_phys = 1 + slots * blocks
    shape = (cfg.n_layers, n_phys, cfg.n_kv_heads, kv_block, cfg.head_dim)
    pool_dtype = kv_quant_spec(quant_kv)[0] if quant_kv else cfg.dtype
    scale = None
    if quant_kv:
        scale = jax.ShapeDtypeStruct(
            (cfg.n_layers, n_phys, cfg.n_kv_heads), jnp.float32
        )
    cache = PagedKVCache(
        k=jax.ShapeDtypeStruct(shape, pool_dtype),
        v=jax.ShapeDtypeStruct(shape, pool_dtype),
        lengths=jax.ShapeDtypeStruct((slots,), jnp.int32),
        k_scale=scale,
        v_scale=scale,
    )
    table = jax.ShapeDtypeStruct((slots, blocks), jnp.int32)
    return cache, table


def _state_avals(slots: int):
    from tony_tpu.serve.engine import _SlotState

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    return _SlotState(
        last_tok=sds((slots,), jnp.int32),
        rng=sds((slots, 2), jnp.uint32),
        temp=sds((slots,), jnp.float32),
        top_k=sds((slots,), jnp.int32),
        top_p=sds((slots,), jnp.float32),
        eos=sds((slots,), jnp.int32),
        done=sds((slots,), bool),
        live=sds((slots,), bool),
    )


def decode_step_analysis(cfg: LlamaConfig, *, slots: int, capacity: int,
                         kv_block: int = 64, decode_impl: str = "scan",
                         max_top_k: int = 64,
                         quant_kv: str = "") -> dict[str, Any]:
    """Compile (avals only — nothing allocated, nothing executed) the serve
    engine's decode step and return its measured memory plan + FLOPs.
    ``quant_kv`` compiles the quantized-cache variant of the step (scale
    gathers + inline dequant included), so the plan is the quantized
    engine's plan, not the bf16 plan with a smaller dtype penciled in."""
    from tony_tpu.serve.engine import _decode_fn

    fn = _decode_fn(cfg, decode_impl, kv_block, max_top_k, False, quant_kv)
    params = _param_avals(cfg)
    cache, table = _cache_avals(cfg, slots, capacity, kv_block, quant_kv)
    compiled = fn.lower(
        params, cache, table, _state_avals(slots)
    ).compile()
    # per-slot KV bytes: the slot's private blocks (the scratch block is
    # shared overhead, visible in cache_bytes = the whole pool)
    blocks = blocks_for(capacity, kv_block)
    from tony_tpu.serve.cache import block_bytes as _bb

    pool_leaves = [cache.k, cache.v]
    if cache.k_scale is not None:
        pool_leaves += [cache.k_scale, cache.v_scale]
    return {
        "slots": slots,
        "capacity": capacity,
        "param_bytes": _tree_bytes(params),
        "cache_bytes": _tree_bytes(pool_leaves),
        "table_bytes": _tree_bytes([table]),
        "kv_bytes_per_slot": blocks * _bb(cfg, kv_block, quant_kv=quant_kv),
        **aot_analysis(compiled),
    }


def derive_slot_budget(cfg: LlamaConfig, *, max_len: int,
                       hbm_bytes: int, kv_block: int = 64,
                       decode_impl: str = "scan",
                       shared_prefix_tokens: int = 0,
                       quant_kv: str = "") -> dict[str, Any]:
    """Slot budget at ``max_len`` from the compiled decode step's
    memory_analysis (params + fixed/per-slot temp + code) instead of the
    old ``hbm * 0.92 - params`` guess. Returns the budget plus every
    component, so a consumer (bench JSON, capacity planning) can see what
    the chip's HBM actually buys.

    ``shared_prefix_tokens`` adds the prefix-store accounting
    (serve/prefix.py): when every request carries that much shared
    system/template prefix, the shared blocks are paid ONCE (one
    refcounted physical copy in the pool) and each slot privately holds
    only its unshared tail — the per-slot marginal KV cost drops by the
    shared fraction and the slot budget rises accordingly.

    ``quant_kv`` ('int8' | 'fp8_e4m3') additionally compiles the
    QUANTIZED decode step at the same two slot counts and reports its
    budget (``max_slots_quant``, ``quant_slot_ratio``) next to the bf16
    number — the ROADMAP item 4 capacity gain, measured from the
    quantized step's own memory plan (smaller pools, extra scale rows,
    dequant scratch) rather than assumed from the dtype ratio."""
    capacity = blocks_for(max_len, kv_block) * kv_block
    one = decode_step_analysis(
        cfg, slots=1, capacity=capacity, kv_block=kv_block,
        decode_impl=decode_impl,
    )
    if "temp_bytes" not in one:
        # aot_analysis returned nothing (backend without memory_analysis):
        # a budget of hbm - params with ZERO scratch/code margin would be
        # MORE optimistic than the formula this module replaces, while
        # wearing the "measured" label — refuse, so callers fall back to
        # the formula and say so
        raise RuntimeError(
            "compiled decode step exposes no memory_analysis on this "
            "backend; slot budget cannot be measured"
        )
    two = decode_step_analysis(
        cfg, slots=2, capacity=capacity, kv_block=kv_block,
        decode_impl=decode_impl,
    )
    temp1 = int(one.get("temp_bytes", 0))
    temp2 = int(two.get("temp_bytes", temp1))
    per_slot_temp = max(temp2 - temp1, 0)
    fixed_temp = max(temp1 - per_slot_temp, 0)
    code = int(one.get("generated_code_bytes", 0))
    param_bytes = one["param_bytes"]
    # per-slot KV bytes are exact from the block math (one slot's private
    # blocks; the shared scratch block sits in cache_bytes, not here)
    per_slot_kv = one["kv_bytes_per_slot"]
    # the hypothetical repeat-expanded layout keeps K/V at n_heads width —
    # the capacity the native-GQA decode kernel exists to avoid paying
    per_slot_kv_repeat = per_slot_kv * cfg.n_heads // cfg.n_kv_heads
    budget = hbm_bytes - param_bytes - fixed_temp - code
    native = max(budget // (per_slot_kv + per_slot_temp), 0) if budget > 0 else 0
    repeat = (
        max(budget // (per_slot_kv_repeat + per_slot_temp), 0)
        if budget > 0 else 0
    )
    out = {
        "hbm_bytes": int(hbm_bytes),
        "param_bytes": int(param_bytes),
        "fixed_temp_bytes": int(fixed_temp),
        "per_slot_temp_bytes": int(per_slot_temp),
        "generated_code_bytes": code,
        "kv_bytes_per_slot_native": int(per_slot_kv),
        "kv_bytes_per_slot_repeat": int(per_slot_kv_repeat),
        "max_slots_native": int(native),
        "max_slots_repeat": int(repeat),
        "source": "memory_analysis",
    }
    if shared_prefix_tokens > 0:
        # shared-block accounting: the prefix's blocks exist once in the
        # pool (refcounted), each slot pays only its unshared tail
        shared_bytes, per_slot_private, slots_shared = _shared_budget(
            per_slot_kv, per_slot_temp, budget,
            shared_prefix_tokens, max_len, kv_block,
        )
        out["shared_prefix_tokens"] = int(shared_prefix_tokens)
        out["shared_prefix_bytes"] = int(shared_bytes)
        out["kv_bytes_per_slot_prefix_shared"] = int(per_slot_private)
        out["max_slots_prefix_shared"] = int(slots_shared)
    if quant_kv:
        q1 = decode_step_analysis(
            cfg, slots=1, capacity=capacity, kv_block=kv_block,
            decode_impl=decode_impl, quant_kv=quant_kv,
        )
        q2 = decode_step_analysis(
            cfg, slots=2, capacity=capacity, kv_block=kv_block,
            decode_impl=decode_impl, quant_kv=quant_kv,
        )
        qtemp1 = int(q1.get("temp_bytes", 0))
        qtemp2 = int(q2.get("temp_bytes", qtemp1))
        q_slot_temp = max(qtemp2 - qtemp1, 0)
        q_fixed = max(qtemp1 - q_slot_temp, 0)
        q_code = int(q1.get("generated_code_bytes", 0))
        per_slot_kv_q = q1["kv_bytes_per_slot"]
        budget_q = hbm_bytes - q1["param_bytes"] - q_fixed - q_code
        quant = (
            max(budget_q // (per_slot_kv_q + q_slot_temp), 0)
            if budget_q > 0 else 0
        )
        out["quant_kv"] = quant_kv
        out["fixed_temp_bytes_quant"] = int(q_fixed)
        out["per_slot_temp_bytes_quant"] = int(q_slot_temp)
        out["kv_bytes_per_slot_quant"] = int(per_slot_kv_q)
        out["max_slots_quant"] = int(quant)
        out["quant_slot_ratio"] = (
            round(quant / native, 3) if native else 0.0
        )
        if shared_prefix_tokens > 0:
            # shared blocks priced at QUANTIZED bytes: a refcounted
            # prefix block in a quantized pool carries the int8/fp8
            # payload plus its scale rows, nothing more
            q_shared, q_private, q_slots_shared = _shared_budget(
                per_slot_kv_q, q_slot_temp, budget_q,
                shared_prefix_tokens, max_len, kv_block,
            )
            out["shared_prefix_bytes_quant"] = int(q_shared)
            out["kv_bytes_per_slot_quant_prefix_shared"] = int(q_private)
            out["max_slots_quant_prefix_shared"] = int(q_slots_shared)
    return out


def _shared_budget(per_slot_kv: int, per_slot_temp: int, budget: int,
                   shared_prefix_tokens: int, max_len: int,
                   kv_block: int) -> tuple[int, int, int]:
    """(shared bytes paid once, per-slot private KV bytes, slot budget)
    under prefix sharing — the common math for the bf16 and quantized
    variants, each feeding its own per-slot KV price."""
    total_blocks = blocks_for(max_len, kv_block)
    shared_blocks = min(shared_prefix_tokens // kv_block, total_blocks)
    per_block = per_slot_kv // total_blocks
    shared_bytes = shared_blocks * per_block
    per_slot_private = per_slot_kv - shared_bytes
    budget_shared = budget - shared_bytes
    slots_shared = (
        max(budget_shared // (per_slot_private + per_slot_temp), 0)
        if budget_shared > 0 and (per_slot_private + per_slot_temp) > 0
        else 0
    )
    return shared_bytes, per_slot_private, slots_shared


__all__ = ["decode_step_analysis", "derive_slot_budget"]

"""TonyClient: submit and track one application.

Rebuild of the reference's ``TonyClient`` (SURVEY.md sections 2, 3.1): parse
config, stage the user's src dir + config into the application dir (the HDFS
staging analogue), launch the ApplicationMaster, then poll status until the
job is terminal and propagate its exit code. Where the reference submits an
AM container to the YARN RM and polls application reports, this client spawns
the AM process directly (the local substrate's RM role) and polls the AM's
own status RPC.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import subprocess
import sys
import time
import uuid

import grpc

from tony_tpu.config.config import TonyConfig
from tony_tpu.config.keys import Keys
from tony_tpu.rpc import ApplicationRpcClient

log = logging.getLogger(__name__)

TERMINAL_STATES = {"SUCCEEDED", "FAILED", "KILLED"}


def default_apps_root() -> str:
    return os.environ.get(
        "TONY_APPS_ROOT", os.path.join(os.path.expanduser("~"), ".tony-tpu", "apps")
    )


def resolve_app_dir(app: str) -> str:
    """Accept an app id (under the apps root) or a path to an app dir."""
    if os.path.isdir(app):
        return os.path.abspath(app)
    candidate = os.path.join(default_apps_root(), app)
    if os.path.isdir(candidate):
        return candidate
    raise FileNotFoundError(f"unknown application {app!r}")


class TonyClient:
    def __init__(self, config: TonyConfig, src_dir: str = ""):
        self.config = config
        self.src_dir = src_dir
        self.app_id = self._make_app_id()
        stage_root = config.get_str(Keys.APPLICATION_PREPARE_STAGE_DIR) or default_apps_root()
        self.app_dir = os.path.join(stage_root, self.app_id)
        self._am_proc: subprocess.Popen | None = None

    def _make_app_id(self) -> str:
        name = self.config.get_str(Keys.APPLICATION_NAME, "tony-tpu-job")
        safe = "".join(c if c.isalnum() or c in "-_" else "-" for c in name)
        return f"{safe}-{time.strftime('%Y%m%d-%H%M%S')}-{uuid.uuid4().hex[:6]}"

    # --- submission ----------------------------------------------------------

    def stage(self) -> None:
        """Materialise the application dir: config.json + src/ copy + token."""
        os.makedirs(self.app_dir, exist_ok=True)
        with open(os.path.join(self.app_dir, "config.json"), "w") as f:
            f.write(self.config.to_json())
        if self.src_dir:
            dst = os.path.join(self.app_dir, "src")
            shutil.copytree(self.src_dir, dst, dirs_exist_ok=True)
        self._token = None
        if self.config.get_bool(Keys.APPLICATION_SECURITY_ENABLED, False):
            from tony_tpu.rpc.auth import mint_token

            # The delegation-token analogue: minted at staging, file-scoped
            # (0600), required on every control-plane RPC.
            self._token = mint_token(self.app_dir)

    def launch_am(self, am_attempt: int = 0) -> None:
        am_log = open(os.path.join(self.app_dir, "am.log"), "ab")
        env = dict(os.environ)
        # Make the tony_tpu package importable in the AM (and, transitively,
        # in executors) even when it is run from a source checkout.
        import tony_tpu

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(tony_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env["TONY_AM_ATTEMPT"] = str(am_attempt)
        self._am_attempt = am_attempt
        # a stale address file would point the monitor at the dead AM
        try:
            os.remove(os.path.join(self.app_dir, "am.addr"))
        except OSError:
            pass
        self._am_proc = subprocess.Popen(
            [sys.executable, "-m", "tony_tpu.am.app_master", self.app_dir],
            stdout=am_log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
            env=env,
        )
        log.info(
            "launched AM pid=%d attempt=%d app_dir=%s",
            self._am_proc.pid, am_attempt, self.app_dir,
        )

    def am_address(self, timeout_s: float = 30.0) -> str:
        path = os.path.join(self.app_dir, "am.addr")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(path):
                with open(path) as f:
                    return f.read().strip()
            if self._am_proc is not None and self._am_proc.poll() is not None:
                raise RuntimeError(
                    f"AM exited early (code {self._am_proc.returncode}); "
                    f"see {os.path.join(self.app_dir, 'am.log')}"
                )
            time.sleep(0.2)
        raise TimeoutError("AM did not publish its address in time")

    # --- tracking -------------------------------------------------------------

    def monitor(self, poll_interval_s: float = 1.0, quiet: bool = False) -> int:
        """Poll status until terminal, relaunching a dead AM up to
        am.retry_count times (the YARN application-attempt analogue: the RM
        role the client plays on the local substrate includes AM retries)."""
        max_retries = self.config.get_int(Keys.AM_RETRY_COUNT, 0)
        while True:
            try:
                code = self._monitor_attempt(poll_interval_s, quiet)
            except (RuntimeError, TimeoutError) as e:
                # am_address() failures (AM died before publishing its
                # address) consume a retry like any other AM death
                log.warning("AM attempt unusable: %s", e)
                code = None
            if code is not None:
                return code
            # AM vanished mid-job without a terminal status file.
            attempt = getattr(self, "_am_attempt", 0)
            if attempt >= max_retries:
                log.error("AM vanished without status.json; retries exhausted")
                return 1
            if not quiet:
                print(f"[{self.app_id}] AM died; relaunching (attempt {attempt + 1})")
            self.launch_am(am_attempt=attempt + 1)

    def _monitor_attempt(self, poll_interval_s: float, quiet: bool) -> int | None:
        """One AM attempt's report loop. Returns the final exit code, or
        None if the AM vanished before reaching a terminal state."""
        addr = self.am_address()
        client = ApplicationRpcClient(addr, token=getattr(self, "_token", None))
        last_states: dict[str, str] = {}
        printed_tb = False
        try:
            while True:
                try:
                    status = client.get_application_status()
                except grpc.RpcError:
                    if self._am_proc is not None and self._am_proc.poll() is None:
                        # AM process alive: transient RPC failure (deadline,
                        # thread-pool pressure) — keep polling, do NOT declare
                        # the attempt dead or we'd launch a duplicate AM that
                        # reaps the live one's containers.
                        time.sleep(poll_interval_s)
                        continue
                    # AM gone: fall back to the status file it wrote on exit.
                    return self._final_from_status_file()
                if not quiet:
                    for t in status.tasks:
                        tid = f"{t.job_name}:{t.index}"
                        if last_states.get(tid) != t.state:
                            last_states[tid] = t.state
                            print(f"[{self.app_id}] {tid} -> {t.state}")
                    if status.tensorboard_url and not printed_tb:
                        printed_tb = True
                        print(f"[{self.app_id}] tensorboard: {status.tensorboard_url}")
                if status.state in TERMINAL_STATES:
                    if not quiet:
                        print(
                            f"[{self.app_id}] {status.state}"
                            + (f": {status.diagnostics}" if status.diagnostics else "")
                        )
                    self._await_am_exit()
                    return status.exit_code
                time.sleep(poll_interval_s)
        finally:
            client.close()

    def _await_am_exit(self, timeout_s: float = 15.0) -> None:
        if self._am_proc is None:
            return
        try:
            self._am_proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self._am_proc.terminate()

    def _final_from_status_file(self) -> int | None:
        """Exit code from the AM's final status file, or None if the AM died
        without writing one (the caller may retry the AM)."""
        path = os.path.join(self.app_dir, "status.json")
        for _ in range(25):
            if os.path.exists(path):
                with open(path) as f:
                    status = json.load(f)
                print(f"[{self.app_id}] {status['state']} (from status.json)")
                return int(status["exit_code"])
            time.sleep(0.2)
        return None

    # --- one-shot -------------------------------------------------------------

    def run(self, quiet: bool = False) -> int:
        """stage -> launch AM -> monitor -> exit code (TonyClient.run analogue)."""
        submitted_at = time.time()  # BEFORE staging: staging is part of the cost
        self.stage()
        with open(os.path.join(self.app_dir, "submitted_at"), "w") as f:
            json.dump({"ts": submitted_at}, f)
        self.launch_am()
        return self.monitor(quiet=quiet)


__all__ = ["TonyClient", "TERMINAL_STATES", "default_apps_root", "resolve_app_dir"]

"""Submission surface: TonyClient + the `tony` CLI (run as python -m tony_tpu.cli)."""

"""Notebook jobs: the NotebookSubmitter analogue.

The reference's tony-cli ships a NotebookSubmitter that runs a single-container
Jupyter notebook on the cluster and a proxy so the user's browser can reach it
(SURVEY.md section 2 "tony-cli", "tony-proxy"). Same composition here:

- ``tony notebook --conf job.toml`` rewrites the job to one ``notebook`` task
  whose command is this module; submits it through the normal TonyClient path.
- Inside the container, :func:`run_notebook` picks a free port, announces its
  URL to the AM over the existing RegisterTensorBoardUrl RPC (the one URL
  channel the control plane already has), and starts Jupyter — or, when
  jupyter is not installed (this image), a minimal stdlib HTTP console page so
  the wiring is still real and testable offline.
- The client polls status until the URL appears, then starts an
  obs.proxy.ProxyServer to it and prints the local address.
"""

from __future__ import annotations

import os
import shutil
import sys
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import grpc

from tony_tpu.config.config import TonyConfig
from tony_tpu.config.keys import Keys
from tony_tpu.utils.net import find_free_port, local_host

NOTEBOOK_JOB_TYPE = "notebook"


# --- container side -----------------------------------------------------------


def _fallback_page() -> str:
    return (
        "<!doctype html><html><head><title>tony-tpu notebook</title></head>"
        "<body><h1>tony-tpu notebook container</h1>"
        "<p>jupyter is not installed in this image; this placeholder proves "
        "the container &rarr; AM &rarr; proxy wiring. Install jupyter to get "
        "a real notebook here.</p>"
        f"<p>host: {local_host()} pid: {os.getpid()}</p></body></html>"
    )


class _FallbackHandler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def do_GET(self):  # noqa: N802 (stdlib casing)
        raw = _fallback_page().encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)


def _announce(url: str) -> None:
    from tony_tpu.obs.reporter import MetricsReporter

    reporter = MetricsReporter()
    reporter.register_tensorboard(url)
    reporter.close()


def run_notebook() -> int:
    """Entry point of the in-container notebook process.

    The invariant both paths keep: the port is LISTENING before the URL is
    announced, because the client proxies to the URL the moment it appears
    in status.
    """
    host = local_host()
    if shutil.which("jupyter"):
        import socket
        import subprocess

        port = find_free_port()
        proc = subprocess.Popen(
            [
                "jupyter", "notebook", "--no-browser", "--allow-root",
                f"--ip={host}", f"--port={port}", "--port-retries=0",
                "--ServerApp.token=", "--ServerApp.password=",
            ],
        )
        # announce only once jupyter is accepting connections
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and proc.poll() is None:
            try:
                socket.create_connection((host, port), timeout=1).close()
                break
            except OSError:
                time.sleep(0.25)
        if proc.poll() is not None:
            print("jupyter exited before listening", flush=True)
            return proc.returncode or 1
        _announce(f"http://{host}:{port}")
        return proc.wait()
    server = ThreadingHTTPServer((host, 0), _FallbackHandler)
    url = f"http://{host}:{server.server_address[1]}"
    _announce(url)
    print(f"notebook fallback page serving on {url}", flush=True)
    server.serve_forever()
    return 0


# --- client side --------------------------------------------------------------


def notebook_config(base: TonyConfig, memory_mb: int = 2048, cpus: int = 1,
                    tpu_chips: int = 0) -> TonyConfig:
    """Rewrite a job config to a single tracked notebook container, keeping
    the cluster/security/history settings of the base config."""
    values = {
        k: v for k, v in base.to_dict().items() if not k.startswith("job.")
    }
    values["job.notebook.instances"] = 1
    values["job.notebook.memory_mb"] = memory_mb
    values["job.notebook.cpus"] = cpus
    values["job.notebook.tpu_chips"] = tpu_chips
    values["job.notebook.command"] = "python -m tony_tpu.cli.notebook"
    values[Keys.APPLICATION_FRAMEWORK] = "generic"
    return TonyConfig(values)


def launch_notebook(config: TonyConfig, *, listen_port: int = 0,
                    timeout_s: float = 60.0):
    """Submit the notebook job and proxy to it.

    Returns ``(client, proxy, url)`` once the in-container process has
    announced its URL; the caller monitors/stops the job. Raises on timeout
    or early job death.
    """
    from tony_tpu.cli.client import TERMINAL_STATES, TonyClient
    from tony_tpu.obs.proxy import ProxyServer
    from tony_tpu.rpc import ApplicationRpcClient
    from tony_tpu.rpc.auth import read_token

    client = TonyClient(config)
    client.stage()
    try:
        client.launch_am()
        addr = client.am_address()
        url = ""
        deadline = time.monotonic() + timeout_s
        with ApplicationRpcClient(addr, token=read_token(client.app_dir)) as c:
            while time.monotonic() < deadline:
                try:
                    status = c.get_application_status()
                except grpc.RpcError:
                    time.sleep(0.3)
                    continue
                if status.tensorboard_url:
                    url = status.tensorboard_url
                    break
                if status.state in TERMINAL_STATES:
                    raise RuntimeError(
                        f"notebook job {client.app_id} ended before announcing "
                        f"a URL ({status.state}: {status.diagnostics})"
                    )
                time.sleep(0.3)
        if not url:
            raise TimeoutError(
                f"notebook {client.app_id} did not announce its URL in time"
            )
    except Exception:
        _stop_job(client)  # don't leak a running AM + container
        raise
    target = url.split("//", 1)[-1]
    proxy = ProxyServer(target, listen_port=listen_port).start()
    return client, proxy, url


def _stop_job(client) -> None:
    """Best-effort teardown of a half-started notebook job."""
    from tony_tpu.rpc import ApplicationRpcClient
    from tony_tpu.rpc.auth import read_token

    try:
        addr_path = os.path.join(client.app_dir, "am.addr")
        with open(addr_path) as f:
            addr = f.read().strip()
        with ApplicationRpcClient(addr, timeout_s=5.0,
                                  token=read_token(client.app_dir)) as c:
            c.stop_application("notebook launch failed")
        client.monitor(quiet=True)
    except Exception:
        proc = getattr(client, "_am_proc", None)
        if proc is not None and proc.poll() is None:
            proc.terminate()


if __name__ == "__main__":
    sys.exit(run_notebook())

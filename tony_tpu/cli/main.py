"""tony: the command-line submission surface.

Rebuild of tony-cli's ClusterSubmitter/status surface (SURVEY.md section 2
"tony-cli"): ``tony submit`` stages and runs a job to completion;
``status`` / ``logs`` / ``stop`` / ``history`` operate on existing apps.

    tony submit --conf job.toml --src-dir ./my_model -D job.worker.instances=4
    tony status <app-id>
    tony logs <app-id> [--task worker:0]
    tony stop <app-id>
    tony history [--dir DIR]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

import grpc

from tony_tpu.cli.client import TonyClient, default_apps_root, resolve_app_dir
from tony_tpu.config.config import TonyConfig
from tony_tpu.rpc import ApplicationRpcClient
from tony_tpu.rpc.auth import read_token


def _read_am_addr(app_dir: str) -> str | None:
    path = os.path.join(app_dir, "am.addr")
    if os.path.exists(path):
        with open(path) as f:
            return f.read().strip()
    return None


def cmd_submit(args: argparse.Namespace) -> int:
    config = TonyConfig.load(args.conf, overrides=args.define, read_env=True)
    client = TonyClient(config, src_dir=args.src_dir or "")
    if args.detach:
        client.stage()
        client.launch_am()
        client.am_address()
        print(client.app_id)
        return 0
    return client.run(quiet=args.quiet)


def _status_dict(app_dir: str) -> dict:
    addr = _read_am_addr(app_dir)
    if addr:
        try:
            with ApplicationRpcClient(addr, timeout_s=3.0, token=read_token(app_dir)) as c:
                s = c.get_application_status()
                return {
                    "state": s.state,
                    "exit_code": s.exit_code,
                    "diagnostics": s.diagnostics,
                    "tensorboard_url": s.tensorboard_url,
                    "tasks": [
                        {
                            "task": f"{t.job_name}:{t.index}",
                            "state": t.state,
                            "exit_code": t.exit_code,
                            "attempt": t.attempt,
                            "log": t.log_path,
                        }
                        for t in s.tasks
                    ],
                }
        except grpc.RpcError:
            pass
    path = os.path.join(app_dir, "status.json")
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    return {"state": "UNKNOWN", "exit_code": -1, "tasks": []}


def cmd_status(args: argparse.Namespace) -> int:
    app_dir = resolve_app_dir(args.app)
    print(json.dumps(_status_dict(app_dir), indent=2, sort_keys=True))
    return 0


def cmd_logs(args: argparse.Namespace) -> int:
    app_dir = resolve_app_dir(args.app)
    logs_dir = os.path.join(app_dir, "logs")
    if args.am:
        entries = [("am.log", os.path.join(app_dir, "am.log"))]
    else:
        names = sorted(os.listdir(logs_dir)) if os.path.isdir(logs_dir) else []
        if args.task:
            prefix = args.task.replace(":", "_") + "_"
            names = [n for n in names if n.startswith(prefix)]
        entries = [(n, os.path.join(logs_dir, n)) for n in names]
    if not entries:
        print("no logs found", file=sys.stderr)
        return 1
    for name, path in entries:
        print(f"===== {name} =====")
        try:
            with open(path, errors="replace") as f:
                sys.stdout.write(f.read())
        except OSError as e:
            print(f"<unreadable: {e}>")
    return 0


def cmd_stop(args: argparse.Namespace) -> int:
    app_dir = resolve_app_dir(args.app)
    addr = _read_am_addr(app_dir)
    if not addr:
        print("AM address unknown; application may not be running", file=sys.stderr)
        return 1
    try:
        with ApplicationRpcClient(addr, timeout_s=5.0, token=read_token(app_dir)) as c:
            c.stop_application(args.reason)
        print("stop requested")
        return 0
    except grpc.RpcError:
        print("AM unreachable (already finished?)", file=sys.stderr)
        return 1


def cmd_notebook(args: argparse.Namespace) -> int:
    from tony_tpu.cli.notebook import launch_notebook, notebook_config

    base = TonyConfig.load(args.conf, overrides=args.define, read_env=True)
    config = notebook_config(
        base, memory_mb=args.memory_mb, cpus=args.cpus, tpu_chips=args.tpu_chips
    )
    try:
        client, proxy, url = launch_notebook(config, listen_port=args.listen)
    except (RuntimeError, TimeoutError) as e:
        print(f"notebook failed to start: {e}", file=sys.stderr)
        return 1
    print(f"[{client.app_id}] notebook at http://127.0.0.1:{proxy.port}/ "
          f"(proxied to {url})")
    print(f"stop with: tony stop {client.app_id}")
    try:
        return client.monitor(quiet=args.quiet)
    finally:
        proxy.stop()


def cmd_history(args: argparse.Namespace) -> int:
    root = args.dir or default_apps_root()
    rows = []
    if os.path.isdir(root):
        for app_id in sorted(os.listdir(root)):
            status_path = os.path.join(root, app_id, "status.json")
            state = "RUNNING?"
            code = ""
            if os.path.exists(status_path):
                with open(status_path) as f:
                    s = json.load(f)
                state, code = s["state"], s["exit_code"]
            rows.append((app_id, state, str(code)))
    if not rows:
        print("no applications found")
        return 0
    width = max(len(r[0]) for r in rows)
    for app_id, state, code in rows:
        print(f"{app_id:<{width}}  {state:<10} {code}")
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    """`tony serve`: gang-serving as a first-class job type (docs/SERVE.md
    "Gang serving"). Submits an AM-supervised gang of decode hosts
    (serve/gang.py), runs the routing frontend in THIS process, and either
    drives a demo batch (--demo N) or serves until interrupted. The job is
    stopped on exit; a deliberate stop exits 0."""
    from tony_tpu.config.keys import Keys, job_key
    from tony_tpu.obs import trace
    from tony_tpu.serve.frontend import GangFrontend
    from tony_tpu.serve.gang import GangSettings

    config = TonyConfig.load(args.conf, overrides=args.define, read_env=True)
    config.set(Keys.APPLICATION_FRAMEWORK, "serve")
    if args.hosts:
        config.set(Keys.SERVE_GANG_HOSTS, args.hosts)
    settings = GangSettings.from_config(config)
    gang_type = settings.job_type
    config.set(job_key(gang_type, "instances"), settings.hosts)
    if not config.get_str(job_key(gang_type, "command")):
        config.set(
            job_key(gang_type, "command"),
            f"{sys.executable} -m tony_tpu.serve.gang",
        )
    if settings.prefill_hosts > 0:
        # disaggregated gang: a second task type carries the prefill pool
        # (same worker binary; pool membership comes from the job name)
        ptype = settings.prefill_job_type
        config.set(job_key(ptype, "instances"), settings.prefill_hosts)
        if not config.get_str(job_key(ptype, "command")):
            config.set(
                job_key(ptype, "command"),
                f"{sys.executable} -m tony_tpu.serve.gang",
            )
    client = TonyClient(config, src_dir=args.src_dir or "")
    client.stage()
    client.launch_am()
    fe = None
    deliberate_stop = False
    try:
        am_addr = client.am_address()
        pools = f"gang of {settings.hosts} x {gang_type}"
        if settings.prefill_hosts > 0:
            pools += f" + {settings.prefill_hosts} x {settings.prefill_job_type}"
        print(f"[{client.app_id}] {pools} (model={settings.model})")
        trace.install_from_config(
            config, client.app_dir, client.app_id, proc="frontend"
        )
        from tony_tpu.cluster.backend import Resource
        from tony_tpu.cluster.lease import GangAsk, LeaseStore

        rm_root = config.get_str(Keys.CLUSTER_RM_ROOT, "")
        gang_spec = config.task_spec(gang_type)
        # autoscale asks must mirror the real containers, PER POOL — a
        # heterogeneous gang growing on a prefill backlog must lease a
        # prefill-sized container, not a decode one
        grow_asks = {
            "decode": GangAsk(
                Resource(gang_spec.memory_mb, gang_spec.cpus, gang_spec.tpu_chips),
                node_label=gang_spec.node_label,
            ),
        }
        if settings.prefill_hosts > 0:
            pspec = config.task_spec(settings.prefill_job_type)
            grow_asks["prefill"] = GangAsk(
                Resource(pspec.memory_mb, pspec.cpus, pspec.tpu_chips),
                node_label=pspec.node_label,
            )
        fe = GangFrontend(
            am_addr, settings, app_dir=client.app_dir,
            token=read_token(client.app_dir), app_id=client.app_id,
            lease_store=LeaseStore(rm_root) if rm_root else None,
            grow_asks=grow_asks,
        )
        ready = fe.wait_ready()
        print(f"[{client.app_id}] {ready} decode host(s) serving")
        if args.demo:
            import random

            rng = random.Random(settings.seed)
            prompts = [
                [rng.randrange(1, 128) for _ in range(rng.randrange(3, 12))]
                for _ in range(args.demo)
            ]
            done = fe.run(prompts, max_new_tokens=args.max_new_tokens)
            for rid in sorted(done, key=lambda r: int(r[1:])):
                c = done[rid]
                print(f"  {rid}: {len(c.tokens)} tokens ({c.finish_reason}, "
                      f"ttft {c.ttft_s:.3f}s, hosts {','.join(c.hosts)})")
            deliberate_stop = True
        else:
            print("serving; Ctrl-C to stop")
            try:
                while True:
                    import time as _time

                    _time.sleep(5.0)
            except KeyboardInterrupt:
                deliberate_stop = True
    finally:
        if fe is not None:
            fe.close()
        try:
            with ApplicationRpcClient(
                client.am_address(timeout_s=5.0),
                timeout_s=5.0, token=read_token(client.app_dir),
            ) as c:
                c.stop_application("tony serve exiting")
        except (grpc.RpcError, RuntimeError, TimeoutError):
            pass
    rc = client.monitor(quiet=True)
    return 0 if deliberate_stop else rc


def cmd_chaos(args: argparse.Namespace) -> int:
    """Run one real job under a seeded fault schedule and print the
    recovery-invariant report (docs/CHAOS.md). Exit 0 iff the report is
    clean AND the final state matches --expect (when given) — the JOB is
    allowed to fail; that is often the point of the schedule."""
    from tony_tpu.chaos import parse_faults
    from tony_tpu.chaos.runner import run_chaos_job
    from tony_tpu.config.keys import Keys

    config = TonyConfig.load(args.conf, overrides=args.define, read_env=True)
    faults = args.faults
    if faults.startswith("@"):
        with open(faults[1:]) as f:
            faults = f.read()
    if faults:
        config.set(Keys.CHAOS_FAULTS, faults)
    try:  # malformed/empty schedule: fail before submitting anything
        if not parse_faults(config.get(Keys.CHAOS_FAULTS)):
            raise ValueError("no faults scheduled (chaos.faults is empty)")
    except ValueError as e:
        print(f"bad fault schedule: {e}", file=sys.stderr)
        return 2
    result = run_chaos_job(config, src_dir=args.src_dir or "", quiet=args.quiet)
    print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
    ok = result.report.ok
    if args.expect and result.state != args.expect:
        print(
            f"expected final state {args.expect} but job ended {result.state or 'UNKNOWN'}",
            file=sys.stderr,
        )
        ok = False
    return 0 if ok else 1


def cmd_trace(args: argparse.Namespace) -> int:
    """Merge an application's per-process trace journals into ONE
    Chrome-trace-event JSON (open in Perfetto / chrome://tracing), plus a
    goodput roll-up and cross-host straggler flags (docs/OBS.md)."""
    from tony_tpu.obs.trace_tool import load_journals, merge_chrome, report

    app_dir = resolve_app_dir(args.app)
    # journals can be large (rotating windows per process) — parse once,
    # share across merge and report
    procs = load_journals(os.path.join(app_dir, "trace"))
    merged = merge_chrome(app_dir, procs)
    # count every renderable event (complete X, begin-only B from killed
    # processes, instants) — a job whose every process died early still
    # has exactly the flight-recorder data worth merging
    n_events = sum(
        1 for e in merged["traceEvents"] if e.get("ph") in ("X", "B", "i", "C")
    )
    if n_events == 0:
        print(
            f"no trace journals under {os.path.join(app_dir, 'trace')} "
            "(job predates tracing, or trace.enabled was false)",
            file=sys.stderr,
        )
        return 1
    out_path = args.out or os.path.join(app_dir, "trace.json")
    with open(out_path, "w") as f:
        json.dump(merged, f)
    summary = report(app_dir, procs)
    summary["out"] = out_path
    summary["events"] = len(merged["traceEvents"])
    print(json.dumps(summary, indent=2, sort_keys=True))
    return 0


def cmd_compiles(args: argparse.Namespace) -> int:
    """Report an application's compile ledgers (obs/compiles.py): per
    process, every XLA backend compile with its duration and attributed
    fn name, plus the AOT entry points' measured memory plans
    (memory_analysis temp/argument/output/code bytes) and cost-analysis
    FLOPs — the 'what compiled, when, and what it costs in HBM' answer."""
    from tony_tpu.obs.compiles import read_app_ledgers, summarize

    app_dir = resolve_app_dir(args.app)
    ledgers = read_app_ledgers(app_dir)
    if not ledgers:
        print(
            f"no compile ledgers under {os.path.join(app_dir, 'compiles')} "
            "(job predates the ledger, or no JAX process ran fit()/serve)",
            file=sys.stderr,
        )
        return 1
    print(json.dumps(summarize(ledgers), indent=2, sort_keys=True))
    return 0


def cmd_health(args: argparse.Namespace) -> int:
    """Roll up an application's numerics-health verdicts + forensics
    bundles (obs/health.py; docs/OBS.md "Numerics health"). Exit 0 =
    healthy, 1 = tripped, 2 = no health data (job predates the sentinel,
    obs.health.enabled was false, or every process died before a verdict
    landed — absence is reported, never read as healthy)."""
    from tony_tpu.obs import health

    app_dir = resolve_app_dir(args.app)
    roll = health.rollup(app_dir)
    if roll["verdict"] == "unknown":
        print(
            f"no health verdicts under {os.path.join(app_dir, 'health')} "
            "(job predates the sentinel, or obs.health.enabled was false)",
            file=sys.stderr,
        )
        return 2
    if args.bundles:
        bundles = {}
        for name in roll["bundles"]:
            try:
                with open(os.path.join(app_dir, "health", name)) as f:
                    bundles[name] = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                bundles[name] = {"unreadable": str(e)}
        roll["bundle_contents"] = bundles
    print(json.dumps(roll, indent=2, sort_keys=True))
    return 0 if roll["verdict"] == "healthy" else 1


def cmd_elastic(args: argparse.Namespace) -> int:
    """Audit an application's elastic membership history (docs/ELASTIC.md):
    every declared generation, each trainer journal's reshard boundaries
    with their skipped data ranges, and the current membership. Exit 0 =
    history present, 2 = the job never declared a generation (not an
    elastic job, or it died before the start record)."""
    from tony_tpu.elastic.protocol import (
        journal_files, read_history, read_journal,
    )

    app_dir = resolve_app_dir(args.app)
    history = read_history(app_dir)
    if not history:
        print(
            f"no elastic generations under {os.path.join(app_dir, 'elastic')} "
            "(not an elastic job?)",
            file=sys.stderr,
        )
        return 2
    out = {
        "generations": [r.to_dict() for r in history],
        "current": history[-1].to_dict(),
        "journals": {},
    }
    for path in journal_files(app_dir):
        recs = read_journal(path)
        steps = [r for r in recs if r.get("type") == "step"]
        out["journals"][os.path.basename(path)] = {
            "steps": len(steps),
            "first_step": steps[0]["step"] if steps else None,
            "last_step": steps[-1]["step"] if steps else None,
            "reshards": [r for r in recs if r.get("type") == "reshard"],
        }
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0


def cmd_profile(args: argparse.Namespace) -> int:
    """``tony profile <app_id> [--steps N | --seconds T]``: ask the AM to
    broadcast a bounded capture window to every process of the job, wait
    for the per-process manifests to land, and print the step-anatomy
    report (docs/OBS.md "Step anatomy"). ``tony profile report <app_id>``
    reports on an existing capture without triggering a new one."""
    import time as _time

    from tony_tpu.obs import profile as profile_mod
    from tony_tpu.obs.anatomy import build_anatomy

    target = list(args.target)
    report_only = target and target[0] == "report"
    if report_only:
        target = target[1:]
    if len(target) != 1:
        print("usage: tony profile [report] <app_id>", file=sys.stderr)
        return 2
    app_dir = resolve_app_dir(target[0])
    profile_id = args.id

    if not report_only:
        addr = _read_am_addr(app_dir)
        if not addr:
            print("AM address unknown; application may not be running",
                  file=sys.stderr)
            return 1
        steps = args.steps
        if steps <= 0 and args.seconds <= 0:
            steps = 3  # the useful default: three full steps
        try:
            with ApplicationRpcClient(
                addr, timeout_s=10.0, token=read_token(app_dir)
            ) as c:
                resp = c.start_profile(steps=steps, duration_s=args.seconds)
        except grpc.RpcError as e:
            print(f"AM unreachable: {e}", file=sys.stderr)
            return 1
        if not resp.accepted:
            print(f"profile refused: {resp.message}", file=sys.stderr)
            return 1
        profile_id = resp.profile_id
        note = f" ({resp.message})" if resp.message else ""
        print(f"profile {profile_id} broadcast{note}; waiting for captures",
              file=sys.stderr)
        if args.no_wait:
            print(json.dumps({"profile_id": profile_id}))
            return 0
        # poll for manifests: done when the landed set has been stable for
        # two rounds (a straggler host finishing later still lands — its
        # manifest is on disk for a later `tony profile report`)
        deadline = _time.monotonic() + args.wait
        seen: set[str] = set()
        stable = 0
        while _time.monotonic() < deadline:
            _time.sleep(1.0)
            procs = set(profile_mod.read_manifests(app_dir, profile_id))
            if procs and procs == seen:
                stable += 1
                if stable >= 2:
                    break
            else:
                stable = 0
                seen = procs

    report = build_anatomy(app_dir, profile_id)
    if not report["procs"]:
        where = os.path.join(app_dir, "profile")
        print(
            f"no capture manifests under {where}"
            + (f" for {profile_id}" if profile_id else "")
            + " (no process reached a step boundary inside the window, or "
            "obs.profile.enabled was false)",
            file=sys.stderr,
        )
        return 1 if not report_only else 2
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


def cmd_top(args: argparse.Namespace) -> int:
    """Live terminal view of one application (docs/OBS.md "SLO + time
    series"): per-host rows off the series journals + AM rollup,
    TTFT/queue-depth sparklines, straggler flags, SLO/health columns."""
    from tony_tpu.obs.top import run_top

    app_dir = resolve_app_dir(args.app)
    try:
        return run_top(app_dir, once=args.once, interval_s=args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_perf(args: argparse.Namespace) -> int:
    """``tony perf diff <old> <new>``: compare two bench reports (or two
    series rollups) under per-section tolerance rules and emit a
    regression verdict. Exit 0 = no regression, 1 = regression(s), 2 =
    unusable input. tests/test_perf_diff.py holds this as a tier-1 gate
    against committed fixtures."""
    from tony_tpu.obs.perf_diff import diff_files

    try:
        verdict = diff_files(args.old, args.new, tol_scale=args.tol_scale)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"cannot diff: {e}", file=sys.stderr)
        return 2
    if not args.full:
        # the printed verdict leads with the judgement; the full key dump
        # stays behind --full so a green diff is one screen
        for k in ("unjudged", "only_old", "only_new"):
            verdict[k] = len(verdict[k])
    print(json.dumps(verdict, indent=2, sort_keys=True))
    return 0 if verdict["ok"] else 1


def cmd_lint(args: argparse.Namespace) -> int:
    """graft-lint: JAX-aware + concurrency-aware static analysis over the
    given paths (docs/ANALYSIS.md). Exit 0 = no non-baselined findings."""
    from tony_tpu.analysis.cli import run_lint

    return run_lint(args)


def cmd_rm_status(args: argparse.Namespace) -> int:
    """Inspect (or clean) the shared ResourceManager lease store — the
    `yarn top` analogue for the cross-job arbitration substrate."""
    from tony_tpu.cluster.lease import LeaseStore

    root = args.rm_root
    if not root and args.conf:
        from tony_tpu.config.config import TonyConfig
        from tony_tpu.config.keys import Keys

        root = TonyConfig.load(args.conf).get_str(Keys.CLUSTER_RM_ROOT, "")
    if not root:
        print(
            "no RM store: pass --rm-root or a --conf with cluster.rm_root set",
            file=sys.stderr,
        )
        return 2
    if not os.path.isdir(os.path.expanduser(root)):
        # inspection must not conjure an empty store out of a typo'd path
        # and report a healthy idle cluster
        print(f"no RM store at {root!r} (directory does not exist)", file=sys.stderr)
        return 2
    store = LeaseStore(root)
    if args.release:
        store.force_release_app(args.release)
        print(f"released all leases of {args.release}")
    print(json.dumps(store.summary(), indent=2))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tony", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("submit", help="submit a job and wait for completion")
    s.add_argument("--conf", help="TOML config file (the tony.xml analogue)")
    s.add_argument("--src-dir", help="source dir staged into containers")
    s.add_argument(
        "-D", "--define", action="append", default=[], metavar="KEY=VALUE",
        help="config override (repeatable; the -Dtony.k=v analogue)",
    )
    s.add_argument("--detach", action="store_true", help="print app id and return")
    s.add_argument("--quiet", action="store_true")
    s.set_defaults(fn=cmd_submit)

    s = sub.add_parser("status", help="show application status JSON")
    s.add_argument("app")
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("logs", help="dump task logs")
    s.add_argument("app")
    s.add_argument("--task", help="restrict to one task, e.g. worker:0")
    s.add_argument("--am", action="store_true", help="show the AM log")
    s.set_defaults(fn=cmd_logs)

    s = sub.add_parser(
        "notebook", help="run a single-container notebook and proxy to it"
    )
    s.add_argument("--conf", help="TOML config (cluster/security settings)")
    s.add_argument(
        "-D", "--define", action="append", default=[], metavar="KEY=VALUE",
        help="config override (repeatable)",
    )
    s.add_argument("--listen", type=int, default=0,
                   help="local proxy port (default: ephemeral)")
    s.add_argument("--memory-mb", type=int, default=2048)
    s.add_argument("--cpus", type=int, default=1)
    s.add_argument("--tpu-chips", type=int, default=0)
    s.add_argument("--quiet", action="store_true")
    s.set_defaults(fn=cmd_notebook)

    s = sub.add_parser("stop", help="stop a running application")
    s.add_argument("app")
    s.add_argument("--reason", default="stopped via CLI")
    s.set_defaults(fn=cmd_stop)

    s = sub.add_parser("history", help="list applications")
    s.add_argument("--dir", help="apps root (default ~/.tony-tpu/apps)")
    s.set_defaults(fn=cmd_history)

    s = sub.add_parser(
        "serve",
        help="run a gang-serving job: AM-scheduled decode hosts + a local "
             "routing frontend (docs/SERVE.md)",
    )
    s.add_argument("--conf", help="TOML config (serve.gang.* + job.<type>.*)")
    s.add_argument("--src-dir", help="source dir staged into containers")
    s.add_argument(
        "-D", "--define", action="append", default=[], metavar="KEY=VALUE",
        help="config override (repeatable)",
    )
    s.add_argument(
        "--hosts", type=int, default=0,
        help="override serve.gang.hosts (decode-host container count)",
    )
    s.add_argument(
        "--demo", type=int, default=0, metavar="N",
        help="submit N demo prompts, print completions, stop the job",
    )
    s.add_argument("--max-new-tokens", type=int, default=32)
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser(
        "chaos",
        help="run a job under a fault schedule and report recovery invariants",
    )
    s.add_argument("--conf", help="TOML config for the job under test")
    s.add_argument("--src-dir", help="source dir staged into containers")
    s.add_argument(
        "-D", "--define", action="append", default=[], metavar="KEY=VALUE",
        help="config override (repeatable)",
    )
    s.add_argument(
        "--faults", default="",
        help="JSON fault schedule (or @file.json); overrides chaos.faults",
    )
    s.add_argument(
        "--expect", default="", choices=["", "SUCCEEDED", "FAILED", "KILLED"],
        help="require this final job state in addition to a clean report",
    )
    s.add_argument("--quiet", action="store_true")
    s.set_defaults(fn=cmd_chaos)

    s = sub.add_parser(
        "trace",
        help="merge an app's trace journals into one Chrome-trace JSON "
             "(Perfetto-loadable) with a goodput/straggler report",
    )
    s.add_argument("app", help="application id or app-dir path")
    s.add_argument(
        "--out", default="",
        help="output path (default <app_dir>/trace.json)",
    )
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser(
        "compiles",
        help="report an app's compile ledgers (per-process XLA compiles, "
             "AOT memory plans and FLOPs)",
    )
    s.add_argument("app", help="application id or app-dir path")
    s.set_defaults(fn=cmd_compiles)

    s = sub.add_parser(
        "health",
        help="roll up an app's numerics-health verdicts and forensics "
             "bundles (exit 0 healthy / 1 tripped / 2 no data)",
    )
    s.add_argument("app", help="application id or app-dir path")
    s.add_argument(
        "--bundles", action="store_true",
        help="inline the forensics bundle contents into the report",
    )
    s.set_defaults(fn=cmd_health)

    s = sub.add_parser(
        "elastic",
        help="audit an app's elastic membership history: generations, "
             "reshard boundaries, skipped data ranges (docs/ELASTIC.md)",
    )
    s.add_argument("app", help="application id or app-dir path")
    s.set_defaults(fn=cmd_elastic)

    s = sub.add_parser(
        "profile",
        help="broadcast a bounded fleet capture window (AM StartProfile) "
             "and print the step-anatomy report; `tony profile report "
             "<app>` reads an existing capture (docs/OBS.md)",
    )
    s.add_argument(
        "target", nargs="+",
        help="application id / app-dir path; prefix with `report` to "
             "report on an existing capture without triggering a new one",
    )
    s.add_argument("--steps", type=int, default=0,
                   help="capture N steps per process (default 3)")
    s.add_argument("--seconds", type=float, default=0.0,
                   help="capture a wall-clock window instead of N steps")
    s.add_argument("--wait", type=float, default=60.0,
                   help="how long to wait for capture manifests")
    s.add_argument("--no-wait", action="store_true",
                   help="trigger and return (report later with "
                        "`tony profile report`)")
    s.add_argument("--id", default="",
                   help="report a specific capture id (default: newest)")
    s.set_defaults(fn=cmd_profile)

    s = sub.add_parser(
        "top",
        help="live per-host view of an app: series sparklines, straggler "
             "flags, SLO/health columns (Ctrl-C exits)",
    )
    s.add_argument("app", help="application id or app-dir path")
    s.add_argument("--once", action="store_true",
                   help="print one frame and exit (scripts, tests)")
    s.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds")
    s.set_defaults(fn=cmd_top)

    s = sub.add_parser(
        "perf",
        help="performance tooling: `perf diff <old> <new>` compares two "
             "bench reports / series rollups and exits 1 on regression",
    )
    psub = s.add_subparsers(dest="perf_command", required=True)
    d = psub.add_parser(
        "diff", help="regression verdict between two reports"
    )
    d.add_argument("old", help="baseline report (BENCH_r*.json, bench.py "
                              "output, or a series rollup)")
    d.add_argument("new", help="candidate report (same shapes)")
    d.add_argument(
        "--tol-scale", type=float, default=1.0,
        help="scale every rule's relative tolerance (noisy rigs > 1.0)",
    )
    d.add_argument(
        "--full", action="store_true",
        help="include the unjudged/one-sided key lists verbatim",
    )
    d.set_defaults(fn=cmd_perf)

    s = sub.add_parser(
        "lint",
        help="run graft-lint static analysis (GL001-GL005: host-sync-in-jit, "
             "recompile-hazard, donation-reuse, lock-discipline, "
             "disarmed-hook-cost)",
    )
    from tony_tpu.analysis.cli import add_lint_args

    add_lint_args(s)
    s.set_defaults(fn=cmd_lint)

    s = sub.add_parser(
        "rm-status",
        help="show the shared ResourceManager store (hosts, leases, queue)",
    )
    s.add_argument("--rm-root", default="", help="lease store directory")
    s.add_argument("--conf", help="TOML config carrying cluster.rm_root")
    s.add_argument(
        "--release", default="", metavar="APP_ID",
        help="force-release a (stale cross-host) app's leases first",
    )
    s.set_defaults(fn=cmd_rm_status)
    return p


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(level=logging.WARNING)
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

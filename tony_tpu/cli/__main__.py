import sys

from tony_tpu.cli.main import main

sys.exit(main())

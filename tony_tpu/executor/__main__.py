from tony_tpu.executor.task_executor import main

main()

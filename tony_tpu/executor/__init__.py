"""TaskExecutor: in-container bootstrap and user-process supervision.

Deliberately does not import task_executor here: the AM launches it as
``python -m tony_tpu.executor`` and an eager re-import would double-import
the module under runpy.
"""

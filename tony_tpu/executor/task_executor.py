"""TaskExecutor: in-container bootstrap.

Rebuild of the reference's ``TaskExecutor`` (SURVEY.md sections 2, 3.2 — the
contract this must replicate): read the AM-injected env; reserve a data port;
register ``(jobName, index, host:port)`` with the AM; block for the cluster
spec (gang barrier); let the framework runtime translate the spec into env;
exec the user process; heartbeat + metrics loops; propagate the exit code
faithfully.

Launched by the AM inside each container as
``python -m tony_tpu.executor.task_executor`` with TONY_* env set.
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
import time

import grpc

from tony_tpu.chaos import chaos_hook
from tony_tpu.obs import trace
from tony_tpu.config.config import TonyConfig
from tony_tpu.config.keys import Keys
from tony_tpu.rpc import ApplicationRpcClient, pb
from tony_tpu.runtime import TaskIdentity, make_runtime
from tony_tpu.utils.net import find_free_port, local_host
from tony_tpu.utils.proc import run_logged

log = logging.getLogger(__name__)

# Exit code when the AM tells us to abort (stale attempt / job teardown);
# mirrors 128+SIGTERM so it reads like a kill in status output.
ABORT_EXIT_CODE = 143


class TaskExecutor:
    def __init__(self) -> None:
        self.job_name = os.environ["TONY_JOB_NAME"]
        self.index = int(os.environ["TONY_TASK_INDEX"])
        self.attempt = int(os.environ.get("TONY_ATTEMPT", "0"))
        self.am_addr = os.environ["TONY_AM_ADDR"]
        self.container_id = os.environ.get("TONY_CONTAINER_ID", "")
        conf_path = os.environ["TONY_CONF_PATH"]
        self.config = TonyConfig.from_json(open(conf_path).read())
        self.spec = self.config.task_spec(self.job_name)
        self.runtime = make_runtime(
            self.config.get_str(Keys.APPLICATION_FRAMEWORK, "jax")
        )
        token = None
        if self.config.get_bool(Keys.APPLICATION_SECURITY_ENABLED, False):
            from tony_tpu.rpc.auth import read_token

            token = read_token(os.environ.get("TONY_APP_DIR", ""))
        self.client = ApplicationRpcClient(self.am_addr, token=token)
        self.host = local_host()
        self.port = find_free_port() if self.runtime.needs_data_port() else 0
        self._abort = threading.Event()
        self._child = None

    # --- bootstrap ----------------------------------------------------------

    def register(self, timeout_s: float = 60.0) -> None:
        """Register with the AM, retrying while its RPC server comes up."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                resp = self.client.register_worker_spec(
                    self.job_name,
                    self.index,
                    self.host,
                    self.port,
                    attempt=self.attempt,
                    container_id=self.container_id,
                )
                if not resp.accepted:
                    raise SystemExit(
                        f"AM rejected registration: {resp.message} (stale attempt?)"
                    )
                return
            except grpc.RpcError as e:
                if time.monotonic() > deadline:
                    raise SystemExit(f"cannot reach AM at {self.am_addr}: {e}") from e
                time.sleep(0.5)

    def await_cluster_spec(self) -> TaskIdentity:
        """Poll GetClusterSpec until the gang barrier opens."""
        timeout_s = self.config.get_float(Keys.TASK_REGISTRATION_TIMEOUT_S, 300.0)
        deadline = time.monotonic() + timeout_s
        while True:
            if self._abort.is_set():
                raise SystemExit(ABORT_EXIT_CODE)
            resp = self.client.get_cluster_spec(self.job_name, self.index, self.attempt)
            if resp.ready:
                return TaskIdentity.from_cluster_spec_response(
                    self.job_name, self.index, resp
                )
            if time.monotonic() > deadline:
                raise SystemExit(
                    f"cluster spec not ready after {timeout_s}s (gang barrier)"
                )
            time.sleep(0.3)

    # --- supervision threads -------------------------------------------------

    def _heartbeat_loop(self) -> None:
        interval = self.config.get_int(Keys.TASK_HEARTBEAT_INTERVAL_MS, 1000) / 1000
        while not self._abort.is_set():
            # chaos seam: kill_container SIGKILLs this process group here
            # (the count is this executor's heartbeat number — "at beat N"
            # is exact); drop_heartbeats returns a suppression and the
            # beat is skipped while the user process keeps running
            if chaos_hook(
                "executor.beat",
                task=f"{self.job_name}:{self.index}",
                attempt=self.attempt,
            ):
                time.sleep(interval)
                continue
            try:
                resp = self.client.heartbeat(self.job_name, self.index, self.attempt)
                if resp.action == pb.HeartbeatResponse.ABORT:
                    log.warning("AM ordered abort; killing user process")
                    trace.instant(
                        "executor.abort", task=f"{self.job_name}:{self.index}"
                    )
                    self._abort.set()
                    break
            except grpc.RpcError:
                # AM temporarily unreachable: keep trying; the AM's own
                # missed-heartbeat accounting decides when we are lost.
                pass
            time.sleep(interval)

    def _metrics_loop(self) -> None:
        if not self.config.get_bool(Keys.METRICS_ENABLED, True):
            return
        from tony_tpu.obs.monitor import TaskMonitor

        interval = self.config.get_int(Keys.METRICS_INTERVAL_MS, 2000) / 1000
        monitor = TaskMonitor()
        while not self._abort.is_set():
            time.sleep(interval)
            try:
                samples = monitor.sample()
                if samples:
                    self.client.push_metrics(self.job_name, self.index, samples)
            except grpc.RpcError:
                pass
            except Exception:
                log.exception("metrics sampling failed")
                return

    # --- main ----------------------------------------------------------------

    def run(self) -> int:
        with trace.span("executor.register",
                        task=f"{self.job_name}:{self.index}"):
            self.register()
        log.info(
            "%s:%d registered at %s:%d (attempt %d); awaiting cluster spec",
            self.job_name, self.index, self.host, self.port, self.attempt,
        )
        # Heartbeat from the moment we are registered (the reference starts
        # its heartbeat right after registration too) — a gang that takes a
        # while to assemble must not look heartbeat-dead to the AM.
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True, name="heartbeat")
        hb.start()
        with trace.span("executor.await_cluster_spec",
                        task=f"{self.job_name}:{self.index}"):
            identity = self.await_cluster_spec()
        env = self.runtime.build_env(identity, self.config)
        env["TONY_APP_ID"] = os.environ.get("TONY_APP_ID", "")
        env["TONY_APP_DIR"] = os.environ.get("TONY_APP_DIR", "")
        env["TONY_EXECUTOR_PID"] = str(os.getpid())
        # This image preloads a TPU PJRT backend into every python process via
        # sitecustomize (gated on PALLAS_AXON_POOL_IPS), which would both
        # seize the chip from non-JAX tasks and pre-initialise backends before
        # the user script's jax.distributed.initialize. Neutralise the preload
        # whenever the job explicitly targets the CPU platform.
        effective_platform = env.get(
            "JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", "")
        )
        if effective_platform == "cpu":
            env["PALLAS_AXON_POOL_IPS"] = ""
        command = self.spec.command
        if not command:
            raise SystemExit(f"job.{self.job_name}.command is empty")
        # Run in the staged source dir (the HDFS src_dir localisation analogue,
        # SURVEY.md section 3.1: client stages src zip -> containers unpack).
        src_dir = os.path.join(os.environ.get("TONY_APP_DIR", ""), "src")
        cwd = src_dir if os.path.isdir(src_dir) else None
        log.info("starting user process: %s (cwd=%s)", command, cwd or ".")
        # the user process joins the trace under its own journal name,
        # rooted on this span (fit()/the engine call trace.install_from_env)
        user_span = trace.span(
            "executor.user_process",
            task=f"{self.job_name}:{self.index}", attempt=self.attempt,
        )
        if trace.active_tracer() is not None:
            env[trace.ENV_PROC] = (
                f"{self.job_name}_{self.index}_user_a{self.attempt}"
            )
            env[trace.ENV_PARENT] = user_span.sid
        self._child = run_logged(command, env=env, cwd=cwd)

        mt = threading.Thread(target=self._metrics_loop, daemon=True, name="metrics")
        mt.start()

        # Forward SIGTERM (container release) to the child so user cleanup runs.
        signal.signal(signal.SIGTERM, lambda *_: self._abort.set())

        while True:
            code = self._child.poll()
            if code is not None:
                self._child.wait()  # drain log pump
                break
            if self._abort.is_set():
                self._child.terminate()
                try:
                    code = self._child.wait(timeout=5)
                except Exception:
                    self._child.kill()
                    code = ABORT_EXIT_CODE
                code = ABORT_EXIT_CODE
                break
            time.sleep(0.2)

        log.info("user process exited with code %d", code)
        user_span.end(exit_code=code)
        self._abort.set()
        try:
            self.client.register_execution_result(
                self.job_name, self.index, code, attempt=self.attempt
            )
        except grpc.RpcError as e:
            # AM may already be tearing down; the container exit code still
            # carries the result (AM's backup path).
            log.warning("could not report result to AM: %s", e)
        self.client.close()
        return code


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s EXEC %(levelname)s %(name)s: %(message)s",
    )
    executor = TaskExecutor()
    # arm fault injection for THIS executor only when the job asks for it
    from tony_tpu.chaos import install_from_config

    install_from_config(executor.config, role="executor")
    # join the trace spine from the AM-exported env (no-op when untraced)
    trace.install_from_env()
    code = executor.run()
    trace.uninstall()  # flush + close the journal before exit
    sys.exit(code)


if __name__ == "__main__":
    main()

"""TonyConfig: the layered configuration object.

Rebuild of TonY's Hadoop-``Configuration`` XML layering (tony-default.xml ->
user tony.xml -> ``-Dtony.k=v`` CLI; SURVEY.md section 5 "Config/flag system"),
TPU-era: defaults registry -> TOML file -> ``key=value`` CLI overrides ->
``TONY_CONF_<KEY>`` env overrides. Values are JSON-serialisable so a config can
be shipped verbatim from client to AM to executors.
"""

from __future__ import annotations

import json
import os

try:
    import tomllib  # Python 3.11+
except ModuleNotFoundError:  # 3.10 image: subset reader, same load() surface
    from tony_tpu.config import _minitoml as tomllib  # type: ignore[no-redef]
from dataclasses import dataclass, field
from typing import Any, Iterator

from tony_tpu.config.keys import DEFAULTS, Keys, job_key

_ENV_PREFIX = "TONY_CONF_"


def _apply_env(values: dict[str, Any]) -> None:
    """Apply ``TONY_CONF_section__key=value`` environment overrides in place."""
    for name, raw in os.environ.items():
        if name.startswith(_ENV_PREFIX):
            key = name[len(_ENV_PREFIX):].lower().replace("__", ".")
            values[key] = _coerce(raw)


def _flatten(tree: dict[str, Any], prefix: str = "") -> Iterator[tuple[str, Any]]:
    for k, v in tree.items():
        dotted = f"{prefix}{k}"
        if isinstance(v, dict):
            yield from _flatten(v, f"{dotted}.")
        else:
            yield dotted, v


def _coerce(raw: str) -> Any:
    """Type-infer a CLI/env override string the way Hadoop's getInt/getBoolean do."""
    low = raw.strip().lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    return raw


@dataclass(frozen=True)
class TaskTypeSpec:
    """Resolved per-jobtype spec (the ``tony.<jobtype>.*`` key group).

    Reference: per-jobtype resource keys consumed by TonyApplicationMaster when
    building container requests (SURVEY.md section 2, "TonyApplicationMaster").
    """

    name: str
    instances: int = 1
    memory_mb: int = 2048
    cpus: int = 1
    tpu_chips: int = 0
    command: str = ""
    env: dict[str, str] = field(default_factory=dict)
    depends_on: str = ""
    depends_timeout_s: int = 0
    untracked: bool = False
    node_label: str = ""

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name,
            "instances": self.instances,
            "memory_mb": self.memory_mb,
            "cpus": self.cpus,
            "tpu_chips": self.tpu_chips,
            "command": self.command,
            "env": dict(self.env),
            "depends_on": self.depends_on,
            "depends_timeout_s": self.depends_timeout_s,
            "untracked": self.untracked,
            "node_label": self.node_label,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TaskTypeSpec":
        return cls(**d)


class TonyConfig:
    """Layered key/value configuration with typed accessors.

    Layers, lowest to highest precedence:
      1. ``DEFAULTS`` (the tony-default.xml analogue)
      2. a TOML file (the user tony.xml analogue)
      3. explicit ``set``/CLI ``key=value`` overrides
      4. ``TONY_CONF_*`` environment overrides (read at construction)
    """

    def __init__(self, values: dict[str, Any] | None = None, *, read_env: bool = False):
        self._values: dict[str, Any] = dict(DEFAULTS)
        if values:
            self._values.update(values)
        if read_env:
            _apply_env(self._values)

    # --- construction -----------------------------------------------------

    @classmethod
    def load(
        cls,
        toml_path: str | os.PathLike[str] | None = None,
        overrides: list[str] | dict[str, Any] | None = None,
        *,
        read_env: bool = False,
    ) -> "TonyConfig":
        cfg = cls(read_env=False)
        if toml_path:
            with open(toml_path, "rb") as f:
                tree = tomllib.load(f)
            for k, v in _flatten(tree):
                cfg._values[k] = v
        if isinstance(overrides, dict):
            cfg._values.update(overrides)
        elif overrides:
            for item in overrides:
                if "=" not in item:
                    raise ValueError(f"override must be key=value, got {item!r}")
                k, _, v = item.partition("=")
                cfg._values[k.strip()] = _coerce(v)
        if read_env:
            _apply_env(cfg._values)
        return cfg

    # --- typed accessors ---------------------------------------------------

    def get(self, key: str, default: Any = None) -> Any:
        return self._values.get(key, default)

    def get_str(self, key: str, default: str = "") -> str:
        v = self._values.get(key, default)
        return "" if v is None else str(v)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self._values.get(key, default)
        return int(v)

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self._values.get(key, default)
        return float(v)

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._values.get(key, default)
        if isinstance(v, str):
            return v.strip().lower() == "true"
        return bool(v)

    def get_list(self, key: str, default: list[str] | None = None) -> list[str]:
        v = self._values.get(key)
        if v is None:
            return list(default or [])
        if isinstance(v, list):
            return [str(x) for x in v]
        return [s.strip() for s in str(v).split(",") if s.strip()]

    def set(self, key: str, value: Any) -> None:
        self._values[key] = value

    def __contains__(self, key: str) -> bool:
        return key in self._values

    # --- per-jobtype resolution ---------------------------------------------

    def job_types(self) -> list[str]:
        """Discover configured job types from ``job.<type>.*`` keys.

        The reference discovers task types by scanning ``tony.<jobtype>.instances``
        keys (Utils.getAllJobTypes analogue).
        """
        types: list[str] = []
        for k in self._values:
            if k.startswith("job.") and k.count(".") >= 2:
                t = k.split(".", 2)[1]
                if t not in types:
                    types.append(t)
        return types

    def task_spec(self, job_type: str) -> TaskTypeSpec:
        def g(suffix: str, default: Any) -> Any:
            return self._values.get(job_key(job_type, suffix), default)

        env_val = g("env", {})
        if isinstance(env_val, str):
            env_val = [s for s in env_val.split(",") if s.strip()]
        if isinstance(env_val, list):  # ["K=V", ...] form from TOML/CLI
            pairs = {}
            for item in env_val:
                if "=" not in item:
                    raise ValueError(
                        f"env entry {item!r} for job type {job_type!r} must be KEY=VALUE"
                    )
                k, _, v = str(item).partition("=")
                pairs[k] = v
            env_val = pairs
        elif not isinstance(env_val, dict):
            env_val = {}

        def as_bool(v: Any) -> bool:
            if isinstance(v, str):
                return v.strip().lower() == "true"
            return bool(v)
        return TaskTypeSpec(
            name=job_type,
            instances=int(g("instances", 1)),
            memory_mb=int(g("memory_mb", 2048)),
            cpus=int(g("cpus", 1)),
            tpu_chips=int(g("tpu_chips", 0)),
            command=str(g("command", "")),
            env={str(k): str(v) for k, v in env_val.items()},
            depends_on=str(g("depends_on", "")),
            depends_timeout_s=int(g("depends_timeout_s", 0)),
            untracked=as_bool(g("untracked", False)),
            node_label=str(g("node_label", "")),
        )

    def task_specs(self) -> dict[str, TaskTypeSpec]:
        return {t: self.task_spec(t) for t in self.job_types()}

    # --- serialisation (ship client -> AM -> executor) -----------------------

    def to_json(self) -> str:
        return json.dumps(self._values, sort_keys=True)

    @classmethod
    def from_json(cls, blob: str) -> "TonyConfig":
        return cls(json.loads(blob))

    def to_dict(self) -> dict[str, Any]:
        return dict(self._values)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        n = len(self._values)
        return f"TonyConfig({n} keys, framework={self.get_str(Keys.APPLICATION_FRAMEWORK)})"


__all__ = ["TonyConfig", "TaskTypeSpec"]

"""Minimal TOML-subset reader: the ``tomllib`` fallback for Python < 3.11.

The container image pins Python 3.10 (no stdlib ``tomllib``) and installing
``tomli`` is off the table, so job configs parse through this subset reader
instead. It covers exactly the surface tony-tpu configs use — ``[a.b]``
tables, bare keys, basic strings (with escapes), ints, floats, booleans,
single- or multi-line arrays, and ``#`` comments — and raises loudly on
anything fancier (multi-line strings, inline tables, dates, dotted keys),
so a config silently half-parsed can never reach a job.
"""

from __future__ import annotations

from typing import Any, BinaryIO

_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\", "f": "\f", "b": "\b"}


class TOMLDecodeError(ValueError):
    pass


def load(fp: BinaryIO) -> dict[str, Any]:
    """``tomllib.load`` signature parity: read a binary file object."""
    return loads(fp.read().decode("utf-8"))


def loads(text: str) -> dict[str, Any]:
    root: dict[str, Any] = {}
    table = root
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = _strip_comment(lines[i]).strip()
        i += 1
        if not line:
            continue
        if line.startswith("["):
            if line.startswith("[["):
                raise TOMLDecodeError(
                    f"arrays of tables are not supported by the minimal "
                    f"TOML reader (line {i}): {line!r}"
                )
            if not line.endswith("]"):
                raise TOMLDecodeError(f"malformed table header (line {i}): {line!r}")
            table = root
            for part in line[1:-1].strip().split("."):
                part = part.strip()
                if not part:
                    raise TOMLDecodeError(f"empty table name (line {i}): {line!r}")
                table = table.setdefault(part, {})
                if not isinstance(table, dict):
                    raise TOMLDecodeError(f"table {part!r} collides with a value")
            continue
        if "=" not in line:
            raise TOMLDecodeError(f"expected key = value (line {i}): {line!r}")
        key, _, raw = line.partition("=")
        key = key.strip().strip('"')
        raw = raw.strip()
        # a multi-line array continues until brackets balance outside strings
        while raw.startswith("[") and _bracket_depth(raw) > 0:
            if i >= len(lines):
                raise TOMLDecodeError(f"unterminated array for key {key!r}")
            raw += " " + _strip_comment(lines[i]).strip()
            i += 1
        table[key] = _value(raw, i)
    return root


def _strip_comment(line: str) -> str:
    """Drop a ``#`` comment, honouring both basic (``"``) and literal
    (``'``) strings so a '#' inside either survives."""
    out = []
    quote = ""  # the active string delimiter, "" when outside strings
    escaped = False
    for ch in line:
        if quote:
            out.append(ch)
            if escaped:
                escaped = False
            elif ch == "\\" and quote == '"':  # literal strings have no escapes
                escaped = True
            elif ch == quote:
                quote = ""
            continue
        if ch == "#":
            break
        out.append(ch)
        if ch in ('"', "'"):
            quote = ch
    return "".join(out)


def _bracket_depth(raw: str) -> int:
    depth = 0
    quote = ""
    escaped = False
    for ch in raw:
        if quote:
            if escaped:
                escaped = False
            elif ch == "\\" and quote == '"':
                escaped = True
            elif ch == quote:
                quote = ""
            continue
        if ch in ('"', "'"):
            quote = ch
        elif ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
    return depth


def _value(raw: str, lineno: int) -> Any:
    raw = raw.strip()
    if not raw:
        raise TOMLDecodeError(f"empty value (line {lineno})")
    if raw.startswith('"""') or raw.startswith("'''"):
        raise TOMLDecodeError(f"multi-line strings unsupported (line {lineno})")
    if raw.startswith('"'):
        s, rest = _string(raw, lineno)
        if rest.strip():
            raise TOMLDecodeError(f"trailing data after string (line {lineno}): {rest!r}")
        return s
    if raw.startswith("'"):
        if not raw.endswith("'") or len(raw) < 2:
            raise TOMLDecodeError(f"unterminated literal string (line {lineno})")
        return raw[1:-1]
    if raw.startswith("["):
        return _array(raw, lineno)
    if raw.startswith("{"):
        raise TOMLDecodeError(f"inline tables unsupported (line {lineno}): {raw!r}")
    if raw == "true":
        return True
    if raw == "false":
        return False
    try:
        return int(raw.replace("_", ""), 0) if raw.lower().startswith(("0x", "0o", "0b", "-0x")) else int(raw.replace("_", ""))
    except ValueError:
        pass
    try:
        return float(raw.replace("_", ""))
    except ValueError:
        pass
    raise TOMLDecodeError(f"unsupported value (line {lineno}): {raw!r}")


def _string(raw: str, lineno: int) -> tuple[str, str]:
    """Parse a leading basic string; return (value, remainder)."""
    assert raw[0] == '"'
    out = []
    j = 1
    while j < len(raw):
        ch = raw[j]
        if ch == "\\":
            j += 1
            if j >= len(raw):
                break
            esc = raw[j]
            if esc == "u" and j + 4 < len(raw):
                out.append(chr(int(raw[j + 1 : j + 5], 16)))
                j += 5
                continue
            if esc not in _ESCAPES:
                # 3.11 tomllib rejects unknown escapes; silently passing
                # them through would ship a different value on 3.10
                raise TOMLDecodeError(
                    f"invalid escape \\{esc} in string (line {lineno}): {raw!r}"
                )
            out.append(_ESCAPES[esc])
            j += 1
            continue
        if ch == '"':
            return "".join(out), raw[j + 1 :]
        out.append(ch)
        j += 1
    raise TOMLDecodeError(f"unterminated string (line {lineno}): {raw!r}")


def _array(raw: str, lineno: int) -> list:
    body = raw.strip()
    if not body.endswith("]"):
        raise TOMLDecodeError(f"unterminated array (line {lineno}): {raw!r}")
    body = body[1:-1]
    items: list = []
    current = ""
    depth = 0
    quote = ""
    escaped = False
    for ch in body:
        if quote:
            current += ch
            if escaped:
                escaped = False
            elif ch == "\\" and quote == '"':
                escaped = True
            elif ch == quote:
                quote = ""
            continue
        if ch in ('"', "'"):
            quote = ch
            current += ch
        elif ch == "[":
            depth += 1
            current += ch
        elif ch == "]":
            depth -= 1
            current += ch
        elif ch == "," and depth == 0:
            if current.strip():
                items.append(_value(current, lineno))
            current = ""
        else:
            current += ch
    if current.strip():
        items.append(_value(current, lineno))
    return items


__all__ = ["TOMLDecodeError", "load", "loads"]

"""Configuration key registry and defaults.

The analogue of TonY's ``TonyConfigurationKeys`` (all keys centralised, named
``tony.*``) plus ``tony-default.xml`` (baked-in defaults layer). See SURVEY.md
section 2 "Config system" and section 5 "Config/flag system". Keys here use
plain dotted names; per-jobtype keys are templated via :func:`job_key`.
"""

from __future__ import annotations


class Keys:
    """Centralised configuration key names (TonyConfigurationKeys analogue)."""

    # --- application-level ---
    APPLICATION_NAME = "application.name"
    APPLICATION_FRAMEWORK = "application.framework"  # jax | tensorflow | pytorch | horovod | generic
    APPLICATION_QUEUE = "application.queue"
    APPLICATION_SECURITY_ENABLED = "application.security.enabled"
    APPLICATION_TIMEOUT_S = "application.timeout_s"  # 0 = no timeout
    APPLICATION_PREPARE_STAGE_DIR = "application.stage_dir"
    APPLICATION_TAGS = "application.tags"

    # --- AM (ApplicationMaster) ---
    AM_MEMORY_MB = "am.memory_mb"  # reserved from backend inventory by the AM
    AM_CPUS = "am.cpus"  # ditto; also sizes the AM RPC thread pool
    AM_RETRY_COUNT = "am.retry_count"  # tony.am.retry-count analogue
    AM_RPC_PORT = "am.rpc_port"  # 0 = ephemeral
    AM_ALLOCATION_TIMEOUT_S = "am.allocation_timeout_s"  # gang partial-alloc guard

    # --- task supervision ---
    TASK_HEARTBEAT_INTERVAL_MS = "task.heartbeat_interval_ms"
    TASK_MAX_MISSED_HEARTBEATS = "task.max_missed_heartbeats"
    TASK_REGISTRATION_TIMEOUT_S = "task.registration_timeout_s"
    TASK_MAX_TOTAL_INSTANCES = "task.max_total_instances"
    TASK_EXECUTOR_PYTHON = "task.executor.python"  # python binary for executors

    # --- elastic / restart policy ---
    RESTART_MAX_WORKER_RESTARTS = "restart.max_worker_restarts"
    RESTART_POLICY = "restart.policy"  # never | failed_only | gang
    RESTART_RESUME_FROM_CHECKPOINT = "restart.resume_from_checkpoint"

    # --- elastic training (tony_tpu/elastic/; docs/ELASTIC.md) ---
    # survive preemption without a cold restart: on a lost training host
    # the AM declares a new cluster generation (members minus the dead
    # host) instead of gang-restarting; the trainer reshards its dp axis
    # and continues from the in-memory state of survivors. Auto-enabled
    # for application.framework = "elastic" jobs.
    ELASTIC_ENABLED = "elastic.enabled"
    # smallest surviving membership the job may shrink to; fewer survivors
    # (or a lost coordinator) falls back to the restart.policy cold path
    ELASTIC_MIN_MEMBERS = "elastic.min_members"
    # re-acquire capacity and restore dead members automatically (the
    # grow-back half; LeaseStore.grow_gang re-leases the REAL container ask)
    ELASTIC_GROW_BACK = "elastic.grow_back"
    # how often the AM retries capacity for a dead member (seconds)
    ELASTIC_GROW_RETRY_S = "elastic.grow_retry_s"
    # trainer-side knobs, exported AM -> executor -> user process:
    # how often the trainer polls the generation broadcast file
    ELASTIC_POLL_S = "elastic.poll_interval_s"
    # async device->host checkpoint-shadow stride (steps); the shadow is
    # the bounded-lag fallback recovery point (the fence capture is exact)
    ELASTIC_SHADOW_STEPS = "elastic.shadow_interval_steps"

    # --- distributed mode ---
    SCHEDULER_MODE = "scheduler.mode"  # GANG | FCFS (SURVEY.md: TaskScheduler modes)

    # --- checkpoint glue ---
    CHECKPOINT_DIR = "checkpoint.dir"
    CHECKPOINT_INTERVAL_STEPS = "checkpoint.interval_steps"
    CHECKPOINT_KEEP = "checkpoint.keep"

    # --- observability ---
    METRICS_INTERVAL_MS = "metrics.interval_ms"
    METRICS_ENABLED = "metrics.enabled"
    PROFILER_ENABLED = "profiler.enabled"
    PROFILER_PORT = "profiler.port"
    # persistent XLA compilation cache for fit() jobs: resubmits and elastic
    # restarts skip compile — the dominant submit->first-step cost (the
    # north-star latency metric; measured in docs/PERF.md)
    TRAIN_JAX_CACHE = "train.jax_cache"
    TRAIN_JAX_CACHE_DIR = "train.jax_cache_dir"  # default ~/.tony-tpu/jax_cache
    # cloud-tpu-diagnostics periodic stack traces (wedged-job debugging)
    DIAGNOSTICS_ENABLED = "diagnostics.enabled"
    # distributed trace spine (obs/trace.py; docs/OBS.md): always-on sampled
    # span recording across AM/executor/user processes, merged by
    # `tony trace <app_id>` into one Chrome-trace JSON
    TRACE_ENABLED = "trace.enabled"
    # record every Nth train/serve step as a span (1 = every step);
    # control-plane and lifecycle spans are never sampled away
    TRACE_SAMPLE_STEPS = "trace.sample_steps"
    # per-process in-memory span ring; overflow drops oldest and counts
    TRACE_RING_EVENTS = "trace.ring_events"
    # per-process journal rotation size: at the cap the journal rotates and
    # the newest window is kept (flight-recorder retention, <= 2x on disk)
    TRACE_MAX_JOURNAL_MB = "trace.max_journal_mb"
    # HBM observatory (obs/hbm.py; docs/OBS.md "Memory and compiles"):
    # phase-scoped device-memory watermarks, sampled per-step readings as
    # Perfetto counter tracks, and OOM forensics dumps
    OBS_HBM_ENABLED = "obs.hbm.enabled"
    # read device memory_stats every Nth train/serve step (the counter-
    # track sampling stride; off-stride calls are one increment + compare)
    OBS_HBM_SAMPLE_STEPS = "obs.hbm.sample_steps"
    # per-process in-memory sample-history ring (lands in OOM forensics)
    OBS_HBM_HISTORY = "obs.hbm.history_events"
    # numerics health sentinel (obs/health.py; docs/OBS.md "Numerics
    # health"): in-graph value monitors (nonfinite counts, update ratio,
    # per-layer grad RMS, batch fingerprint, serve logits/entropy) feeding
    # an async anomaly-rule engine; a trip flips the per-app verdict
    # (portal /healthz, `tony health <app_id>`) and dumps a forensics
    # bundle under <app_dir>/health/
    OBS_HEALTH_ENABLED = "obs.health.enabled"
    # evaluate health rules every Nth train/serve step (monitors stay
    # fused in-graph each step; off-stride seam calls are one increment)
    OBS_HEALTH_SAMPLE_STEPS = "obs.health.sample_steps"
    # rolling-statistics window (loss-spike z-score, stagnation) — also
    # the last-k step-stats ring a forensics bundle carries
    OBS_HEALTH_WINDOW = "obs.health.window_steps"
    # live time-series recorder (obs/series.py; docs/OBS.md "SLO + time
    # series"): stride-scraped per-process points (step time, TTFT/TPOT
    # quantiles, queue depth, HBM live/peak, health verdict, goodput)
    # journaled to ring-rotated series/<proc>.jsonl — the feed `tony top`
    # renders and the SLO engine alerts on
    OBS_SERIES_ENABLED = "obs.series.enabled"
    # scrape every Nth train/serve step (off-stride seam calls are one
    # increment + compare; the disarmed seam is one global load)
    OBS_SERIES_SAMPLE_STEPS = "obs.series.sample_steps"
    # per-process journal rotation size (newest window kept, <= 2x on disk)
    OBS_SERIES_JOURNAL_MB = "obs.series.max_journal_mb"
    # coordinated fleet profiling (obs/profile.py; docs/OBS.md "Step
    # anatomy"): `tony profile <app_id>` asks the AM to broadcast a bounded
    # capture window; every device-owning process records a jax.profiler
    # device trace into <app_dir>/profile/<proc>/ over the same steps,
    # and `tony profile report` merges them into the per-step budget table
    OBS_PROFILE_ENABLED = "obs.profile.enabled"
    # how often each process polls the broadcast request file (seconds);
    # the off-window hot-path seam cost is unaffected by this knob
    OBS_PROFILE_POLL_S = "obs.profile.poll_interval_s"
    # hard cap on the steps one window may capture (device traces are
    # big; a typo'd `--steps 100000` must not fill the disk)
    OBS_PROFILE_MAX_STEPS = "obs.profile.max_steps"

    # --- SLOs (obs/slo.py; docs/OBS.md "SLO + time series") ---
    # declared targets, evaluated as multi-window burn rates over the live
    # series; 0 = not contracted. A trip latches, emits an slo.<name>
    # trace instant + tony_slo_* metrics, and writes a verdict + forensics
    # bundle under <app_dir>/slo/ (the chaos invariant checker's
    # slo-surfaced rule refuses to report a tripped run clean)
    SLO_TTFT_P99_S = "slo.ttft_p99_s"
    SLO_STEP_TIME_P99_S = "slo.step_time_p99_s"
    SLO_GOODPUT_FLOOR = "slo.goodput_floor"
    SLO_HBM_HEADROOM_FRAC = "slo.hbm_headroom_frac"
    SLO_ERROR_RATE = "slo.error_rate"
    # error budget: the bad-point fraction a window may carry before the
    # burn rate (bad_frac / budget) exceeds 1 and the SLO trips
    SLO_BUDGET_FRAC = "slo.budget_frac"
    # SRE-style multi-window gates: the fast window catches the incident
    # now, the slow one (clipped to recorded data) proves it is sustained
    SLO_FAST_WINDOW_S = "slo.fast_window_s"
    SLO_SLOW_WINDOW_S = "slo.slow_window_s"
    # minimum fast-window samples before an SLO may trip (blip guard)
    SLO_MIN_POINTS = "slo.min_points"

    # --- gang serving (`tony serve`; serve/gang.py + serve/frontend.py) ---
    # decode-host containers the AM gang-schedules (the serve job's size)
    SERVE_GANG_HOSTS = "serve.gang.hosts"
    # task-type name of the decode hosts (job.<type>.* keys configure their
    # containers; the command defaults to `python -m tony_tpu.serve.gang`)
    SERVE_GANG_JOB_TYPE = "serve.gang.job_type"
    # model preset each host builds: a LlamaConfig classmethod name
    # (tiny | bench_410m | bench_1b4 | ...)
    SERVE_GANG_MODEL = "serve.gang.model"
    # parameter-init seed: every replica derives identical weights from it,
    # so any host can serve (or replay) any request
    SERVE_GANG_SEED = "serve.gang.seed"
    # per-host engine shape (ServeConfig.slots / max_len; 0 = model max)
    SERVE_GANG_SLOTS = "serve.gang.slots"
    SERVE_GANG_MAX_LEN = "serve.gang.max_len"
    # per-host bounded admission (ServeConfig.max_queue): submits beyond
    # this queue depth are rejected so the frontend reroutes instead of
    # burying work in a saturated host
    SERVE_GANG_MAX_QUEUE = "serve.gang.max_queue"
    # shard each host's params over its local devices via the default mesh
    # (parallel/mesh.py) instead of single-device replication
    SERVE_GANG_SHARD = "serve.gang.shard"
    # frontend admission bound: total requests in flight across the gang
    SERVE_GANG_MAX_INFLIGHT = "serve.gang.frontend_max_inflight"
    # replay budget per request: a request re-queued off a dead host more
    # than this many times finishes with reason=error (never hangs)
    SERVE_GANG_MAX_REPLAYS = "serve.gang.max_replays"
    # TTFT contract recorded into the serve ledger; the chaos invariant
    # checker flags completed requests over budget (0 = uncontracted)
    SERVE_GANG_TTFT_BUDGET_S = "serve.gang.ttft_budget_s"
    # rolling-restart drain: how long a host finishes its live slots
    # before Drain gives up and reports the remainder
    SERVE_GANG_DRAIN_TIMEOUT_S = "serve.gang.drain_timeout_s"
    # lease-store autoscale hooks: grow the gang when the aggregate queue
    # depth stays above `high` for `window_s`, shrink when it stays below
    # `low` (high 0 disables; see LeaseStore.grow_gang/shrink_gang)
    SERVE_GANG_AUTOSCALE_HIGH = "serve.gang.autoscale_queue_high"
    SERVE_GANG_AUTOSCALE_LOW = "serve.gang.autoscale_queue_low"
    SERVE_GANG_AUTOSCALE_WINDOW_S = "serve.gang.autoscale_window_s"

    # --- prefix store (cross-request KV reuse; serve/prefix.py) ---
    # radix prefix store over the paged KV cache: admission matches each
    # prompt's longest cached prefix and prefills only the unshared tail;
    # matched blocks are shared copy-on-write
    SERVE_PREFIX_ENABLED = "serve.prefix.enabled"
    # HBM the store may pin for prefixes no live slot references; LRU
    # leaves evict beyond it (0 = bound only by allocation pressure)
    SERVE_PREFIX_BUDGET_MB = "serve.prefix.budget_mb"
    # frontend prefix-affinity routing: requests sharing a prefix
    # fingerprint route to the host whose store already holds it (falls
    # back to least-loaded when that host is dead/draining/overloaded)
    SERVE_PREFIX_AFFINITY = "serve.prefix.affinity"
    # leading tokens hashed into the routing fingerprint; prompts shorter
    # than this route purely by load (too little prefix to pin a host for)
    SERVE_PREFIX_FINGERPRINT_TOKENS = "serve.prefix.fingerprint_tokens"

    # --- speculative decoding (model-free drafts; serve/spec.py) ---
    # trie/n-gram drafted multi-token decode steps: each slot proposes up
    # to max_draft tokens per step, the engine verifies all of them in
    # ONE widened forward and accepts via the exact rejection rule —
    # output stays draw-for-draw identical to autoregressive decoding
    SERVE_SPEC_ENABLED = "serve.spec.enabled"
    # draft tokens proposed per slot per step (the verify step scores
    # max_draft + 1 positions; one decode signature per engine)
    SERVE_SPEC_MAX_DRAFT = "serve.spec.max_draft"
    # draft source: auto (radix store first, n-gram fallback) | prefix
    # (store only) | ngram (the slot's own prompt-lookup only)
    SERVE_SPEC_DRAFT_SOURCE = "serve.spec.draft_source"

    # --- quantized serving (block-scaled KV + weight-only int8;
    #     serve/cache.py, ops/quant_mm.py, docs/SERVE.md) ---
    # quantize the paged KV cache at physical-block granularity: int8/fp8
    # pools with per-block-per-head float32 scales; decode attention
    # dequantizes inline, roughly doubling the slot budget at a bounded
    # logits drift (bench decode.quant states the tolerance)
    SERVE_QUANT_ENABLED = "serve.quant.enabled"
    # KV storage dtype: int8 | fp8_e4m3 (fp8 needs a jax with
    # jnp.float8_e4m3fn; the engine refuses rather than silently widening)
    SERVE_QUANT_KV_DTYPE = "serve.quant.kv_dtype"
    # also run decode/verify matmuls on int8 weights with per-output-
    # channel scales (prefill keeps the bf16 master weights)
    SERVE_QUANT_WEIGHTS = "serve.quant.weights"

    # --- chunked prefill + disaggregated pools (docs/SERVE.md
    # "Disaggregated serving") ---
    # prompts whose unshared tail exceeds this prefill in block-aligned
    # chunks, one chunk per decode step, so a long prompt cannot stall
    # co-resident streams (TPOT stays bounded, TTFT degrades gracefully);
    # must be a multiple of serve.gang.kv block size; 0 = off
    SERVE_CHUNK_TOKENS = "serve.chunk_tokens"
    # containers in the prefill pool (0 = colocated serving, no pool split);
    # when > 0 the serve gang is heterogeneous: the AM schedules this many
    # prefill-type containers next to serve.gang.hosts decode ones, and the
    # frontend routes long prompts through prefill -> ShipBlocks -> decode
    SERVE_POOL_PREFILL_HOSTS = "serve.pool.prefill_hosts"
    # task-type name of the prefill pool (job.<type>.* keys configure its
    # containers; same worker binary as the decode pool)
    SERVE_POOL_PREFILL_JOB_TYPE = "serve.pool.prefill_job_type"
    # minimum prompt tokens before the frontend routes through the prefill
    # pool — short prompts prefill faster in place than a handoff round-trip
    SERVE_POOL_HANDOFF_MIN_TOKENS = "serve.pool.handoff_min_tokens"

    # --- cluster backend ---
    # Deliberate non-goals vs the reference key surface: docker keys (no
    # container runtime in this environment — processes are the container
    # abstraction) and a max-containers cap (the inventory's memory/cpu/chip
    # capacity already bounds concurrent containers).
    CLUSTER_BACKEND = "cluster.backend"  # local | remote | tpu_vm
    CLUSTER_TPU_CHIPS_PER_HOST = "cluster.tpu_chips_per_host"
    CLUSTER_HOSTS = "cluster.hosts"  # remote backend: comma list of hosts
    CLUSTER_REMOTE_TRANSPORT = "cluster.remote_transport"  # ssh | local
    # copy the app dir to each host over the transport (pod slices without a
    # shared FS) instead of assuming the same path everywhere
    CLUSTER_LOCALIZE = "cluster.localize"
    # destination root for localized app dirs (default ~/.tony-tpu/localized,
    # expanded on the AM host — assumes the same home path on every host)
    CLUSTER_LOCALIZE_ROOT = "cluster.localize_root"
    # shared ResourceManager (YARN-RM analogue): a directory reachable by
    # every submitter (same machine or shared FS); when set, all jobs lease
    # capacity from this file-locked store, so concurrent submits queue
    # FIFO instead of double-booking hosts/chips. Empty = per-job inventory.
    CLUSTER_RM_ROOT = "cluster.rm_root"
    # lease TTL for the shared RM store: a job's leases expire this many
    # seconds after their last renewal (the AM renews on its heartbeat
    # cadence), so a submit host that dies on ANOTHER machine — where pid
    # liveness cannot be checked — frees its chips automatically instead
    # of stranding them until an operator runs `tony rm-status --release`.
    # 0 disables expiry (manual/pid reaping only).
    CLUSTER_LEASE_TTL_S = "cluster.lease_ttl_s"

    # --- portal/history ---
    HISTORY_INTERMEDIATE_DIR = "history.intermediate_dir"
    HISTORY_FINISHED_DIR = "history.finished_dir"
    PORTAL_PORT = "portal.port"

    # --- chaos (fault injection; docs/CHAOS.md) ---
    # master gate: when false (the default) every chaos hook is a no-op and
    # no fault schedule is ever parsed or armed
    CHAOS_ENABLED = "chaos.enabled"
    # declarative fault schedule: a JSON list of fault objects (as a
    # string — portable across TOML readers), e.g.
    # [{"type": "kill_container", "task": "worker:0", "at_count": 3}]
    CHAOS_FAULTS = "chaos.faults"
    # seed for the injector's RNG (delay jitter); same seed = same schedule
    CHAOS_SEED = "chaos.seed"


# Per-jobtype key suffixes (the ``tony.<jobtype>.<suffix>`` templating scheme).
JOB_SUFFIXES = (
    "instances",
    "memory_mb",
    "cpus",
    "tpu_chips",
    "command",
    "env",
    "depends_on",  # inter-task-type dependency (workers wait on ps)
    "depends_timeout_s",
    "untracked",  # excluded from job final-status accounting (e.g. tensorboard)
    "node_label",
)


def job_key(job_type: str, suffix: str) -> str:
    """``job_key("worker", "instances") -> "job.worker.instances"``.

    Analogue of TonY's per-jobtype conf templating
    (``tony.<jobtype>.instances`` / ``.memory`` / ``.vcores`` / ``.gpus``).
    """
    return f"job.{job_type}.{suffix}"


# The tony-default.xml analogue: the base layer of every TonyConfig.
# tests/test_config.py pins these against docs (reference had a
# defaults-vs-docs consistency test, SURVEY.md section 5).
DEFAULTS: dict[str, object] = {
    Keys.APPLICATION_NAME: "tony-tpu-job",
    Keys.APPLICATION_FRAMEWORK: "jax",
    Keys.APPLICATION_QUEUE: "default",
    Keys.APPLICATION_SECURITY_ENABLED: False,
    Keys.APPLICATION_TIMEOUT_S: 0,
    Keys.APPLICATION_PREPARE_STAGE_DIR: "",
    Keys.APPLICATION_TAGS: "",
    Keys.AM_MEMORY_MB: 2048,
    Keys.AM_CPUS: 1,
    Keys.AM_RETRY_COUNT: 0,
    Keys.AM_RPC_PORT: 0,
    Keys.AM_ALLOCATION_TIMEOUT_S: 300,
    Keys.TASK_HEARTBEAT_INTERVAL_MS: 1000,
    Keys.TASK_MAX_MISSED_HEARTBEATS: 25,
    Keys.TASK_REGISTRATION_TIMEOUT_S: 300,
    Keys.TASK_MAX_TOTAL_INSTANCES: -1,
    Keys.TASK_EXECUTOR_PYTHON: "",
    Keys.RESTART_MAX_WORKER_RESTARTS: 0,
    Keys.RESTART_POLICY: "never",
    Keys.RESTART_RESUME_FROM_CHECKPOINT: True,
    Keys.ELASTIC_ENABLED: False,
    Keys.ELASTIC_MIN_MEMBERS: 1,
    Keys.ELASTIC_GROW_BACK: True,
    Keys.ELASTIC_GROW_RETRY_S: 2.0,
    Keys.ELASTIC_POLL_S: 0.5,
    Keys.ELASTIC_SHADOW_STEPS: 16,
    Keys.SCHEDULER_MODE: "GANG",
    Keys.CHECKPOINT_DIR: "",
    Keys.CHECKPOINT_INTERVAL_STEPS: 0,
    Keys.CHECKPOINT_KEEP: 3,
    Keys.METRICS_INTERVAL_MS: 2000,
    Keys.METRICS_ENABLED: True,
    Keys.PROFILER_ENABLED: False,
    Keys.PROFILER_PORT: 9999,
    Keys.TRAIN_JAX_CACHE: True,
    Keys.TRAIN_JAX_CACHE_DIR: "",

    Keys.DIAGNOSTICS_ENABLED: False,
    Keys.TRACE_ENABLED: True,
    Keys.TRACE_SAMPLE_STEPS: 16,
    Keys.TRACE_RING_EVENTS: 4096,
    Keys.TRACE_MAX_JOURNAL_MB: 64,
    Keys.OBS_HBM_ENABLED: True,
    Keys.OBS_HBM_SAMPLE_STEPS: 16,
    Keys.OBS_HBM_HISTORY: 512,
    Keys.OBS_HEALTH_ENABLED: True,
    Keys.OBS_HEALTH_SAMPLE_STEPS: 16,
    Keys.OBS_HEALTH_WINDOW: 64,
    Keys.OBS_SERIES_ENABLED: True,
    Keys.OBS_SERIES_SAMPLE_STEPS: 16,
    Keys.OBS_SERIES_JOURNAL_MB: 16,
    Keys.OBS_PROFILE_ENABLED: True,
    Keys.OBS_PROFILE_POLL_S: 0.5,
    Keys.OBS_PROFILE_MAX_STEPS: 64,
    Keys.SLO_TTFT_P99_S: 0,
    Keys.SLO_STEP_TIME_P99_S: 0,
    Keys.SLO_GOODPUT_FLOOR: 0,
    Keys.SLO_HBM_HEADROOM_FRAC: 0,
    Keys.SLO_ERROR_RATE: 0,
    Keys.SLO_BUDGET_FRAC: 0.1,
    Keys.SLO_FAST_WINDOW_S: 300,
    Keys.SLO_SLOW_WINDOW_S: 3600,
    Keys.SLO_MIN_POINTS: 3,
    Keys.SERVE_GANG_HOSTS: 2,
    Keys.SERVE_GANG_JOB_TYPE: "decode",
    Keys.SERVE_GANG_MODEL: "tiny",
    Keys.SERVE_GANG_SEED: 0,
    Keys.SERVE_GANG_SLOTS: 4,
    Keys.SERVE_GANG_MAX_LEN: 0,
    Keys.SERVE_GANG_MAX_QUEUE: 16,
    Keys.SERVE_GANG_SHARD: False,
    Keys.SERVE_GANG_MAX_INFLIGHT: 64,
    Keys.SERVE_GANG_MAX_REPLAYS: 3,
    Keys.SERVE_GANG_TTFT_BUDGET_S: 0,
    Keys.SERVE_GANG_DRAIN_TIMEOUT_S: 30,
    Keys.SERVE_GANG_AUTOSCALE_HIGH: 0,
    Keys.SERVE_GANG_AUTOSCALE_LOW: 0,
    Keys.SERVE_GANG_AUTOSCALE_WINDOW_S: 10,
    Keys.SERVE_PREFIX_ENABLED: True,
    Keys.SERVE_PREFIX_BUDGET_MB: 64,
    Keys.SERVE_PREFIX_AFFINITY: True,
    Keys.SERVE_PREFIX_FINGERPRINT_TOKENS: 64,
    Keys.SERVE_SPEC_ENABLED: False,
    Keys.SERVE_SPEC_MAX_DRAFT: 4,
    Keys.SERVE_SPEC_DRAFT_SOURCE: "auto",
    Keys.SERVE_QUANT_ENABLED: False,
    Keys.SERVE_QUANT_KV_DTYPE: "int8",
    Keys.SERVE_QUANT_WEIGHTS: False,
    Keys.SERVE_CHUNK_TOKENS: 0,
    Keys.SERVE_POOL_PREFILL_HOSTS: 0,
    Keys.SERVE_POOL_PREFILL_JOB_TYPE: "prefill",
    Keys.SERVE_POOL_HANDOFF_MIN_TOKENS: 64,
    Keys.CLUSTER_BACKEND: "local",
    Keys.CLUSTER_TPU_CHIPS_PER_HOST: 4,
    Keys.CLUSTER_HOSTS: "",
    Keys.CLUSTER_REMOTE_TRANSPORT: "ssh",
    Keys.CLUSTER_LOCALIZE: False,
    Keys.CLUSTER_LOCALIZE_ROOT: "",
    Keys.CLUSTER_RM_ROOT: "",
    Keys.CLUSTER_LEASE_TTL_S: 600,
    Keys.HISTORY_INTERMEDIATE_DIR: "",
    Keys.HISTORY_FINISHED_DIR: "",
    Keys.PORTAL_PORT: 8080,
    Keys.CHAOS_ENABLED: False,
    Keys.CHAOS_FAULTS: "",
    Keys.CHAOS_SEED: 0,
}

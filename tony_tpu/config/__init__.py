"""Layered configuration system.

Rebuild of TonY's config layer (reference: tony-core/.../TonyConfigurationKeys.java
and tony-default.xml, SURVEY.md section 2 "Config system"): defaults registry ->
user TOML file -> CLI ``-c key=value`` overrides, with per-jobtype key templating
(``job.<jobtype>.instances`` etc., the ``tony.<jobtype>.instances`` analogue).
"""

from tony_tpu.config.keys import Keys, DEFAULTS, job_key
from tony_tpu.config.config import TonyConfig, TaskTypeSpec

__all__ = ["Keys", "DEFAULTS", "job_key", "TonyConfig", "TaskTypeSpec"]

"""Grouped (ragged) matmul: one GEMM over per-expert row groups.

The MoE dispatch kernel (MegaBlocks, arXiv:2211.15841): tokens are sorted by
assigned expert into contiguous row groups, each group padded up to a
multiple of a row tile, and the expert FFN runs as ONE matmul stream over
row tiles where tile ``i`` contracts against expert ``tile_expert[i]``'s
weight matrix. No fixed per-expert capacity — groups are as long as the
router made them — so nothing is dropped and nothing idles, at the cost of
at most ``block - 1`` padding rows per expert.

Two interchangeable implementations behind :func:`grouped_matmul` (the
``ops/fused_ce.py`` pattern):

- ``'scan'`` — a pure-XLA ``lax.scan`` over row tiles: ``dynamic_slice`` the
  tile out of the sorted buffer, ``jnp.take`` its expert's weights, one dot.
  Runs anywhere (CPU, under ``shard_map``, on an ep mesh) and autodiff
  handles the backward; the default.
- ``'pallas'`` — a TPU kernel over a (row-tiles × out-columns) grid. The
  tile→expert map rides as a scalar-prefetch argument
  (``PrefetchScalarGridSpec``) so the weight BlockSpec can DMA the right
  expert's block before the tile runs; fp32 accumulation on the MXU; the
  backward is a ``custom_vjp`` with dedicated dx and dW kernels (dx
  contracts W's last dim in place — no transposed weight copy; dW carries a
  VMEM accumulator across the consecutive tiles of each expert).
  Interpreter mode on CPU.

The caller owns the layout: build it with :func:`grouped_layout` (per-group
start/size → block-aligned starts + the tile→expert map), scatter rows to
``aligned_start[g] + rank_within_group``, and call ``grouped_matmul`` once
per weight. ``parallel/moe.py`` is the production caller.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tony_tpu.ops.compat import (
    pallas_compiler_params as _CompilerParams,
    struct_with_vma as _struct,
    use_interpret as _use_interpret,
)


def grouped_layout(group_sizes: jax.Array, block: int, n_tiles: int):
    """Block-aligned ragged layout for ``G`` row groups.

    ``group_sizes``: [G] int32. Returns ``(aligned_starts [G], tile_group
    [n_tiles])`` where group ``g``'s rows occupy ``aligned_starts[g] ..
    aligned_starts[g] + group_sizes[g])`` in a buffer of ``n_tiles * block``
    rows, every start is a multiple of ``block``, and ``tile_group[i]`` is
    the group row-tile ``i`` belongs to. Every group gets at least one tile
    (so a zero-load expert still produces a defined — zero — dW block), and
    trailing tiles beyond the last group clamp to ``G - 1`` (their rows are
    zero padding). ``n_tiles`` must be a static bound of at least
    ``cdiv(sum(sizes), block) + G``.
    """
    g = group_sizes.shape[0]
    tiles_per = jnp.maximum((group_sizes + block - 1) // block, 1)
    tile_cum = jnp.cumsum(tiles_per)
    aligned_starts = (tile_cum - tiles_per) * block
    tile_group = jnp.clip(
        jnp.searchsorted(tile_cum, jnp.arange(n_tiles), side="right"), 0, g - 1
    ).astype(jnp.int32)
    return aligned_starts, tile_group


def _pick_block(n: int, pref: int) -> int:
    """Largest of (pref, 512, 256, 128) dividing n, else n itself (ragged
    column tiles would read past the weight edge; full-width is always safe
    and only bites on shapes too small to tile anyway)."""
    for d in (pref, 512, 256, 128):
        if 0 < d <= n and n % d == 0:
            return d
    return n


# --- scan (XLA) implementation ------------------------------------------------


def _gmm_scan(x: jax.Array, w: jax.Array, tile_group: jax.Array) -> jax.Array:
    """lax.scan over row tiles: slice tile i, take its group's weights, dot.
    Autodiff transposes the slice/take into the scatter-adds of the
    backward — no custom VJP needed."""
    n_tiles = tile_group.shape[0]
    br = x.shape[0] // n_tiles

    def body(_, i):
        xt = lax.dynamic_slice_in_dim(x, i * br, br)
        wg = jnp.take(w, tile_group[i], axis=0)
        yt = jnp.dot(xt, wg, preferred_element_type=jnp.float32)
        return None, yt.astype(x.dtype)

    _, ys = lax.scan(body, None, jnp.arange(n_tiles, dtype=jnp.int32))
    return ys.reshape(x.shape[0], w.shape[-1])


# --- pallas (TPU) implementation ----------------------------------------------


def _gmm_kernel(tg_ref, x_ref, w_ref, o_ref):
    o_ref[...] = lax.dot_general(
        x_ref[...], w_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _gmm_pallas_call(x, w, tile_group, block_cols):
    n_tiles = tile_group.shape[0]
    br = x.shape[0] // n_tiles
    d, n = w.shape[1], w.shape[2]
    bc = _pick_block(n, block_cols)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles, n // bc),
        in_specs=[
            pl.BlockSpec((br, d), lambda i, j, tg: (i, 0)),
            # the prefetched tile->group map picks which expert's weight
            # block the DMA brings in for tile i
            pl.BlockSpec((1, d, bc), lambda i, j, tg: (tg[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((br, bc), lambda i, j, tg: (i, j)),
    )
    return pl.pallas_call(
        _gmm_kernel,
        grid_spec=grid_spec,
        out_shape=_struct((x.shape[0], n), x.dtype, x, w),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(tile_group, x, w)


def _gmm_dx_kernel(tg_ref, dy_ref, w_ref, dx_ref, acc):
    """dx_tile = dy_tile @ w[g]^T, contracting w's LAST dim in place — no
    HBM-materialised [G, F, D] transpose of the expert weights (whose
    streaming is the measured MoE bottleneck). The out-column (model-dim)
    blocks accumulate over the F grid dim."""
    fi = pl.program_id(2)
    nf = pl.num_programs(2)

    @pl.when(fi == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    acc[:] = acc[:] + lax.dot_general(
        dy_ref[...], w_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(fi == nf - 1)
    def _finalize():
        dx_ref[...] = acc[:].astype(dx_ref.dtype)


def _gmm_dx_call(dy, w, tile_group, block_cols):
    n_tiles = tile_group.shape[0]
    br = dy.shape[0] // n_tiles
    d, f = w.shape[1], w.shape[2]
    bd, bf = _pick_block(d, block_cols), _pick_block(f, block_cols)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles, d // bd, f // bf),
        in_specs=[
            pl.BlockSpec((br, bf), lambda i, di, fi, tg: (i, fi)),
            pl.BlockSpec((1, bd, bf), lambda i, di, fi, tg: (tg[i], di, fi)),
        ],
        out_specs=pl.BlockSpec((br, bd), lambda i, di, fi, tg: (i, di)),
        scratch_shapes=[pltpu.VMEM((br, bd), jnp.float32)],
    )
    return pl.pallas_call(
        _gmm_dx_kernel,
        grid_spec=grid_spec,
        out_shape=_struct((dy.shape[0], d), dy.dtype, dy, w),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(tile_group, dy, w)


def _gmm_dw_kernel(tg_ref, x_ref, dy_ref, dw_ref, acc):
    """dW[g] = sum over g's row tiles of x_tile^T @ dy_tile.

    The tile dimension is innermost and tiles of one group are consecutive
    (the buffer is sorted), so the dW output block is revisited on
    consecutive grid steps: init the VMEM accumulator on the group's first
    tile, write the block back on its last."""
    i = pl.program_id(2)
    n = pl.num_programs(2)
    g = tg_ref[i]

    @pl.when((i == 0) | (tg_ref[jnp.maximum(i - 1, 0)] != g))
    def _init():
        acc[:] = jnp.zeros_like(acc)

    acc[:] = acc[:] + lax.dot_general(
        x_ref[...], dy_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when((i == n - 1) | (tg_ref[jnp.minimum(i + 1, n - 1)] != g))
    def _finalize():
        dw_ref[0] = acc[:].astype(dw_ref.dtype)


def _gmm_dw_call(x, dy, tile_group, n_groups, block_cols):
    n_tiles = tile_group.shape[0]
    br = x.shape[0] // n_tiles
    d, n = x.shape[1], dy.shape[1]
    bd, bn = _pick_block(d, block_cols), _pick_block(n, block_cols)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d // bd, n // bn, n_tiles),
        in_specs=[
            pl.BlockSpec((br, bd), lambda di, ni, i, tg: (i, di)),
            pl.BlockSpec((br, bn), lambda di, ni, i, tg: (i, ni)),
        ],
        out_specs=pl.BlockSpec((1, bd, bn), lambda di, ni, i, tg: (tg[i], di, ni)),
        scratch_shapes=[pltpu.VMEM((bd, bn), jnp.float32)],
    )
    return pl.pallas_call(
        _gmm_dw_kernel,
        grid_spec=grid_spec,
        out_shape=_struct((n_groups, d, n), jnp.float32, x, dy),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(tile_group, x, dy)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm_pallas(x, w, tile_group, block_cols):
    return _gmm_pallas_call(x, w, tile_group, block_cols)


def _gmm_pallas_fwd(x, w, tile_group, block_cols):
    return _gmm_pallas_call(x, w, tile_group, block_cols), (x, w, tile_group)


def _gmm_pallas_bwd(block_cols, res, dy):
    x, w, tile_group = res
    dx = _gmm_dx_call(dy, w, tile_group, block_cols)
    dw = _gmm_dw_call(x, dy, tile_group, w.shape[0], block_cols).astype(w.dtype)
    return dx, dw, np.zeros(tile_group.shape, jax.dtypes.float0)


_gmm_pallas.defvjp(_gmm_pallas_fwd, _gmm_pallas_bwd)


# --- public entry -------------------------------------------------------------


def grouped_matmul(
    x: jax.Array,
    w: jax.Array,
    tile_group: jax.Array,
    *,
    impl: str = "scan",
    block_cols: int = 512,
) -> jax.Array:
    """``[N, D] x [G, D, F] -> [N, F]`` where row tile ``i`` (of
    ``N / len(tile_group)`` rows) contracts against ``w[tile_group[i]]``.

    ``x`` must be laid out by :func:`grouped_layout` (group-contiguous,
    block-aligned, zero padding rows). Differentiable under both impls.
    """
    if x.ndim != 2 or w.ndim != 3 or w.shape[1] != x.shape[1]:
        raise ValueError(f"grouped_matmul shapes {x.shape} x {w.shape}")
    n_tiles = tile_group.shape[0]
    if n_tiles == 0 or x.shape[0] % n_tiles:
        raise ValueError(
            f"rows {x.shape[0]} not a whole number of {n_tiles} tiles"
        )
    if impl == "pallas":
        return _gmm_pallas(x, w, tile_group, block_cols)
    if impl != "scan":
        raise ValueError(f"unknown gmm impl {impl!r} (expected scan | pallas)")
    return _gmm_scan(x, w, tile_group)


__all__ = ["grouped_layout", "grouped_matmul"]

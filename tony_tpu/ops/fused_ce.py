"""Fused chunked cross-entropy head: never materialise [B, S, V] logits.

The loss head is the single biggest HBM transient of the dense train step:
``h @ lm_head`` builds fp32 logits of [B, S, V] (~1 GB at bench shapes) and
autodiff materialises a same-sized ``dlogits`` in the backward. This module
applies the recompute-instead-of-materialise trick the attention kernel
already uses (FlashAttention-2, arXiv:2307.08691) to the vocab projection,
the fused/parallel CE head that Megatron-LM (arXiv:2104.04473) makes
standard at scale:

- **forward** walks the vocab in chunks of ``Vc`` columns keeping an online
  logsumexp ``(m, s)`` plus the target logit per row — at most one
  ``[N, Vc]`` logits block is ever live;
- **backward** recomputes each chunk's logits from the saved
  ``(h, lse)`` residuals (``softmax = exp(logits - lse)``), forms the chunk's
  ``dlogits = (softmax - onehot) * g`` in registers/VMEM, and accumulates
  ``dh`` and the chunk's ``d(lm_head)`` columns directly — no full
  ``dlogits`` ever exists.

Two interchangeable implementations behind one ``jax.custom_vjp`` (the
``ops/attention.py`` pattern), selected by ``LlamaConfig.ce_impl``:

- ``'pallas'`` — TPU kernels with VMEM accumulators over a (rows, vocab)
  grid; interpreter mode on CPU for tests.
- ``'scan'`` — a pure-XLA ``lax.scan`` over vocab chunks; runs anywhere
  (CPU, under ``shard_map``, inside the 1F1B pipeline's manual region) and
  is the default train path.

Both return **per-token** losses ``[B, S]`` fp32 (callers take the mean),
so a ``dp``/``fsdp``/``sp``-sharded batch/seq axis stays sharded end to end
and the MoE aux term composes unchanged at the call site.

Sharding note: both paths read the full ``lm_head`` per data shard (the
scan's dynamic vocab slice and the pallas wrapper's replicated W both defeat
the column-parallel vocab layout). A Megatron-style vocab-parallel CE (local
max/sum + two small psums) is the follow-up for large-tp meshes; at the
single-chip/fsdp bench shapes W traffic is one streaming read per pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tony_tpu.ops.compat import (
    pallas_compiler_params as _CompilerParams,
    shard_map_compat as _shard_map,
    struct_with_vma as _struct,
    use_interpret as _use_interpret,
)

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)

# pallas tile defaults (clipped to the actual shapes); 512x512 keeps the
# fp32 accumulators + one W block + one h block well under VMEM at D=2048
_BLOCK_N = 512
_BLOCK_V = 512


# --- scan (XLA) implementation ------------------------------------------------


def _scan_chunk_fwd(carry, logits, start, tgt):
    """Online-logsumexp update for one [N, Vc] fp32 logits block."""
    m, s, tl = carry
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=-1)
    rel = tgt - start
    in_chunk = (rel >= 0) & (rel < logits.shape[1])
    idx = jnp.clip(rel, 0, logits.shape[1] - 1)
    got = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
    tl = jnp.where(in_chunk, got, tl)
    return m_new, s, tl


def _scan_fwd(h, w, tgt, vc):
    """h [N, D], w [D, V], tgt [N] -> (lse [N] f32, target_logit [N] f32)."""
    N, D = h.shape
    V = w.shape[1]
    vc = min(vc, V)
    nfull = V // vc

    # derive the carries from h (not fresh zeros) so they inherit h's
    # varying-mesh-axes type: inside a shard_map manual region (the 1F1B
    # head) a fresh-constant carry would fail the scan's vma typing once
    # the body makes it varying
    zrow = jnp.sum(h * 0, axis=1).astype(jnp.float32)  # [N] f32 zeros
    init = (zrow + _NEG, zrow, zrow)

    def body(carry, j):
        start = j * vc
        wc = lax.dynamic_slice(w, (0, start), (D, vc))
        logits = jnp.dot(h, wc, preferred_element_type=jnp.float32)
        return _scan_chunk_fwd(carry, logits, start, tgt), None

    carry, _ = lax.scan(body, init, jnp.arange(nfull))
    if V % vc:
        tail = jnp.dot(h, w[:, nfull * vc:], preferred_element_type=jnp.float32)
        carry = _scan_chunk_fwd(carry, tail, nfull * vc, tgt)
    m, s, tl = carry
    return m + jnp.log(s), tl


def _scan_chunk_bwd(h, wc, tgt, lse, g, start):
    """One chunk of the backward: recompute logits, return (dh_part f32,
    dwc in w.dtype). dlogits = (softmax - onehot(target)) * g, formed only
    at [N, Vc]."""
    vcc = wc.shape[1]
    logits = jnp.dot(h, wc, preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse[:, None])
    rel = tgt - start
    onehot = (jnp.arange(vcc)[None, :] == rel[:, None]).astype(jnp.float32)
    dlogits = (p - onehot) * g[:, None]
    dh_part = lax.dot_general(
        dlogits, wc, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    dwc = lax.dot_general(
        h, dlogits, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return dh_part, dwc.astype(wc.dtype)


def _scan_bwd(h, w, tgt, lse, g, vc):
    """Backward accumulation over vocab chunks; returns (dh, dw) in the
    primal dtypes. Each chunk's dW columns are written exactly once (no
    cross-chunk accumulation), dh accumulates fp32."""
    N, D = h.shape
    V = w.shape[1]
    vc = min(vc, V)
    nfull = V // vc

    def body(carry, j):
        dh_acc, dw = carry
        start = j * vc
        wc = lax.dynamic_slice(w, (0, start), (D, vc))
        dh_part, dwc = _scan_chunk_bwd(h, wc, tgt, lse, g, start)
        dw = lax.dynamic_update_slice(dw, dwc, (0, start))
        return (dh_acc + dh_part, dw), None

    # (h*0) / zeros_like(w) keep the operands' varying-axes type (see
    # _scan_fwd); g joins the dh carry so a varying cotangent also taints it
    init = (
        (h * 0).astype(jnp.float32) + (g * 0)[:, None],
        jnp.zeros_like(w),
    )
    (dh_acc, dw), _ = lax.scan(body, init, jnp.arange(nfull))
    if V % vc:
        start = nfull * vc
        dh_part, dwc = _scan_chunk_bwd(h, w[:, start:], tgt, lse, g, start)
        dh_acc = dh_acc + dh_part
        dw = lax.dynamic_update_slice(dw, dwc, (0, start))
    return dh_acc.astype(h.dtype), dw


# --- pallas (TPU) implementation ----------------------------------------------


def _ce_fwd_kernel(h_ref, w_ref, tgt_ref, lse_ref, tl_ref, m_sc, s_sc, t_sc,
                   *, blk_n, blk_v, vocab):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        s_sc[:] = jnp.zeros_like(s_sc)
        t_sc[:] = jnp.zeros_like(t_sc)

    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # the grid over-covers a vocab not divisible by blk_v: mask the padded
    # columns before they touch the online max/sum
    col = j * blk_v + jax.lax.broadcasted_iota(jnp.int32, (blk_n, blk_v), 1)
    logits = jnp.where(col < vocab, logits, _NEG)

    m_prev = m_sc[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1))
    p = jnp.exp(logits - m_new[:, None])
    s_sc[:, 0] = s_sc[:, 0] * jnp.exp(m_prev - m_new) + jnp.sum(p, axis=1)
    m_sc[:, 0] = m_new

    tgt = tgt_ref[0]
    rel = tgt - j * blk_v
    hit = (rel >= 0) & (rel < blk_v)
    eq = col == tgt[:, None]  # col is global, so padded cols never match
    got = jnp.sum(jnp.where(eq, logits, 0.0), axis=1)
    t_sc[:, 0] = jnp.where(hit, got, t_sc[:, 0])

    @pl.when(j == nv - 1)
    def _finalize():
        l = jnp.maximum(s_sc[:, 0], 1e-30)
        lse_ref[0] = m_sc[:, 0] + jnp.log(l)
        tl_ref[0] = t_sc[:, 0]


def _ce_dh_kernel(h_ref, w_ref, tgt_ref, lse_ref, g_ref, dh_ref, acc,
                  *, blk_n, blk_v, vocab):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    # mask padded W columns BEFORE the dh matmul: the vocab contraction
    # mixes every column into every dh element, so garbage lanes (reads past
    # V are unspecified) must be zeroed, not just ignored
    col = j * blk_v + jax.lax.broadcasted_iota(jnp.int32, (blk_n, blk_v), 1)
    valid = col < vocab
    w = jnp.where(valid[:1].reshape(1, blk_v), w_ref[...].astype(jnp.float32), 0.0)
    logits = jax.lax.dot_general(
        h_ref[...].astype(jnp.float32), w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # select (not arithmetic) so inf/NaN in padded lanes cannot propagate
    p = jnp.where(valid, jnp.exp(logits - lse_ref[0][:, None]), 0.0)
    eq = (col == tgt_ref[0][:, None]).astype(jnp.float32)
    dlogits = (p - eq) * g_ref[0][:, None]
    acc[:] = acc[:] + jax.lax.dot_general(
        dlogits, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(j == nv - 1)
    def _finalize():
        dh_ref[...] = acc[:].astype(dh_ref.dtype)


def _ce_dw_kernel(h_ref, w_ref, tgt_ref, lse_ref, g_ref, dw_ref, acc,
                  *, blk_n, blk_v, vocab, n_rows):
    j, i = pl.program_id(0), pl.program_id(1)
    ni = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    col = j * blk_v + jax.lax.broadcasted_iota(jnp.int32, (blk_n, blk_v), 1)
    logits = jax.lax.dot_general(
        h_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # rows past N (the grid over-covers) carry garbage h/lse/g: select their
    # softmax AND cotangent to exact zeros so the row contraction below
    # cannot mix inf/NaN into the dW accumulation
    row = i * blk_n + jax.lax.broadcasted_iota(jnp.int32, (blk_n, blk_v), 0)[:, 0]
    rvalid = row < n_rows
    mask = (col < vocab) & rvalid[:, None]
    p = jnp.where(mask, jnp.exp(logits - lse_ref[0][:, None]), 0.0)
    eq = (col == tgt_ref[0][:, None]).astype(jnp.float32)
    g = jnp.where(rvalid, g_ref[0], 0.0)
    dlogits = (p - eq * rvalid[:, None].astype(jnp.float32)) * g[:, None]
    h = jnp.where(rvalid[:, None], h_ref[...].astype(jnp.float32), 0.0)
    acc[:] = acc[:] + jax.lax.dot_general(
        h, dlogits, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )

    @pl.when(i == ni - 1)
    def _finalize():
        dw_ref[...] = acc[:].astype(dw_ref.dtype)


def _pallas_specs(blk_n, blk_v, D, row_major=True):
    """(h, w, row-vector) BlockSpecs for a (rows, vocab) or (vocab, rows)
    grid. Row vectors (tgt/lse/g/losses) are [1, N] arrays blocked (1, blk_n)."""
    if row_major:  # grid (i=rows, j=vocab)
        hspec = pl.BlockSpec((blk_n, D), lambda i, j: (i, 0))
        wspec = pl.BlockSpec((D, blk_v), lambda i, j: (0, j))
        rowspec = pl.BlockSpec((1, blk_n), lambda i, j: (0, i))
        return hspec, wspec, rowspec
    hspec = pl.BlockSpec((blk_n, D), lambda j, i: (i, 0))
    wspec = pl.BlockSpec((D, blk_v), lambda j, i: (0, j))
    rowspec = pl.BlockSpec((1, blk_n), lambda j, i: (0, i))
    return hspec, wspec, rowspec


def _pallas_fwd(h, w, tgt, blk_n, blk_v):
    N, D = h.shape
    V = w.shape[1]
    blk_n, blk_v = min(blk_n, N), min(blk_v, V)
    ni, nv = pl.cdiv(N, blk_n), pl.cdiv(V, blk_v)
    hspec, wspec, rowspec = _pallas_specs(blk_n, blk_v, D)
    tgt2 = tgt.reshape(1, N)
    lse, tl = pl.pallas_call(
        functools.partial(_ce_fwd_kernel, blk_n=blk_n, blk_v=blk_v, vocab=V),
        grid=(ni, nv),
        in_specs=[hspec, wspec, rowspec],
        out_specs=[rowspec, rowspec],
        out_shape=[
            _struct((1, N), jnp.float32, h, w, tgt),
            _struct((1, N), jnp.float32, h, w, tgt),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_n, 1), jnp.float32),
            pltpu.VMEM((blk_n, 1), jnp.float32),
            pltpu.VMEM((blk_n, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(h, w, tgt2)
    return lse[0], tl[0]


def _pallas_bwd(h, w, tgt, lse, g, blk_n, blk_v):
    N, D = h.shape
    V = w.shape[1]
    blk_n, blk_v = min(blk_n, N), min(blk_v, V)
    ni, nv = pl.cdiv(N, blk_n), pl.cdiv(V, blk_v)
    tgt2, lse2, g2 = tgt.reshape(1, N), lse.reshape(1, N), g.reshape(1, N)

    hspec, wspec, rowspec = _pallas_specs(blk_n, blk_v, D)
    dh = pl.pallas_call(
        functools.partial(_ce_dh_kernel, blk_n=blk_n, blk_v=blk_v, vocab=V),
        grid=(ni, nv),
        in_specs=[hspec, wspec, rowspec, rowspec, rowspec],
        out_specs=[hspec],
        out_shape=[_struct((N, D), h.dtype, h, w, g)],
        scratch_shapes=[pltpu.VMEM((blk_n, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(h, w, tgt2, lse2, g2)[0]

    hspec_t, wspec_t, rowspec_t = _pallas_specs(blk_n, blk_v, D, row_major=False)
    dw = pl.pallas_call(
        functools.partial(
            _ce_dw_kernel, blk_n=blk_n, blk_v=blk_v, vocab=V, n_rows=N
        ),
        grid=(nv, ni),
        in_specs=[hspec_t, wspec_t, rowspec_t, rowspec_t, rowspec_t],
        out_specs=[wspec_t],
        out_shape=[_struct((D, V), w.dtype, h, w, g)],
        scratch_shapes=[pltpu.VMEM((D, blk_v), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(h, w, tgt2, lse2, g2)[0]
    return dh, dw


# --- custom_vjp core ----------------------------------------------------------


def _fwd_impl(h, w, tgt, impl, vc, blk_n, blk_v):
    if impl == "pallas":
        return _pallas_fwd(h, w, tgt, blk_n, blk_v)
    return _scan_fwd(h, w, tgt, vc)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_ce(h, w, tgt, impl, vc, blk_n, blk_v):
    lse, tl = _fwd_impl(h, w, tgt, impl, vc, blk_n, blk_v)
    return lse - tl


def _fused_ce_fwd(h, w, tgt, impl, vc, blk_n, blk_v):
    lse, tl = _fwd_impl(h, w, tgt, impl, vc, blk_n, blk_v)
    # residuals: (h, w, tgt, lse) — lse is [N] fp32, target_logit is only
    # part of the VALUE, not the gradient (the -tgt term's grad is the
    # onehot the backward rebuilds from tgt)
    return lse - tl, (h, w, tgt, lse)


def _fused_ce_bwd(impl, vc, blk_n, blk_v, res, g):
    h, w, tgt, lse = res
    if impl == "pallas":
        dh, dw = _pallas_bwd(h, w, tgt, lse, g, blk_n, blk_v)
    else:
        dh, dw = _scan_bwd(h, w, tgt, lse, g, vc)
    return dh, dw, np.zeros(tgt.shape, jax.dtypes.float0)


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


# --- public entries -----------------------------------------------------------


def fused_ce_tokens(
    h: jax.Array,
    w: jax.Array,
    targets: jax.Array,
    cfg=None,
    *,
    impl: str | None = None,
    vocab_chunk: int | None = None,
    block_n: int | None = None,
    block_v: int | None = None,
) -> jax.Array:
    """Per-token cross-entropy [B, S] f32 from hidden states, without full
    logits.

    ``h``: [B, S, D] (post final-norm, any float dtype), ``w``: [D, V]
    lm_head, ``targets``: [B, S] int32. Knobs come from
    ``cfg.ce_impl`` / ``cfg.ce_vocab_chunk`` / ``cfg.ce_block_n`` /
    ``cfg.ce_block_v`` when a config is passed (kwargs win). Callers take
    ``jnp.mean`` (and add the MoE aux term) themselves.
    """
    if impl is None:
        impl = getattr(cfg, "ce_impl", None) or "scan"
    if vocab_chunk is None:
        vocab_chunk = getattr(cfg, "ce_vocab_chunk", None) or 4096
    if block_n is None:
        block_n = getattr(cfg, "ce_block_n", None) or _BLOCK_N
    if block_v is None:
        block_v = getattr(cfg, "ce_block_v", None) or _BLOCK_V
    if impl not in ("scan", "pallas"):
        raise ValueError(f"unknown ce_impl {impl!r} (expected scan | pallas)")
    B, S, D = h.shape
    if w.shape[0] != D:
        raise ValueError(f"lm_head {w.shape} does not match hidden dim {D}")
    if targets.shape != (B, S):
        raise ValueError(f"targets {targets.shape} != batch/seq {(B, S)}")
    h2 = h.reshape(B * S, D)
    t2 = targets.reshape(B * S)
    losses = _fused_ce(h2, w, t2, impl, int(vocab_chunk), int(block_n), int(block_v))
    return losses.reshape(B, S)


def sharded_fused_ce_tokens(h, w, targets, cfg=None, **kwargs) -> jax.Array:
    """Mesh-aware entry for the pallas impl (the model-level hook).

    A raw pallas_call gives the SPMD partitioner no partitioning rule, so
    under a multi-device jit it would replicate the op. The loss is row-wise
    independent: shard_map over the registered default mesh keeps batch on
    dp/fsdp/ep and seq on sp with W replicated per shard, and the per-token
    [B, S] output keeps the batch sharding (the caller's mean inserts the
    cross-shard reduce). The scan impl partitions fine under plain jit and
    never takes this path.
    """
    from jax.sharding import PartitionSpec as P

    from tony_tpu.parallel.mesh import get_default_mesh, inside_manual_region

    impl = kwargs.get("impl") or getattr(cfg, "ce_impl", None) or "scan"
    mesh = get_default_mesh()
    if (
        impl != "pallas"
        or mesh is None
        or mesh.size == 1
        or inside_manual_region()
    ):
        # inside a manual region (a pp pipeline stage) the kernel runs on
        # the region-local data; shardy cannot re-bind mesh axes there
        return fused_ce_tokens(h, w, targets, cfg, **kwargs)
    axes = set(mesh.axis_names)
    batch = tuple(a for a in ("dp", "fsdp", "ep") if a in axes) or None
    seq = "sp" if "sp" in axes else None
    spec = P(batch, seq)
    return _shard_map(
        lambda a, b, c: fused_ce_tokens(a, b, c, cfg, **kwargs),
        mesh=mesh,
        in_specs=(P(batch, seq, None), P(), spec),
        out_specs=spec,
    )(h, w, targets)


def reference_ce_tokens(h: jax.Array, w: jax.Array, targets: jax.Array) -> jax.Array:
    """Full-logits logsumexp reference: the parity oracle for both impls
    (and the legacy ``ce_impl='dense'`` math). Materialises [B, S, V]."""
    logits = jnp.einsum(
        "bsd,dv->bsv", h, w, preferred_element_type=jnp.float32
    )
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return lse - tgt


__all__ = ["fused_ce_tokens", "reference_ce_tokens", "sharded_fused_ce_tokens"]

"""jax-version compatibility shims shared by the Pallas kernel modules.

One copy of the glue that differs across the jax lines this repo runs on
(the CI image's 0.4.x vs newer): the TPU compiler-params spelling, the
vma-carrying ShapeDtypeStruct for kernels under shard_map, interpret-mode
selection off-TPU, and the shard_map entry itself. Kernel modules
(fused_ce, grouped_mm) and their callers import from here so a version fix
lands once.
"""

from __future__ import annotations

import jax
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 spells these differently; resolve once so the kernels (and the
# CPU interpreter tests) run on either line
pallas_compiler_params = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def struct_with_vma(shape, dtype, *inputs) -> jax.ShapeDtypeStruct:
    """Pallas out_shape carrying the inputs' varying-mesh-axes type (see
    ops/attention._out_struct); degrades to a plain struct on jax builds
    without ``jax.typeof``/vma typing."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    vma = frozenset()
    for x in inputs:
        vma |= getattr(typeof(x), "vma", frozenset()) or frozenset()
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def shard_map_compat(*args, **kwargs):
    """``jax.shard_map`` where it exists, the experimental spelling
    otherwise — translating the new kwargs the old one doesn't know:
    ``check_vma`` -> ``check_rep`` (default off — the legacy checker has no
    rule for pallas_call; the new-jax path carries the vma set on the
    kernel out_shape instead) and partial-manual ``axis_names`` -> its
    complement ``auto``."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn

        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        kwargs.setdefault("check_rep", False)
        if "axis_names" in kwargs:
            # partial-manual is not viable on this line: the old tracer
            # lowers axis_index in a manual-with-auto region through a
            # PartitionId instruction the SPMD partitioner rejects. Fall
            # back to FULL manual: unmentioned axes are treated as
            # replicated (shard_map reshards at entry), which trades the
            # auto axes' compute sharding inside the region for
            # correctness — acceptable on the CPU-correctness CI line;
            # the new-jax path keeps true partial-auto.
            kwargs.pop("axis_names")
    return fn(*args, **kwargs)


def use_interpret() -> bool:
    """Pallas interpret mode everywhere but real TPU (CPU tests/CI)."""
    return jax.default_backend() != "tpu"


def axis_size(axis_name) -> int:
    """``lax.axis_size`` where it exists; the psum-of-1 idiom otherwise
    (old jax constant-folds a literal psum to the axis size)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def pcast_varying(x, axis_names):
    """``lax.pcast(x, axes, to='varying')`` on jax lines with vma typing;
    identity where the typing system (and pcast) doesn't exist — old
    shard_map with check_rep off imposes no varying-axes constraints, so
    there is nothing to cast."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, tuple(axis_names), to="varying")


def vma_of(x) -> frozenset:
    """The varying-mesh-axes set of ``x``'s type (empty on jax builds
    without ``jax.typeof``)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return getattr(typeof(x), "vma", frozenset()) or frozenset()


__all__ = [
    "axis_size", "pallas_compiler_params", "pcast_varying",
    "shard_map_compat", "struct_with_vma", "use_interpret", "vma_of",
]

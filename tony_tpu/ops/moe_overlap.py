"""Overlapped expert-parallel MoE combine (docs/PERF.md round 20).

The plain ep grouped path (`tony_tpu.parallel.moe._moe_grouped_ep`) runs the
whole local expert FFN and then issues ONE blocking full-width
``psum(y, "ep")`` — every byte of combine traffic waits for the last FLOP of
expert compute, the Megatron-style serialization `ops.overlap` already
removed from the dense fsdp/dp collectives (arXiv:2104.04473). This module
decomposes that combine on the TOKEN dim: the per-shard token rows are split
into ``n_chunks`` static slices and each chunk runs (local grouped FFN over
the chunk's routes) -> (chunk-width psum of the per-expert-group partials).
The loop is python-unrolled, so XLA's latency-hiding scheduler starts chunk
``c``'s psum while chunk ``c+1``'s FFN is still on the MXU — later chunks'
compute hides earlier chunks' combine traffic.

Token-chunking (not expert-group-chunking) is the deliberate schedule:
chunking the combine by expert group would psum each group's full ``[T, D]``
partial separately — ``n_chunks``x the combine bytes — while token slices
keep total traffic exactly equal to the single psum (disjoint row blocks)
and keep every shape static. Each chunk's psum still combines that chunk's
per-expert-group partials across the ep shards.

``overlapped_combine`` is a ``custom_vjp`` so the backward is the matching
decomposed collective: the transpose of a per-chunk psum of disjoint row
slices is a per-chunk psum of the corresponding COTANGENT slices — never
one refused full-width collective. The boundary contract (probed on this
jax line, ``check_rep=False``): shard_map delivers an ep-unmentioned
output's cotangent split 1/ep per shard and itself psums returned
cotangents over each input's unmentioned axes. So the backward psums each
incoming cotangent chunk once (restoring the true value, exactly how AD
transposes the plain path's single psum) and returns everything else
LOCAL: the ep-sharded expert weights keep their shard's grad, the
ep-replicated token/weight cotangents are per-shard contributions the
boundary reduces, and the int route tensor takes ``float0`` zeros (the
`ops.grouped_mm` idiom).

The two impls follow the repo pattern: ``'scan'`` drives the chunk FFN's
grouped matmuls through the pure-XLA lax.scan kernel (CPU/shard_map-safe
reference), ``'pallas'`` through the TPU Pallas kernel (interpret mode on
CPU). The schedule itself is identical — only the per-chunk GEMM kernel
changes.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

_IMPLS = ("scan", "pallas")


def _check_impl(impl: str) -> None:
    if impl not in _IMPLS:
        raise ValueError(
            f"unknown MoE overlap impl {impl!r}; expected one of {_IMPLS} "
            "(or 'off')"
        )


def overlap_chunks(t_local: int, chunk_tokens: int) -> int | None:
    """Resolve the chunk count for ``t_local`` per-shard token rows, or
    ``None`` when the decomposition does not apply (the caller keeps the
    single blocking psum — overlap is an optimisation, never a
    requirement).

    ``chunk_tokens > 0`` pins the chunk size (from the measured sizing
    rule, `chunk_tokens_from_report`); it must divide ``t_local`` and
    leave >= 2 chunks, else decline — a ragged tail chunk would change
    the collective's shape per chunk and recompile per schedule.
    ``chunk_tokens == 0`` auto-picks the largest clean split in {4, 3, 2}.
    """
    if t_local <= 1:
        return None
    if chunk_tokens > 0:
        if chunk_tokens >= t_local or t_local % chunk_tokens != 0:
            return None
        return t_local // chunk_tokens
    for n in (4, 3, 2):
        if t_local % n == 0:
            return n
    return None


def chunk_tokens_from_report(step_anatomy: dict[str, Any] | None, *,
                             dim: int, dtype_bytes: int = 2,
                             default_tokens: int = 2048) -> int:
    """Solve the overlap chunk size from a measured step-anatomy section
    (the OFF capture of the MoE bench — the `bucket_bytes_from_report`
    rule transposed to tokens).

    A chunk's psum hides iff it finishes within one chunk's FFN window,
    so ``tokens x dim x dtype_bytes = achieved_gbps x window`` with
    ``window = compute_ms / 2`` as the conservative per-chunk compute
    share (the FFN dominates an MoE step; half the step is the floor any
    >= 2-way split guarantees). Uses the top collective's measured
    bandwidth (the ep combine is the dominant MoE collective); falls back
    to ``default_tokens`` when the capture has no measured bandwidth.
    Clamped to [256, 8192] and rounded down to a multiple of 256 so the
    chunk rows stay sublane-tile aligned through the grouped GEMM.
    """
    if not step_anatomy or dim <= 0:
        return default_tokens
    top = step_anatomy.get("top_collective") or {}
    gbps = float(top.get("achieved_gbps") or 0.0)
    compute_ms = float(step_anatomy.get("compute_ms") or 0.0)
    if gbps <= 0.0 or compute_ms <= 0.0:
        return default_tokens
    window_s = 0.5 * (compute_ms / 1e3)
    raw = int(gbps * 1e9 * window_s / (dim * dtype_bytes))
    clamped = max(256, min(raw, 8192))
    return (clamped // 256) * 256


# --- the decomposed combine ---------------------------------------------------


def _chunk_slices(t: int, n_chunks: int) -> list[slice]:
    ct = t // n_chunks
    return [slice(c * ct, (c + 1) * ct) for c in range(n_chunks)]


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def overlapped_combine(ffn_fn: Callable[..., jax.Array], axis_name: str,
                       n_chunks: int, w1: jax.Array, w3: jax.Array,
                       w2: jax.Array, flat: jax.Array, sel: jax.Array,
                       weight: jax.Array) -> jax.Array:
    """Chunked-psum ep combine: ``concat_c(psum(ffn_fn(chunk_c), axis))``.

    Call INSIDE the ep shard_map. ``ffn_fn(w1, w3, w2, flat_c, sel_c,
    weight_c) -> [ct, D]`` is the shard-local chunk FFN (ownership masking
    included — `parallel.moe._chunk_ffn`); it must be a hashable static
    callable. ``flat [t, D]`` / ``sel [t, k]`` / ``weight [t, k]`` are the
    shard-local token rows and routes. Numerically this IS the single
    ``psum(ffn(flat))``: the chunks are disjoint row slices, so the
    per-chunk psums are elementwise identical to one full-width psum.
    """
    outs = []
    for s in _chunk_slices(flat.shape[0], n_chunks):
        y_c = ffn_fn(w1, w3, w2, flat[s], sel[s], weight[s])
        outs.append(jax.lax.psum(y_c, axis_name))
    return jnp.concatenate(outs, axis=0)


def _chunk_primal(ffn_fn, sel_c, w1, w3, w2, flat_c, weight_c):
    """Diff-arg-only view of one chunk's local FFN (sel is int, closed
    over) — what the backward re-linearises per chunk."""
    return ffn_fn(w1, w3, w2, flat_c, sel_c, weight_c)


def _overlapped_combine_fwd(ffn_fn, axis_name, n_chunks, w1, w3, w2, flat,
                            sel, weight):
    y = overlapped_combine(ffn_fn, axis_name, n_chunks, w1, w3, w2, flat,
                           sel, weight)
    return y, (w1, w3, w2, flat, sel, weight)


def _overlapped_combine_bwd(ffn_fn, axis_name, n_chunks, res, g):
    w1, w3, w2, flat, sel, weight = res
    dw1 = dw3 = dw2 = None
    dflat, dweight = [], []
    for s in _chunk_slices(flat.shape[0], n_chunks):
        # the transpose of a chunk's forward psum is a psum of that
        # chunk's cotangent slice — the boundary splits an ep-unmentioned
        # output's cotangent 1/ep across shards (probed, check_rep=False),
        # and this per-chunk collective restores the full value, exactly
        # how AD transposes the plain path's single psum, decomposed
        g_c = jax.lax.psum(g[s], axis_name)
        chunk = partial(_chunk_primal, ffn_fn, sel[s])
        _, vjp_fn = jax.vjp(chunk, w1, w3, w2, flat[s], weight[s])
        dw1_c, dw3_c, dw2_c, dfl_c, dwg_c = vjp_fn(g_c)
        # everything below stays LOCAL: the ep-sharded expert weights keep
        # their own shard's grad (accumulated over chunks), and the ep-
        # replicated token/weight cotangents are per-shard contributions
        # the boundary itself psums over ep — adding our own psum here
        # would double-count it
        dw1 = dw1_c if dw1 is None else dw1 + dw1_c
        dw3 = dw3_c if dw3 is None else dw3 + dw3_c
        dw2 = dw2_c if dw2 is None else dw2 + dw2_c
        dflat.append(dfl_c)
        dweight.append(dwg_c)
    dsel = np.zeros(sel.shape, jax.dtypes.float0)
    return (dw1, dw3, dw2, jnp.concatenate(dflat, axis=0), dsel,
            jnp.concatenate(dweight, axis=0))


overlapped_combine.defvjp(_overlapped_combine_fwd, _overlapped_combine_bwd)


__all__ = [
    "chunk_tokens_from_report",
    "overlap_chunks",
    "overlapped_combine",
]

"""TPU kernels (Pallas) with interpreter-mode CPU fallbacks."""

from tony_tpu.ops.attention import flash_attention

__all__ = ["flash_attention"]

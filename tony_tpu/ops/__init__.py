"""TPU kernels (Pallas) with interpreter-mode CPU fallbacks."""

from tony_tpu.ops.attention import flash_attention
from tony_tpu.ops.fused_ce import fused_ce_tokens
from tony_tpu.ops.grouped_mm import grouped_layout, grouped_matmul
from tony_tpu.ops.moe_overlap import overlapped_combine

__all__ = [
    "flash_attention", "fused_ce_tokens", "grouped_layout", "grouped_matmul",
    "overlapped_combine",
]

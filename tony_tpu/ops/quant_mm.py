"""Int8 weight-only matmul with per-output-channel scales.

The serving decode step is memory-bandwidth bound: every step streams the
full weight matrices through the chip to produce ONE row per slot. Weight-
only quantization (the AWQ lineage, arXiv:2306.00978 — store int8, compute
in the activation dtype) halves that stream without touching activations:
``W [D, N]`` is stored as int8 with one float32 scale per OUTPUT channel
(``amax over D / 127``), and the matmul dequantizes tiles of W on the fly.
Per-output-channel granularity keeps the scale a [N] vector the matmul can
fold in after the contraction — no per-group bookkeeping inside the MXU
inner loop — while bounding each channel's quantization error by its own
dynamic range.

Two interchangeable implementations (the ``fused_ce``/``grouped_mm``/
``decode_attention`` pattern), dispatched on ``impl``:

- ``'scan'`` — ``lax.scan`` over column tiles: dequantize one ``[D, bn]``
  tile, matmul, emit. Pure XLA, runs anywhere, bounds the dequantized
  transient to one tile instead of the whole matrix.
- ``'pallas'`` — a TPU kernel over an ``(N / bn,)`` grid that fuses
  dequantize + matmul per tile, so the bf16 copy of W never exists outside
  VMEM. Interpreter mode on CPU.

Inference-only (no backward): the engine quantizes its decode weights once
at construction (serve/engine.py, ``serve.quant.weights``); prefill keeps
the bf16 master weights.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from tony_tpu.ops.compat import (
    pallas_compiler_params as _CompilerParams,
    use_interpret as _use_interpret,
)

WEIGHT_QMAX = 127.0


def quantize_weights(w: jax.Array) -> tuple[jax.Array, jax.Array]:
    """``w [..., D, N]`` -> (int8 ``[..., D, N]``, float32 scales
    ``[..., N]``): symmetric per-output-channel quantization (amax over
    the contraction dim / 127). Leading dims (the engine's stacked-layer
    ``[L, D, N]`` weights) quantize independently per layer."""
    wf = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(wf), axis=-2) / WEIGHT_QMAX          # [..., N]
    q = wf / jnp.maximum(scale[..., None, :], 1e-30)
    return (
        jnp.clip(jnp.round(q), -WEIGHT_QMAX, WEIGHT_QMAX).astype(jnp.int8),
        scale,
    )


def _pick_block(n: int, block_n: int) -> int:
    """Largest divisor of N out of (block_n, halvings of it, N itself)."""
    bn = min(block_n, n)
    while bn > 1 and n % bn:
        bn //= 2
    return bn if n % bn == 0 else n


def _scan_impl(x2, wq, scale, bn):
    D, N = wq.shape
    nb = N // bn

    def body(_, j):
        wb = lax.dynamic_slice_in_dim(wq, j * bn, bn, axis=1)
        sb = lax.dynamic_slice_in_dim(scale, j * bn, bn)
        wd = (wb.astype(jnp.float32) * sb[None, :]).astype(x2.dtype)
        y = lax.dot_general(
            x2, wd, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return None, y.astype(x2.dtype)                 # [Bx, bn]

    _, ys = lax.scan(body, None, jnp.arange(nb, dtype=jnp.int32))
    return jnp.moveaxis(ys, 0, 1).reshape(x2.shape[0], N)


def _qmm_kernel(x_ref, wq_ref, s_ref, o_ref):
    w = (wq_ref[...].astype(jnp.float32) * s_ref[0, :][None, :]).astype(
        x_ref.dtype
    )
    o_ref[...] = lax.dot_general(
        x_ref[...], w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def _pallas_impl(x2, wq, scale, bn):
    Bx, D = x2.shape
    N = wq.shape[1]
    out = pl.pallas_call(
        _qmm_kernel,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((Bx, D), lambda j: (0, 0)),
            pl.BlockSpec((D, bn), lambda j: (0, j)),
            pl.BlockSpec((1, bn), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((Bx, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((Bx, N), x2.dtype),
        compiler_params=_CompilerParams(dimension_semantics=("parallel",)),
        interpret=_use_interpret(),
    )(x2, wq, scale[None, :].astype(jnp.float32))
    return out


def quant_matmul(
    x: jax.Array, wq: jax.Array, scale: jax.Array, *,
    impl: str = "scan", block_n: int = 256,
) -> jax.Array:
    """``x [..., D] @ dequant(wq [D, N], scale [N]) -> [..., N]``.

    The contraction runs in ``x.dtype`` with float32 accumulation —
    numerically what the bf16 matmul does, on weights whose per-channel
    error is bounded by ``scale / 2`` (half an int8 step). ``block_n``
    tiles the output channels (rounded down to a divisor of N)."""
    if impl not in ("scan", "pallas"):
        raise ValueError(f"unknown quant_mm impl {impl!r} (scan | pallas)")
    if wq.ndim != 2 or scale.shape != wq.shape[-1:]:
        raise ValueError(
            f"quant_matmul weight shapes wq={wq.shape} scale={scale.shape}"
        )
    D, N = wq.shape
    lead = x.shape[:-1]
    if x.shape[-1] != D:
        raise ValueError(f"quant_matmul x={x.shape} vs wq={wq.shape}")
    x2 = x.reshape(-1, D)
    bn = _pick_block(N, block_n)
    if impl == "pallas":
        out = _pallas_impl(x2, wq, scale, bn)
    else:
        out = _scan_impl(x2, wq, scale, bn)
    return out.reshape(*lead, N)


__all__ = ["WEIGHT_QMAX", "quant_matmul", "quantize_weights"]

"""Communication–compute overlap: decomposed fsdp collectives.

The GSPMD partitioner materialises each fsdp-sharded weight with one
blocking all-gather per matmul and reduces its gradient with one blocking
reduce-scatter — the step-anatomy report (obs/anatomy.py) prices that as
``exposed_collective_s``. This module spends the report: the collective
matmul decomposition (Wang et al., "Overlap communication with dependent
computation", ASPLOS'23 — the same lineage as Megatron-LM's overlap flags,
arXiv:2104.04473) splits the gathered operand into ring chunks and pipelines
``lax.ppermute`` hops against per-chunk matmuls, so the interconnect runs
while the MXU does — nothing waits on a full-tensor gather.

Three per-device primitives (call inside shard_map, manual over the fsdp
axis), each in the repo's two-impl pattern — ``'scan'`` is the pure-XLA
CPU/shard_map-safe default, ``'pallas'`` runs each chunk's matmul as a tiled
TPU kernel (interpret-mode on CPU), the ring hops staying ``lax.ppermute``
between kernel launches exactly like parallel.ring_attention's ring_flash:

- :func:`all_gather_matmul_local` — ``x @ W`` where W is sharded over the
  ring on ``gather_dim`` (0: contraction rows -> accumulate partial
  products; 1: output columns -> write column slices). custom_vjp: dx is the
  mirrored ring against Wᵀ, dW is the matmul-reduce-scatter below, so the
  backward overlaps symmetrically.
- :func:`matmul_reduce_scatter_local` — ``xᵀ @ g`` reduce-scattered over
  the ring: the accumulator rides the ring (one hop per chunk) while each
  device computes the next partial product, landing shard ``i`` on device
  ``i`` with no full [D, N] gradient ever materialised.
- :func:`bucketed_psum` — the dp gradient-reduction side: leaves grouped
  into byte-budgeted buckets, one collective per bucket, so each bucket's
  reduce dispatches as soon as its leaves' backward is done and rides
  behind the remaining backward compute. Grouping is value-exact: a psum
  of a tuple IS the tuple of psums.

:func:`overlap_matmul` is the GSPMD-context entry llama.py calls: it
shard_maps the ring op over the default mesh's fsdp axis and returns None
when the decomposition does not apply (no mesh, axis size 1, indivisible
shapes, already inside a manual region) so the caller falls back to the
plain matmul — overlap is an optimisation, never a requirement.

Bucket sizing is read off the measured anatomy report, not guessed:
:func:`bucket_bytes_from_report` solves ``bytes = achieved_gbps x
per-layer-backward-window`` from the committed fixture numbers.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

from tony_tpu.ops.compat import (
    axis_size as _axis_size,
    pallas_compiler_params as _CompilerParams,
    shard_map_compat as _shard_map,
    struct_with_vma as _struct_with_vma,
    use_interpret as _use_interpret,
)

_IMPLS = ("scan", "pallas")


def _pick_block(n: int, block_n: int) -> int:
    """Largest divisor of N out of (block_n, halvings of it, N itself)."""
    bn = min(block_n, n)
    while bn > 1 and n % bn:
        bn //= 2
    return bn if n % bn == 0 else n


def _mm_kernel(a_ref, b_ref, o_ref):
    o_ref[...] = lax.dot_general(
        a_ref[...], b_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _chunk_mm(a: jax.Array, b: jax.Array, impl: str,
              block_n: int = 256) -> jax.Array:
    """One ring chunk's ``a [M,K] @ b [K,N] -> f32 [M,N]``."""
    if impl == "pallas":
        M, K = a.shape
        N = b.shape[1]
        bn = _pick_block(N, block_n)
        return pl.pallas_call(
            _mm_kernel,
            grid=(N // bn,),
            in_specs=[
                pl.BlockSpec((M, K), lambda j: (0, 0)),
                pl.BlockSpec((K, bn), lambda j: (0, j)),
            ],
            out_specs=pl.BlockSpec((M, bn), lambda j: (0, j)),
            out_shape=_struct_with_vma((M, N), jnp.float32, a, b),
            compiler_params=_CompilerParams(
                dimension_semantics=("parallel",)
            ),
            interpret=_use_interpret(),
        )(a, b)
    return lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _vma_zero(*xs) -> jax.Array:
    """An f32 scalar 0 derived from the operands so accumulators carry
    their varying-mesh-axes type (the ring_attention idiom)."""
    z = jnp.float32(0.0)
    for x in xs:
        z = z + x.astype(jnp.float32).sum() * 0.0
    return z


def _ring_contract(x2, w_loc, axis_name, impl):
    """``sum_i x2[:, rows_i] @ W_i`` — W gathered on its contraction dim.

    x2 [M, D] full-width activations, w_loc [D/n, N] this device's row
    shard. Chunk i's rows multiply while the NEXT shard is already in
    flight on the ring: the ppermute and the matmul have no data
    dependency, so XLA schedules them concurrently.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    Dl, N = w_loc.shape
    perm = [(i, (i + 1) % n) for i in range(n)]
    y0 = jnp.zeros((x2.shape[0], N), jnp.float32) + _vma_zero(x2, w_loc)

    def body(j, carry):
        w_cur, y = carry
        idx = (my - j) % n  # which shard this device holds at step j
        xs = lax.dynamic_slice_in_dim(x2, idx * Dl, Dl, axis=1)
        y = y + _chunk_mm(xs, w_cur, impl)
        w_next = lax.ppermute(w_cur, axis_name, perm)
        return w_next, y

    _, y = lax.fori_loop(0, n, body, (w_loc, y0))
    return y


def _ring_concat(x2, w_loc, axis_name, impl):
    """``y[:, cols_i] = x2 @ W_i`` — W gathered on its output dim.

    x2 [M, D], w_loc [D, N/n] this device's column shard; returns the full
    [M, N] with each column block written as its shard arrives.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    Nl = w_loc.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]
    y0 = jnp.zeros((x2.shape[0], Nl * n), jnp.float32) + _vma_zero(x2, w_loc)

    def body(j, carry):
        w_cur, y = carry
        idx = (my - j) % n
        blk = _chunk_mm(x2, w_cur, impl)
        y = lax.dynamic_update_slice_in_dim(y, blk, idx * Nl, axis=1)
        w_next = lax.ppermute(w_cur, axis_name, perm)
        return w_next, y

    _, y = lax.fori_loop(0, n, body, (w_loc, y0))
    return y


def _ring_reduce_scatter(partial_fn, shape, axis_name, *operands):
    """Ring reduce-scatter of ``sum_devices partial_fn(chunk)``.

    ``partial_fn(c)`` is this device's f32 contribution to output chunk
    ``c``; the accumulator rides the ring (chunk schedule ``(my - j - 1)
    mod n``: what arrives at step j was built by upstream devices for the
    same chunk, and a device adds its OWN chunk last, at j = n-1 — so the
    final hop lands shard ``my`` home fully reduced). Each hop's send
    overlaps the next partial product's matmul.
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    acc0 = partial_fn((my - 1) % n) + (
        jnp.zeros(shape, jnp.float32) + _vma_zero(*operands)
    )

    def body(j, acc):
        acc = lax.ppermute(acc, axis_name, perm)
        return acc + partial_fn((my - j - 1) % n)

    return lax.fori_loop(1, n, body, acc0)


def _check_impl(impl: str) -> None:
    if impl not in _IMPLS:
        raise ValueError(f"unknown overlap impl {impl!r} (scan | pallas)")


def _flat2(x: jax.Array) -> jax.Array:
    return x.reshape(-1, x.shape[-1])


# --- all-gather-matmul --------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def all_gather_matmul_local(x, w_loc, axis_name="fsdp", gather_dim=0,
                            impl="scan"):
    """``x [..., D] @ W [D, N] -> [..., N]`` with W ring-sharded on
    ``gather_dim`` over ``axis_name``; call inside shard_map. Exact (f32
    accumulation), never materialises the gathered W.
    """
    _check_impl(impl)
    y = (_ring_contract if gather_dim == 0 else _ring_concat)(
        _flat2(x), w_loc, axis_name, impl
    )
    out_dtype = jnp.promote_types(x.dtype, w_loc.dtype)
    return y.reshape(*x.shape[:-1], y.shape[-1]).astype(out_dtype)


def _agm_fwd(x, w_loc, axis_name, gather_dim, impl):
    return (
        all_gather_matmul_local(x, w_loc, axis_name, gather_dim, impl),
        (x, w_loc),
    )


def _agm_bwd(axis_name, gather_dim, impl, res, dy):
    x, w_loc = res
    x2, g2 = _flat2(x), _flat2(dy)
    wt = w_loc.T  # sharded on the OPPOSITE dim: the bwd ring mirrors the fwd
    if gather_dim == 0:
        # dx[:, rows_i] = dy @ W_iᵀ ; dW_i = sum_dev x[:, rows_i]ᵀ @ dy
        dx2 = _ring_concat(g2, wt, axis_name, impl)
        Dl = w_loc.shape[0]

        def dw_partial(c):
            xs = lax.dynamic_slice_in_dim(x2, c * Dl, Dl, axis=1)
            return _chunk_mm(xs.T, g2, impl)

        dw = _ring_reduce_scatter(dw_partial, w_loc.shape, axis_name, x, dy)
    else:
        # dx = sum_i dy[:, cols_i] @ W_iᵀ ; dW_i = sum_dev xᵀ @ dy[:, cols_i]
        dx2 = _ring_contract(g2, wt, axis_name, impl)
        Nl = w_loc.shape[1]

        def dw_partial(c):
            gs = lax.dynamic_slice_in_dim(g2, c * Nl, Nl, axis=1)
            return _chunk_mm(x2.T, gs, impl)

        dw = _ring_reduce_scatter(dw_partial, w_loc.shape, axis_name, x, dy)
    dx = dx2.reshape(x.shape).astype(x.dtype)
    return dx, dw.astype(w_loc.dtype)


all_gather_matmul_local.defvjp(_agm_fwd, _agm_bwd)


# --- matmul-reduce-scatter ----------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def matmul_reduce_scatter_local(x, g, axis_name="fsdp", scatter_dim=0,
                                impl="scan"):
    """``reduce_scatter(xᵀ @ g)`` over ``axis_name``: x [..., D], g [..., N]
    -> this device's shard of the [D, N] product (rows for scatter_dim=0,
    columns for 1), summed over the axis. The full product never exists.
    """
    _check_impl(impl)
    x2, g2 = _flat2(x), _flat2(g)
    D, N = x2.shape[1], g2.shape[1]
    n = _axis_size(axis_name)
    if scatter_dim == 0:
        Dl = D // n

        def partial_fn(c):
            xs = lax.dynamic_slice_in_dim(x2, c * Dl, Dl, axis=1)
            return _chunk_mm(xs.T, g2, impl)

        shape = (Dl, N)
    else:
        Nl = N // n

        def partial_fn(c):
            gs = lax.dynamic_slice_in_dim(g2, c * Nl, Nl, axis=1)
            return _chunk_mm(x2.T, gs, impl)

        shape = (D, Nl)
    out = _ring_reduce_scatter(partial_fn, shape, axis_name, x, g)
    return out.astype(jnp.promote_types(x.dtype, g.dtype))


def _mrs_fwd(x, g, axis_name, scatter_dim, impl):
    return matmul_reduce_scatter_local(x, g, axis_name, scatter_dim, impl), (x, g)


def _mrs_bwd(axis_name, scatter_dim, impl, res, dy):
    # y_c = sum_dev x[:, rows_c]ᵀ g (scatter_dim=0): the transpose all-gathers
    # dy around the SAME ring — dx streams chunk products, dg accumulates.
    x, g = res
    x2, g2 = _flat2(x), _flat2(g)
    dyt = dy.T  # [N, Dl] (0) / [Nl, D] (1): ring operand, gathered on dim 1/0
    if scatter_dim == 0:
        dx2 = _ring_concat(g2, dyt, axis_name, impl)       # [M, D]
        dg2 = _ring_contract(x2, dy, axis_name, impl)      # [M, N]
    else:
        dx2 = _ring_contract(g2, dyt, axis_name, impl)     # [M, D]
        # dg[:, cols_c] = x2 @ dy_c: dy [D, Nl] is already the per-chunk
        # column block — concat mode over the ring
        dg2 = _ring_concat(x2, dy, axis_name, impl)        # [M, N]
    return (
        dx2.reshape(x.shape).astype(x.dtype),
        dg2.reshape(g.shape).astype(g.dtype),
    )


matmul_reduce_scatter_local.defvjp(_mrs_fwd, _mrs_bwd)


# --- GSPMD-context entry ------------------------------------------------------


def overlap_matmul(x: jax.Array, w: jax.Array, *, gather_dim: int,
                   impl: str = "scan", axis_name: str = "fsdp",
                   mesh=None) -> jax.Array | None:
    """Route ``x [..., D] @ w`` through the decomposed ring inside a
    shard_map over ``axis_name``, or return None when the decomposition
    does not apply so the caller runs the plain matmul. Safe under jit /
    lax.scan / jax.checkpoint (the ring_attention precedent).
    """
    _check_impl(impl)
    if mesh is None:
        from tony_tpu.parallel.mesh import get_default_mesh

        mesh = get_default_mesh()
    from tony_tpu.parallel.mesh import inside_manual_region

    if mesh is None or inside_manual_region():
        return None
    n = int(mesh.shape.get(axis_name, 1))
    if n <= 1:
        return None
    # the ring needs clean shard boundaries: batch rows per device and
    # weight chunks along the gathered dim
    if x.shape[0] % n or w.shape[gather_dim] % n:
        return None

    def f(xl, wl):
        return all_gather_matmul_local(xl, wl, axis_name, gather_dim, impl)

    x_spec = P(axis_name, *([None] * (x.ndim - 1)))
    w_spec = P(axis_name, None) if gather_dim == 0 else P(None, axis_name)
    return _shard_map(
        f, mesh=mesh,
        in_specs=(x_spec, w_spec),
        out_specs=x_spec,
        axis_names={axis_name},
    )(x, w)


# --- bucketed gradient reduction ----------------------------------------------


def bucket_plan(nbytes: list[int], bucket_bytes: int) -> list[list[int]]:
    """Group leaf indices (in order) into buckets of ~bucket_bytes each.

    Order-preserving greedy fill: grads materialise roughly in tree order
    during the backward, so contiguous buckets are the ones whose reduce
    can dispatch as soon as their last member's layer finishes. A leaf
    larger than the budget gets its own bucket (never split — splitting
    would change the collective's shape and recompile per plan).
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    plan: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i, b in enumerate(nbytes):
        if cur and cur_bytes + b > bucket_bytes:
            plan.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += b
    if cur:
        plan.append(cur)
    return plan


def bucketed_psum(tree: Any, axis_name: str, *, bucket_bytes: int) -> Any:
    """All-reduce a grad pytree over ``axis_name`` in byte-budgeted buckets.

    One ``lax.psum`` per bucket (a tuple psum — XLA fuses it into a single
    collective over the bucket's leaves, lowered on TPU as the
    reduce-scatter + all-gather pair), issued in leaf order: the scheduler
    is free to launch bucket k's collective while the backward for bucket
    k+1's layers is still computing. Value-exact vs one whole-tree psum —
    grouping never changes the elementwise sums.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    sizes = [x.size * x.dtype.itemsize for x in leaves]
    out: list[Any] = [None] * len(leaves)
    for idx in bucket_plan(sizes, bucket_bytes):
        red = lax.psum(tuple(leaves[i] for i in idx), axis_name)
        for i, r in zip(idx, red):
            out[i] = r
    return jax.tree_util.tree_unflatten(treedef, out)


def bucket_bytes_from_report(step_anatomy: dict[str, Any] | None, *,
                             n_layers: int,
                             default_bytes: int = 8 << 20) -> int:
    """Solve the bucket size from a measured step-anatomy section
    (bench_report extra.step_anatomy — the committed fixture shape).

    The sizing rule: a bucket's reduce hides iff it finishes within one
    layer's backward window, so ``bytes = achieved_gbps x window`` with
    ``window = backward share (2/3) x compute_ms / n_layers``. Uses the
    top collective's measured bandwidth (the dominant grad reduce); falls
    back to ``default_bytes`` when the report has no measured bandwidth
    (e.g. a capture without a device trace). Clamped to [1 MiB, 128 MiB].
    """
    if not step_anatomy or n_layers <= 0:
        return default_bytes
    top = step_anatomy.get("top_collective") or {}
    gbps = float(top.get("achieved_gbps") or 0.0)
    compute_ms = float(step_anatomy.get("compute_ms") or 0.0)
    if gbps <= 0.0 or compute_ms <= 0.0:
        return default_bytes
    window_s = (2.0 / 3.0) * (compute_ms / 1e3) / n_layers
    raw = int(gbps * 1e9 * window_s)
    return max(1 << 20, min(raw, 128 << 20))


__all__ = [
    "all_gather_matmul_local",
    "bucket_bytes_from_report",
    "bucket_plan",
    "bucketed_psum",
    "matmul_reduce_scatter_local",
    "overlap_matmul",
]

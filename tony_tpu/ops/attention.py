"""Pallas flash attention for TPU (FlashAttention-2 schedule).

The hot op of the training library (SURVEY.md section 7 layer 7): blockwise
causal attention that never materialises the [S, S] score matrix. Forward and
backward are Pallas kernels; the backward uses the saved logsumexp and the
delta trick (rowsum(dO * O)) per FlashAttention-2 (arXiv:2307.08691).

TPU mapping: inputs are folded to [B*H, S, head_dim] so every block spec ends
in (block, head_dim) — the Mosaic lowering requires the last two block dims
tiled (8, 128)-aligned. The grid is (batch*head, q-block, k-block) with the
k-block dimension innermost: TPU grids iterate sequentially on-core, so the
online-softmax accumulator lives in VMEM scratch across k-steps and the
output block is finalised on the last k-step. Matmuls hit the MXU with fp32
accumulation; blocks entirely above the causal diagonal skip their FLOPs via
pl.when predication.

On non-TPU backends the kernels run in interpreter mode (CPU tests); the
public entry matches the AttnFn contract (q, k, v, cfg) of
tony_tpu.models.llama.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tony_tpu.ops.compat import (
    pallas_compiler_params as _CompilerParams,
    shard_map_compat as _shard_map,
    use_interpret as _use_interpret,
)

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


# --- forward -----------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_sc, l_sc,
                *, scale, blk_q, blk_k, causal):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)

    # whole block above the diagonal -> no contribution, skip its FLOPs
    run = (not causal) or (j * blk_k <= i * blk_q + blk_q - 1)

    @pl.when(run)
    def _block():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = i * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = j * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_sc[:, 0] = l_sc[:, 0] * corr + jnp.sum(p, axis=1)
        acc[:] = acc[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:, 0] = m_new

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:, 0], 1e-30)
        o_ref[0] = (acc[:] / l[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_sc[:, 0] + jnp.log(l)


def _kv_index(b: int, heads: int, kv_heads: int) -> int:
    """Fold a [B*H] grid index onto the [B*kv_heads] K/V array (GQA)."""
    rep = heads // kv_heads
    return (b // heads) * kv_heads + (b % heads) // rep


def _out_struct(shape, dtype, *inputs) -> jax.ShapeDtypeStruct:
    """Pallas out_shape carrying the inputs' varying-mesh-axes type: inside a
    shard_map region (e.g. a pp pipeline stage) outputs must declare the vma
    set or shard_map's type checker rejects the call. One shared copy in
    ops.compat (degrades gracefully on jax builds without ``jax.typeof``)."""
    from tony_tpu.ops.compat import struct_with_vma

    return struct_with_vma(shape, dtype, *inputs)


def _flash_fwd(q, k, v, *, scale, blk_q, blk_k, causal, heads, kv_heads):
    """q: [B*heads, S, D], k/v: [B*kv_heads, S, D] ->
    (out [B*heads, S, D], lse [B*heads, 1, S] fp32)."""
    BH, S, D = q.shape
    nq, nk = pl.cdiv(S, blk_q), pl.cdiv(S, blk_k)
    qspec = pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec(
        (1, blk_k, D), lambda b, i, j: (_kv_index(b, heads, kv_heads), j, 0)
    )
    rowspec = pl.BlockSpec((1, 1, blk_q), lambda b, i, j: (b, 0, i))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k, causal=causal),
        grid=(BH, nq, nk),
        in_specs=[qspec, kspec, kspec],
        out_specs=[qspec, rowspec],
        out_shape=[
            _out_struct((BH, S, D), q.dtype, q, k, v),
            _out_struct((BH, 1, S), jnp.float32, q, k, v),
        ],
        # out/lse blocks revisit the same index across the k-step dim
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        scratch_shapes=[
            pltpu.VMEM((blk_q, D), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q, k, v)
    return out, lse


# --- backward ----------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc,
                   *, scale, blk_q, blk_k, causal):
    i, j = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    run = (not causal) or (j * blk_k <= i * blk_q + blk_q - 1)

    @pl.when(run)
    def _block():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse, delta = lse_ref[0, 0], delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = i * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = j * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        acc[:] = acc[:] + jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
                    dk_acc, dv_acc, *, scale, blk_q, blk_k, causal, nq):
    # grid: (B*kv_heads, k-block j, rep*q-blocks i) — innermost dim walks all
    # q blocks of every query head sharing this kv head (GQA), accumulating
    # dk/dv across the group; i % nq is the q-block position within one head.
    j, i = pl.program_id(1), pl.program_id(2)
    ni = pl.num_programs(2)
    iq = i % nq

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (not causal) or (j * blk_k <= iq * blk_q + blk_q - 1)

    @pl.when(run)
    def _block():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse, delta = lse_ref[0, 0], delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = iq * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
            k_pos = j * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG)
        p = jnp.exp(s - lse[:, None])                       # [bq, bk]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == ni - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def flash_dq_pass(q, k, v, do, lse, delta, *, scale, blk_q, blk_k, causal,
                  heads, kv_heads):
    """dq from explicit (lse, delta) — usable with a GLOBAL lse/delta, which
    is what blockwise/ring backward passes need. Shapes: q/do [B*heads,S,D],
    k/v [B*kv_heads,S,D], lse/delta [B*heads,1,S] fp32."""
    BH, S, D = q.shape
    nq, nk = pl.cdiv(S, blk_q), pl.cdiv(S, blk_k)
    qspec = pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec(
        (1, blk_k, D), lambda b, i, j: (_kv_index(b, heads, kv_heads), j, 0)
    )
    rowspec = pl.BlockSpec((1, 1, blk_q), lambda b, i, j: (b, 0, i))
    return pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k, causal=causal),
        grid=(BH, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=[qspec],
        out_shape=[_out_struct((BH, S, D), q.dtype, q, k, v, do)],
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)[0]


def flash_dkv_pass(q, k, v, do, lse, delta, *, scale, blk_q, blk_k, causal,
                   heads, kv_heads):
    """dk/dv from explicit (lse, delta); see flash_dq_pass.

    Grid over the [B*kv_heads] K/V array; k-block outer, then the inner dim
    walks rep*nq q-blocks (all query heads of the GQA group back-to-back) so
    dk/dv accumulate in VMEM scratch across the group."""
    BH, S, D = q.shape
    BKV = k.shape[0]
    rep = heads // kv_heads
    nq, nk = pl.cdiv(S, blk_q), pl.cdiv(S, blk_k)

    def _q_index(b: int, i: int) -> int:
        return (b // kv_heads) * heads + (b % kv_heads) * rep + i // nq

    qspec_t = pl.BlockSpec((1, blk_q, D), lambda b, j, i: (_q_index(b, i), i % nq, 0))
    kspec_t = pl.BlockSpec((1, blk_k, D), lambda b, j, i: (b, j, 0))
    rowspec_t = pl.BlockSpec((1, 1, blk_q), lambda b, j, i: (_q_index(b, i), 0, i % nq))
    return pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, blk_q=blk_q, blk_k=blk_k,
            causal=causal, nq=nq,
        ),
        grid=(BKV, nk, rep * nq),
        in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, rowspec_t, rowspec_t],
        out_specs=[kspec_t, kspec_t],
        out_shape=[
            _out_struct((BKV, S, D), k.dtype, q, k, v, do),
            _out_struct((BKV, S, D), v.dtype, q, k, v, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)


def _flash_bwd(res, g, *, scale, blk_q, blk_k, causal, heads, kv_heads):
    q, k, v, out, lse = res
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[:, None, :]  # [BH, 1, S]
    kw = dict(scale=scale, blk_q=blk_q, blk_k=blk_k, causal=causal,
              heads=heads, kv_heads=kv_heads)
    dq = flash_dq_pass(q, k, v, g, lse, delta, **kw)
    dk, dv = flash_dkv_pass(q, k, v, g, lse, delta, **kw)
    return dq, dk, dv


# --- public entry -------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, blk_q, blk_k, causal, heads, kv_heads):
    out, _ = _flash_fwd(q, k, v, scale=scale, blk_q=blk_q, blk_k=blk_k,
                        causal=causal, heads=heads, kv_heads=kv_heads)
    return out


def _flash_fwd_rule(q, k, v, scale, blk_q, blk_k, causal, heads, kv_heads):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _flash_fwd(q, k, v, scale=scale, blk_q=blk_q, blk_k=blk_k,
                          causal=causal, heads=heads, kv_heads=kv_heads)
    # named save point: under remat, a policy saving 'flash_res' keeps the
    # kernel's residuals (out + logsumexp) so the backward pass runs only the
    # dq/dkv kernels instead of re-running this forward kernel first
    out = checkpoint_name(out, "flash_res")
    lse = checkpoint_name(lse, "flash_res")
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, blk_q, blk_k, causal, heads, kv_heads, res, g):
    return _flash_bwd(res, g, scale=scale, blk_q=blk_q, blk_k=blk_k,
                      causal=causal, heads=heads, kv_heads=kv_heads)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    cfg=None,
    *,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    scale: float | None = None,
) -> jax.Array:
    """Causal flash attention. q/k/v: [B, S, H, head_dim] -> same shape.

    Matches the AttnFn contract of tony_tpu.models.llama; tile sizes come
    from ``cfg.flash_block_q/flash_block_k`` when a config is passed (kwargs
    win). Sequence length must be a multiple of the (possibly clipped) block
    sizes. The [B,S,H,D] -> [B*H,S,D] fold is done here; XLA fuses the
    transposes into the surrounding projections. K/V may carry fewer heads
    than Q (GQA): the kernel reads each K/V head n_heads/n_kv_heads times via
    its BlockSpec index map instead of materialising the repeat in HBM.
    """
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv:
        raise ValueError(f"n_heads {H} not a multiple of n_kv_heads {Hkv}")
    if v.shape != k.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    if block_q is None:
        block_q = getattr(cfg, "flash_block_q", None) or 512
    if block_k is None:
        block_k = getattr(cfg, "flash_block_k", None) or 1024
    blk_q = min(block_q, S)
    blk_k = min(block_k, S)
    if S % blk_q or S % blk_k:
        raise ValueError(f"seq len {S} must be a multiple of block sizes ({blk_q}, {blk_k})")
    if scale is None:
        scale = 1.0 / math.sqrt(D)

    def fold(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(B * h, S, D)

    out = _flash(fold(q), fold(k), fold(v), scale, blk_q, blk_k, causal, H, Hkv)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def sharded_flash_attention(q, k, v, cfg=None, **kwargs) -> jax.Array:
    """Mesh-aware flash attention: the model-level 'flash' hook.

    A raw pallas_call gives the SPMD partitioner no partitioning rule, so
    under a multi-device jit it would replicate the op (all-gathering global
    q/k/v onto every chip). Wrapping in shard_map over the registered default
    mesh keeps batch on dp/fsdp and heads on tp; the sequence dim stays local
    (flash needs full K/V — use attention_impl='ring' to shard sequence).
    """
    from tony_tpu.parallel.mesh import get_default_mesh, inside_manual_region
    from tony_tpu.parallel.sharding import attn_spec

    mesh = get_default_mesh()
    if mesh is None or mesh.size == 1:
        return flash_attention(q, k, v, cfg, **kwargs)
    if inside_manual_region():
        # already inside a shard_map region (a pp pipeline stage): shardy
        # cannot re-bind mesh axes in a nested manual computation, so run
        # the kernel on the region-local data and let the outer partitioner
        # own batch/heads (correct; may replicate the op across tp)
        return flash_attention(q, k, v, cfg, **kwargs)
    # GQA under tp: the heads axis is sharded over tp, so the narrower K/V
    # head dim must also divide tp — when it doesn't, fall back to expanding
    # K/V to full width in HBM (correct, just not the bandwidth-lean path).
    tp = int(mesh.shape.get("tp", 1))
    if k.shape[2] % tp:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    spec = attn_spec(mesh)  # seq_axis=None: sequence stays device-local
    return _shard_map(
        lambda a, b, c: flash_attention(a, b, c, cfg, **kwargs),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)


# explicit-residual entry for blockwise/ring composition:
# (q, k, v) -> (out, lse) with lse in [B*heads, 1, S] fp32 kernel layout
flash_fwd_pass = _flash_fwd

__all__ = [
    "flash_attention", "flash_dq_pass", "flash_dkv_pass", "flash_fwd_pass",
    "sharded_flash_attention",
]

"""GQA decode attention: one query token per row against a length-aware KV
cache, read at native ``n_kv_heads`` width.

The serving hot op (docs/SERVE.md). Training-side flash attention already
reads each K/V head ``n_heads/n_kv_heads`` times through its BlockSpec index
map instead of materialising the repeat (ops/attention.py); the decode path
in ``generate.py`` still ``jnp.repeat``ed the caches — 4x the HBM traffic
AND residency at llama3's 32:8 head ratio, on an op that is pure memory
bandwidth (one query row per request). Here queries fold to
``[B, n_kv_heads, rep, head_dim]`` and contract against the unexpanded
cache, and per-row ``lengths`` bound the attended positions so work stops at
the written prefix instead of ``max_len``.

Two interchangeable implementations (the ``fused_ce``/``grouped_mm``
pattern), dispatched on ``impl``:

- ``'scan'`` — ``lax.scan`` over KV blocks with an online softmax (the
  flash recurrence). Pure XLA: runs anywhere, is the default, and keeps the
  score transient at ``[B, Hkv, rep, block]`` instead of ``[B, H, T]``.
- ``'pallas'`` — a TPU kernel over a ``(B * n_kv_heads, T/block)`` grid.
  Per-row lengths ride as a scalar-prefetch argument; KV tiles entirely
  beyond a row's length skip their FLOPs via ``pl.when`` (the DMA win comes
  from the caller sizing the cache to the active block count — see
  serve/cache.py). Interpreter mode on CPU.

Cache layout is head-major ``[B, n_kv_heads, T, head_dim]`` (the serve
engine's block cache flattens to exactly this), so the kernel fold is a
reshape, not a transpose of the whole cache every step.

**Paged form** (``tables`` given): K/V are physical-block *pools*
``[P, n_kv_heads, block, head_dim]`` and ``tables [B, M]`` maps row ``b``'s
logical block ``j`` to a physical block id — the indirection that lets the
prefix store (serve/prefix.py) share one physical block across many rows.
The scan impl gathers each step's blocks through the table; the pallas impl
rides the table as a second scalar-prefetch argument whose values steer the
K/V BlockSpec index map (the grouped_mm tile->expert pattern), so the DMA
fetches exactly the mapped block. Rows beyond their length still skip their
FLOPs; table entries beyond a row's allocation must point at a valid id
(the engine uses the scratch block 0).

No backward: decode is inference-only. ``T`` must be a multiple of
``block`` (the block cache guarantees it); ``lengths`` must be >= 1 — the
engine always writes position ``t`` before attending over ``t + 1``
positions, so a live row's first block is never empty.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tony_tpu.ops.compat import (
    pallas_compiler_params as _CompilerParams,
    use_interpret as _use_interpret,
)

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def reference_decode_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, lengths: jax.Array,
    *, scale: float | None = None,
) -> jax.Array:
    """Repeat-expanded full-width reference (the parity oracle, and exactly
    what generate.py's ``_cached_attention`` did per decode step).

    q: [B, H, hd]; k/v: [B, Hkv, T, hd]; lengths: [B] int32 (positions
    < lengths[b] are attended). Returns [B, H, hd].

    Speculative form: q ``[B, G, H, hd]`` carries G query positions per
    row (the last real token plus G-1 draft tokens, serve/spec.py) and
    ``lengths`` counts the cache AFTER all G writes — query g of row b
    attends positions ``< lengths[b] - (G - 1) + g``, so G=1 reduces
    exactly to the one-token rule. Returns [B, G, H, hd].
    """
    if q.ndim == 4:
        G = q.shape[1]
        return jnp.stack(
            [
                reference_decode_attention(
                    q[:, g], k, v, lengths - (G - 1) + g, scale=scale
                )
                for g in range(G)
            ],
            axis=1,
        )
    B, H, hd = q.shape
    Hkv, T = k.shape[1], k.shape[2]
    rep = H // Hkv
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    s = jnp.einsum("bhd,bhkd->bhk", q, k, preferred_element_type=jnp.float32)
    valid = jnp.arange(T)[None, :] < lengths[:, None]          # [B, T]
    s = jnp.where(valid[:, None, :], s * scale, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bhkd->bhd", p, v)


# --- scan (XLA) implementation ------------------------------------------------


def _decode_scan(q, k, v, lengths, *, scale, block):
    """Online-softmax scan over KV blocks, native GQA contraction.
    q ``[B, G, H, hd]``: query g attends ``< lengths[b] - (G-1) + g``."""
    B, G, H, hd = q.shape
    Hkv, T = k.shape[1], k.shape[2]
    rep = H // Hkv
    nb = T // block
    qg = q.reshape(B, G, Hkv, rep, hd)
    goff = jnp.arange(G, dtype=jnp.int32)

    m0 = jnp.full((B, G, Hkv, rep), _NEG, jnp.float32)
    l0 = jnp.zeros((B, G, Hkv, rep), jnp.float32)
    acc0 = jnp.zeros((B, G, Hkv, rep, hd), jnp.float32)

    def body(carry, j):
        m, l, acc = carry
        kb = lax.dynamic_slice_in_dim(k, j * block, block, axis=2)
        vb = lax.dynamic_slice_in_dim(v, j * block, block, axis=2)
        s = jnp.einsum(
            "bgxrd,bxkd->bgxrk", qg, kb, preferred_element_type=jnp.float32
        ) * scale
        pos = j * block + jnp.arange(block)
        valid = pos[None, None, :] < (
            lengths[:, None, None] - (G - 1) + goff[None, :, None]
        )                                                      # [B, G, block]
        vmask = valid[:, :, None, None, :]
        s = jnp.where(vmask, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(vmask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bgxrk,bxkd->bgxrd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l, acc), None

    (m, l, acc), _ = lax.scan(
        body, (m0, l0, acc0), jnp.arange(nb, dtype=jnp.int32)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(B, G, H, hd).astype(q.dtype)


def _paged_scan(q, k, v, lengths, tables, *, scale, k_scale=None,
                v_scale=None):
    """Online-softmax scan over *logical* blocks, each row's block gathered
    through its table entry (native GQA contraction, paged pools). Given
    ``k_scale``/``v_scale`` ``[P, Hkv]`` the pools are quantized: the scale
    row is gathered right next to the block gather and the tile is
    dequantized in registers (serve/cache.py block-scaled quantization)."""
    B, G, H, hd = q.shape
    Hkv, blk = k.shape[1], k.shape[2]
    rep = H // Hkv
    nb = tables.shape[1]
    qg = q.reshape(B, G, Hkv, rep, hd)
    goff = jnp.arange(G, dtype=jnp.int32)

    m0 = jnp.full((B, G, Hkv, rep), _NEG, jnp.float32)
    l0 = jnp.zeros((B, G, Hkv, rep), jnp.float32)
    acc0 = jnp.zeros((B, G, Hkv, rep, hd), jnp.float32)

    def body(carry, j):
        m, l, acc = carry
        pid = lax.dynamic_index_in_dim(tables, j, axis=1, keepdims=False)
        kb = jnp.take(k, pid, axis=0)                          # [B, Hkv, blk, hd]
        vb = jnp.take(v, pid, axis=0)
        if k_scale is not None:
            ksc = jnp.take(k_scale, pid, axis=0)               # [B, Hkv]
            vsc = jnp.take(v_scale, pid, axis=0)
            kb = (kb.astype(jnp.float32) * ksc[..., None, None]).astype(qg.dtype)
            vb = (vb.astype(jnp.float32) * vsc[..., None, None]).astype(qg.dtype)
        s = jnp.einsum(
            "bgxrd,bxkd->bgxrk", qg, kb, preferred_element_type=jnp.float32
        ) * scale
        pos = j * blk + jnp.arange(blk)
        valid = pos[None, None, :] < (
            lengths[:, None, None] - (G - 1) + goff[None, :, None]
        )                                                      # [B, G, blk]
        vmask = valid[:, :, None, None, :]
        s = jnp.where(vmask, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(vmask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "bgxrk,bxkd->bgxrd", p.astype(vb.dtype), vb,
            preferred_element_type=jnp.float32,
        )
        return (m_new, l, acc), None

    (m, l, acc), _ = lax.scan(
        body, (m0, l0, acc0), jnp.arange(nb, dtype=jnp.int32)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(B, G, H, hd).astype(q.dtype)


# --- pallas (TPU) implementation ----------------------------------------------


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc,
                   *, scale, block, kv_heads, rep, queries):
    b, j = pl.program_id(0), pl.program_id(1)
    nb = pl.num_programs(1)
    row_len = len_ref[b // kv_heads]

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)

    # tiles entirely beyond this row's written prefix contribute nothing:
    # skip their FLOPs (their probability mass is exactly zero)
    @pl.when(j * block < row_len)
    def _block():
        q, k, v = q_ref[0], k_ref[0], v_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                      # [queries*rep, block]
        pos = j * block + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # folded row r is query g = r // rep: speculative query g may only
        # see positions < row_len - (G-1) + g (row_len counts the cache
        # AFTER all G writes; G=1 reduces to the plain < row_len rule)
        gq = lax.broadcasted_iota(jnp.int32, s.shape, 0) // rep
        valid = pos < row_len - (queries - 1) + gq
        s = jnp.where(valid, s, _NEG)
        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.where(valid, jnp.exp(s - m_new[:, None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_sc[:, 0] = l_sc[:, 0] * corr + jnp.sum(p, axis=1)
        acc[:] = acc[:] * corr[:, None] + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:, 0] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:, 0], 1e-30)
        o_ref[0] = (acc[:] / l[:, None]).astype(o_ref.dtype)


def _decode_pallas(q, k, v, lengths, *, scale, block):
    B, G, H, hd = q.shape
    Hkv, T = k.shape[1], k.shape[2]
    rep = H // Hkv
    nb = T // block
    R = G * rep
    # fold the G query positions into the tile rows: grid row b*Hkv + x
    # computes every (g, r) pair of row b's kv-head x at once, so the
    # speculative widening adds zero grid steps and zero extra K/V DMA
    qf = q.reshape(B, G, Hkv, rep, hd).transpose(0, 2, 1, 3, 4).reshape(
        B * Hkv, R, hd
    )
    kf = k.reshape(B * Hkv, T, hd)
    vf = v.reshape(B * Hkv, T, hd)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * Hkv, nb),
        in_specs=[
            pl.BlockSpec((1, R, hd), lambda b, j, ln: (b, 0, 0)),
            pl.BlockSpec((1, block, hd), lambda b, j, ln: (b, j, 0)),
            pl.BlockSpec((1, block, hd), lambda b, j, ln: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, R, hd), lambda b, j, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((R, hd), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _decode_kernel, scale=scale, block=block, kv_heads=Hkv,
            rep=rep, queries=G,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * Hkv, R, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(lengths.astype(jnp.int32), qf, kf, vf)
    return out.reshape(B, Hkv, G, rep, hd).transpose(0, 2, 1, 3, 4).reshape(
        B, G, H, hd
    )


def _paged_body(j, nb, row_len, q_ref, read_kv, o_ref, acc, m_sc, l_sc,
                *, scale, block, rep, queries):
    """Shared paged tile body: ``read_kv`` hands back this tile's (k, v) in
    the query dtype — the plain kernel reads the refs directly, the quant
    kernel dequantizes through its scale refs first."""

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, _NEG)
        l_sc[:] = jnp.zeros_like(l_sc)

    @pl.when(j * block < row_len)
    def _block():
        q = q_ref[0]
        k, v = read_kv()
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                      # [queries*rep, block]
        pos = j * block + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # same affine speculative mask as _decode_kernel: row r is query
        # g = r // rep, attending < row_len - (G-1) + g
        gq = lax.broadcasted_iota(jnp.int32, s.shape, 0) // rep
        valid = pos < row_len - (queries - 1) + gq
        s = jnp.where(valid, s, _NEG)
        m_prev = m_sc[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        p = jnp.where(valid, jnp.exp(s - m_new[:, None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_sc[:, 0] = l_sc[:, 0] * corr + jnp.sum(p, axis=1)
        acc[:] = acc[:] * corr[:, None] + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[:, 0] = m_new

    @pl.when(j == nb - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:, 0], 1e-30)
        o_ref[0] = (acc[:] / l[:, None]).astype(o_ref.dtype)


def _paged_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref, acc, m_sc,
                  l_sc, *, scale, block, kv_heads, rep, queries):
    i, j = pl.program_id(0), pl.program_id(1)
    nb = pl.num_programs(1)
    row_len = len_ref[i // kv_heads]
    _paged_body(
        j, nb, row_len, q_ref, lambda: (k_ref[0, 0], v_ref[0, 0]),
        o_ref, acc, m_sc, l_sc,
        scale=scale, block=block, rep=rep, queries=queries,
    )


def _paged_quant_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref, ksc_ref,
                        vsc_ref, o_ref, acc, m_sc, l_sc, *, scale, block,
                        kv_heads, rep, queries):
    """Quantized pools: the (1, 1) scale tiles ride BlockSpecs steered by
    the same table lookup as their K/V tiles, so the per-block-per-head
    scale arrives alongside the int8/fp8 payload and the dequant happens in
    registers — the bf16 cache never exists in HBM."""
    i, j = pl.program_id(0), pl.program_id(1)
    nb = pl.num_programs(1)
    row_len = len_ref[i // kv_heads]

    def read_kv():
        k = (k_ref[0, 0].astype(jnp.float32) * ksc_ref[0, 0]).astype(
            q_ref.dtype
        )
        v = (v_ref[0, 0].astype(jnp.float32) * vsc_ref[0, 0]).astype(
            q_ref.dtype
        )
        return k, v

    _paged_body(
        j, nb, row_len, q_ref, read_kv, o_ref, acc, m_sc, l_sc,
        scale=scale, block=block, rep=rep, queries=queries,
    )


def _paged_pallas(q, k, v, lengths, tables, *, scale, k_scale=None,
                  v_scale=None):
    """Grid (B * Hkv, M): the table rides as scalar prefetch and its values
    steer the K/V BlockSpec index map, so each tile's DMA fetches the
    physical block the row's table names (no gather materialised). With
    ``k_scale``/``v_scale`` ``[P, Hkv]`` the same table-steered index map
    carries each tile's scale scalar in as a (1, 1) block."""
    B, G, H, hd = q.shape
    Hkv, blk = k.shape[1], k.shape[2]
    rep = H // Hkv
    nb = tables.shape[1]
    R = G * rep
    quant = k_scale is not None
    qf = q.reshape(B, G, Hkv, rep, hd).transpose(0, 2, 1, 3, 4).reshape(
        B * Hkv, R, hd
    )

    def kv_spec():
        return pl.BlockSpec(
            (1, 1, blk, hd),
            lambda i, j, ln, tb, kv_heads=Hkv: (
                tb[i // kv_heads, j], i % kv_heads, 0, 0
            ),
        )

    def scale_spec():
        return pl.BlockSpec(
            (1, 1),
            lambda i, j, ln, tb, kv_heads=Hkv: (
                tb[i // kv_heads, j], i % kv_heads
            ),
        )

    in_specs = [
        pl.BlockSpec((1, R, hd), lambda i, j, ln, tb: (i, 0, 0)),
        kv_spec(),
        kv_spec(),
    ]
    operands = [qf, k, v]
    kernel = _paged_kernel
    if quant:
        in_specs += [scale_spec(), scale_spec()]
        operands += [k_scale, v_scale]
        kernel = _paged_quant_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * Hkv, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, R, hd), lambda i, j, ln, tb: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((R, hd), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
            pltpu.VMEM((R, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            kernel, scale=scale, block=blk, kv_heads=Hkv,
            rep=rep, queries=G,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * Hkv, R, hd), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=_use_interpret(),
    )(lengths.astype(jnp.int32), tables.astype(jnp.int32), *operands)
    return out.reshape(B, Hkv, G, rep, hd).transpose(0, 2, 1, 3, 4).reshape(
        B, G, H, hd
    )


# --- public entry -------------------------------------------------------------


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lengths: jax.Array,
    *,
    tables: jax.Array | None = None,
    impl: str = "scan",
    block: int = 128,
    scale: float | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """One decode step of attention at native GQA width.

    Contiguous form (``tables`` is None): q: [B, H, head_dim] (this step's
    query rows); k/v: [B, Hkv, T, head_dim] head-major caches (T = the
    active capacity, a multiple of ``block``); lengths: [B] int32 — row b
    attends positions ``[0, lengths[b])``. Returns [B, H, head_dim].

    Paged form (``tables [B, M]`` given): k/v are physical-block pools
    ``[P, Hkv, block, head_dim]`` and row b's logical block j lives at
    ``tables[b, j]`` — the serve engine's copy-on-write sharing substrate
    (serve/cache.py, serve/prefix.py). Entries beyond a row's length must
    still be valid pool ids (the engine points them at the scratch block).

    Speculative form: q ``[B, G, H, head_dim]`` verifies G query positions
    per row in one call (serve/spec.py) — ``lengths`` counts the cache
    AFTER all G writes, and query g of row b attends positions
    ``< lengths[b] - (G - 1) + g`` (for G=1 exactly the one-token rule).
    Returns [B, G, H, head_dim]. Works in both contiguous and paged form;
    both impls fold the G positions into the existing tile rows, so the
    per-step K/V traffic does not grow with G.

    Quantized paged form (``k_scale``/``v_scale [P, Hkv]`` given, paged
    only): the pools hold int8 or fp8 payloads quantized per physical
    block per kv-head (serve/cache.py); both impls dequantize each tile
    inline — scan gathers the scale row next to the block gather, pallas
    threads the scale pools through the same table-steered index map.
    """
    squeeze = q.ndim == 3
    if squeeze:
        q = q[:, None]
    B, G, H, hd = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    if impl not in ("scan", "pallas"):
        raise ValueError(f"unknown decode impl {impl!r} (expected scan | pallas)")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if k_scale is not None and tables is None:
        raise ValueError(
            "quantized decode_attention requires the paged form (tables)"
        )
    if tables is not None:
        if k.shape != v.shape or k.shape[3] != hd:
            raise ValueError(
                f"paged decode_attention shapes q={q.shape} k={k.shape} "
                f"v={v.shape}"
            )
        if tables.shape[0] != B:
            raise ValueError(
                f"tables rows {tables.shape[0]} != batch {B}"
            )
        if H % k.shape[1]:
            raise ValueError(
                f"n_heads {H} not a multiple of n_kv_heads {k.shape[1]}"
            )
        if impl == "pallas":
            out = _paged_pallas(
                q, k, v, lengths, tables, scale=scale,
                k_scale=k_scale, v_scale=v_scale,
            )
        else:
            out = _paged_scan(
                q, k, v, lengths, tables, scale=scale,
                k_scale=k_scale, v_scale=v_scale,
            )
        return out[:, 0] if squeeze else out
    if k.shape != v.shape or k.shape[0] != B or k.shape[3] != hd:
        raise ValueError(f"decode_attention shapes q={q.shape} k={k.shape} v={v.shape}")
    Hkv, T = k.shape[1], k.shape[2]
    if H % Hkv:
        raise ValueError(f"n_heads {H} not a multiple of n_kv_heads {Hkv}")
    blk = min(block, T)
    if T % blk:
        raise ValueError(f"cache length {T} must be a multiple of block {blk}")
    if impl == "pallas":
        out = _decode_pallas(q, k, v, lengths, scale=scale, block=blk)
    else:
        out = _decode_scan(q, k, v, lengths, scale=scale, block=blk)
    return out[:, 0] if squeeze else out


__all__ = ["decode_attention", "reference_decode_attention"]

"""KV-cache decoding + generation for the Llama family.

The reference delegates inference entirely (it launches whatever script the
user brings); here generation is part of the model library. TPU-first
choices: the cache is a static-shape ring of [L, B, max_len, H_kv, hd]
buffers updated with dynamic_update_slice (no growing shapes under jit — one
compile for prefill, one for decode), attention masks by absolute position,
and the whole decode loop is a single jitted lax.scan with donated cache
buffers (in-place HBM updates).
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from tony_tpu.models.llama import LlamaConfig, Params, rms_norm, rope_table, apply_rope


class KVCache(NamedTuple):
    """Per-layer stacked K/V buffers [L, B, max_len, n_kv_heads, head_dim]."""

    k: jax.Array
    v: jax.Array

    @classmethod
    def create(cls, cfg: LlamaConfig, batch: int, max_len: int = 0) -> "KVCache":
        shape = (
            cfg.n_layers,
            batch,
            max_len or cfg.max_seq_len,
            cfg.n_kv_heads,
            cfg.head_dim,
        )
        return cls(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _cached_attention(q, k_cache, v_cache, q_pos, cfg: LlamaConfig):
    """q: [B,S,H,hd]; caches [B,max_len,Hkv,hd]; q_pos: [S] absolute."""
    rep = cfg.n_heads // cfg.n_kv_heads
    if rep > 1:
        k_cache = jnp.repeat(k_cache, rep, axis=2)
        v_cache = jnp.repeat(v_cache, rep, axis=2)
    scale = 1.0 / math.sqrt(cfg.head_dim)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    k_pos = jnp.arange(k_cache.shape[1])
    mask = q_pos[:, None] >= k_pos[None, :]  # causal over absolute positions
    s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v_cache)


def forward_with_cache(
    params: Params,
    tokens: jax.Array,
    cache: KVCache,
    start_pos: jax.Array,
    cfg: LlamaConfig,
    last_only: bool = False,
) -> tuple[jax.Array, KVCache]:
    """tokens [B,S] starting at absolute position start_pos (traced scalar).

    Returns (logits [B,S,vocab] f32, updated cache). Used for both prefill
    (S = prompt length) and decode (S = 1) — same trace, two compiles.

    ``last_only`` (static) projects only the final position through
    ``lm_head``, returning logits [B,1,vocab]: prefill needs exactly the
    last position to sample from, and the full projection would build a
    [B,S,V] fp32 tensor (at 7B shapes, ~0.5GB for a 2k prompt) just to
    discard all but one row.
    """
    B, S = tokens.shape
    x = params["tok_emb"][tokens]
    half = cfg.head_dim // 2
    freqs = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    q_pos = start_pos + jnp.arange(S)
    angles = q_pos.astype(jnp.float32)[:, None] * freqs[None, :]
    cos, sin = jnp.cos(angles), jnp.sin(angles)

    def block(x, layer):
        lp, k_cache, v_cache = layer
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        hd = cfg.head_dim
        q = (h @ lp["wq"]).reshape(B, S, cfg.n_heads, hd)
        k = (h @ lp["wk"]).reshape(B, S, cfg.n_kv_heads, hd)
        v = (h @ lp["wv"]).reshape(B, S, cfg.n_kv_heads, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_cache = lax.dynamic_update_slice(k_cache, k, (0, start_pos, 0, 0))
        v_cache = lax.dynamic_update_slice(v_cache, v, (0, start_pos, 0, 0))
        attn = _cached_attention(q, k_cache, v_cache, q_pos, cfg)
        x = x + attn.reshape(B, S, cfg.n_heads * hd) @ lp["wo"]
        h2 = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
        x = x + (jax.nn.silu(h2 @ lp["w1"]) * (h2 @ lp["w3"])) @ lp["w2"]
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(block, x, (params["layers"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if last_only:
        x = x[:, -1:]
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, KVCache(new_k, new_v)


def generate(
    params: Params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    eos_id: int | None = None,
    rng: jax.Array | None = None,
    max_len: int = 0,
) -> jax.Array:
    """Autoregressive generation. prompt [B,P] -> [B, P+max_new_tokens].

    temperature 0 = greedy; otherwise softmax sampling, optionally top-k
    and/or nucleus (top-p) truncated. ``eos_id`` makes finished rows stick
    at EOS (static shapes: the scan always runs max_new_tokens steps; rows
    that hit EOS keep emitting it). The decode loop is one jitted lax.scan.
    """
    B, P = prompt.shape
    total = P + max_new_tokens
    cache = KVCache.create(cfg, B, max_len or max(total, 1))
    if rng is None:
        rng = jax.random.key(0)

    # prefill projects only the last position through lm_head (the rest of
    # the prompt's logits would be discarded by the [:, -1] below anyway)
    prefill = jax.jit(partial(forward_with_cache, cfg=cfg, last_only=True))
    logits, cache = prefill(params, prompt, cache, jnp.int32(0))
    next_rng, rng = jax.random.split(rng)
    last = _sample(logits[:, -1], temperature, top_k, top_p, next_rng)
    done0 = (
        last == eos_id if eos_id is not None else jnp.zeros((B,), bool)
    )

    def step(carry, rng_step):
        cache, tok, pos, done = carry
        logits, cache = forward_with_cache(
            params, tok[:, None], cache, pos, cfg, last_only=True
        )
        nxt = _sample(logits[:, -1], temperature, top_k, top_p, rng_step)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, pos + 1, done), tok

    # scan emits each step's *input* token, so ys = [last, nxt_1, ...,
    # nxt_{T-1}] — exactly the max_new_tokens generated tokens in order.
    steps_rng = jax.random.split(rng, max_new_tokens)
    _, toks = jax.jit(partial(lax.scan, step))(
        (cache, last, jnp.int32(P), done0), steps_rng
    )
    generated = jnp.moveaxis(toks, 0, 1)  # [B, max_new_tokens]
    return jnp.concatenate([prompt, generated], axis=1)


def _sample(logits: jax.Array, temperature: float, top_k: int, top_p: float,
            rng: jax.Array) -> jax.Array:
    """logits [B,V] -> token ids [B]."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0 or top_p > 0.0:
        # one descending sort serves both truncations (V log V per decode
        # step is the dominant cost of sampling at real vocab sizes)
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        if top_k > 0:
            kth = sorted_logits[:, top_k - 1][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
            sorted_logits = jnp.where(
                sorted_logits < kth, -jnp.inf, sorted_logits
            )
        if top_p > 0.0:
            # nucleus: keep the smallest prefix of the sorted distribution
            # whose cumulative probability reaches top_p (the top token
            # always stays)
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = (cum - probs) < top_p
            # the smallest kept logit per row is the admission threshold
            cutoff = jnp.min(
                jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1
            )[:, None]
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


__all__ = ["KVCache", "forward_with_cache", "generate"]

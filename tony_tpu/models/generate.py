"""KV-cache decoding + generation for the Llama family.

The reference delegates inference entirely (it launches whatever script the
user brings); here generation is part of the model library. TPU-first
choices: the cache is a static-shape ring of [L, B, max_len, H_kv, hd]
buffers updated with dynamic_update_slice (no growing shapes under jit — one
compile for prefill, one for decode), and attention masks by absolute
position.

``generate()`` is a thin convenience wrapper over the serving engine
(tony_tpu.serve.engine): each prompt row becomes one request into a
slot-batched continuous-decoding loop, so the one-off API and the serving
path share one decode step (native-GQA block-cache attention, sort-free
sampling) and parity between them is a test, not a hope (tests/test_serve.py).

Sampling is sort-free: ``lax.top_k`` over a bounded slice replaces the full
``V log V`` descending sort per decode step; nucleus (top-p) truncation runs
over the sorted top-k slice only (when only top-p is set, a bounded default
k — ``DEFAULT_NUCLEUS_K`` — caps the slice; at real vocab sizes the mass
beyond the top 64 logits is negligible, and for V <= k the semantics are
exact).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tony_tpu.models.llama import LlamaConfig, Params, rms_norm, rope_freqs, apply_rope

# bounded top-k slice used for nucleus truncation when no top_k was given:
# the candidate set for top-p sampling (big enough that the excluded tail
# carries negligible probability mass; exact whenever vocab <= this)
DEFAULT_NUCLEUS_K = 64


class KVCache(NamedTuple):
    """Per-layer stacked K/V buffers [L, B, max_len, n_kv_heads, head_dim]."""

    k: jax.Array
    v: jax.Array

    @classmethod
    def create(cls, cfg: LlamaConfig, batch: int, max_len: int = 0) -> "KVCache":
        shape = (
            cfg.n_layers,
            batch,
            max_len or cfg.max_seq_len,
            cfg.n_kv_heads,
            cfg.head_dim,
        )
        return cls(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _cached_attention(q, k_cache, v_cache, q_pos, cfg: LlamaConfig):
    """q: [B,S,H,hd]; caches [B,max_len,Hkv,hd]; q_pos: [S] absolute."""
    rep = cfg.n_heads // cfg.n_kv_heads
    if rep > 1:
        k_cache = jnp.repeat(k_cache, rep, axis=2)
        v_cache = jnp.repeat(v_cache, rep, axis=2)
    scale = 1.0 / math.sqrt(cfg.head_dim)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    k_pos = jnp.arange(k_cache.shape[1])
    mask = q_pos[:, None] >= k_pos[None, :]  # causal over absolute positions
    s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v_cache)


def forward_with_cache(
    params: Params,
    tokens: jax.Array,
    cache: KVCache,
    start_pos: jax.Array,
    cfg: LlamaConfig,
    last_only: bool = False,
    last_index: jax.Array | None = None,
) -> tuple[jax.Array, KVCache]:
    """tokens [B,S] starting at absolute position start_pos (traced scalar).

    Returns (logits [B,S,vocab] f32, updated cache). Used for both prefill
    (S = prompt length) and decode (S = 1) — same trace, two compiles.

    ``last_only`` (static) projects only the final position through
    ``lm_head``, returning logits [B,1,vocab]: prefill needs exactly the
    last position to sample from, and the full projection would build a
    [B,S,V] fp32 tensor (at 7B shapes, ~0.5GB for a 2k prompt) just to
    discard all but one row. ``last_index`` (traced scalar) generalises it
    to an arbitrary position — the engine's bucketed prefill pads prompts
    up to a bucket length and needs the logits at the *prompt's* last
    position, not the bucket's.
    """
    B, S = tokens.shape
    x = params["tok_emb"][tokens]
    freqs = rope_freqs(cfg)
    q_pos = start_pos + jnp.arange(S)
    angles = q_pos.astype(jnp.float32)[:, None] * freqs[None, :]
    cos, sin = jnp.cos(angles), jnp.sin(angles)

    def block(x, layer):
        lp, k_cache, v_cache = layer
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        hd = cfg.head_dim
        q = (h @ lp["wq"]).reshape(B, S, cfg.n_heads, hd)
        k = (h @ lp["wk"]).reshape(B, S, cfg.n_kv_heads, hd)
        v = (h @ lp["wv"]).reshape(B, S, cfg.n_kv_heads, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_cache = lax.dynamic_update_slice(k_cache, k, (0, start_pos, 0, 0))
        v_cache = lax.dynamic_update_slice(v_cache, v, (0, start_pos, 0, 0))
        attn = _cached_attention(q, k_cache, v_cache, q_pos, cfg)
        x = x + attn.reshape(B, S, cfg.n_heads * hd) @ lp["wo"]
        h2 = rms_norm(x, lp["ffn_norm"], cfg.norm_eps)
        x = x + (jax.nn.silu(h2 @ lp["w1"]) * (h2 @ lp["w3"])) @ lp["w2"]
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(block, x, (params["layers"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if last_index is not None:
        x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
    elif last_only:
        x = x[:, -1:]
    logits = (x @ params["lm_head"]).astype(jnp.float32)
    return logits, KVCache(new_k, new_v)


def generate(
    params: Params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    eos_id: int | None = None,
    rng: jax.Array | None = None,
    max_len: int = 0,
    max_top_k: int = 0,
    serve: dict | None = None,
) -> jax.Array:
    """Autoregressive generation. prompt [B,P] -> [B, P+max_new_tokens].

    temperature 0 = greedy; otherwise softmax sampling, optionally top-k
    and/or nucleus (top-p) truncated. ``eos_id`` makes finished rows stick
    at EOS (the output always has max_new_tokens generated positions; rows
    that hit EOS pad with it).

    Implemented as B requests into the serving engine (one slot per row,
    prefill bucket = the exact prompt length, same jitted decode step the
    server runs). Each row gets its own rng stream derived by
    ``jax.random.split(rng, B)`` — row i's tokens depend only on row i's
    key, so the same row submitted alone or in a batch samples identically.

    ``max_top_k`` widens the sampler's bounded candidate slice (default
    ``max(top_k, DEFAULT_NUCLEUS_K)``): top-p-only sampling truncates to
    the top ``max_top_k`` logits before the nucleus cut, so callers who
    need a wider nucleus than the top-64 tail raise it here.

    ``serve`` overrides ServeConfig fields on the underlying engine (e.g.
    ``dict(quant_kv="int8", quant_weights=True)``). This is how quantized
    serving stays a TESTABLE parity surface: a quantized engine can never
    be token-exact against a bf16 reference, but generate() with the same
    overrides runs the identical quantized step — so engine-vs-generate
    parity remains exact equality, quantization and all.
    """
    from tony_tpu.serve.engine import Engine, Request, ServeConfig

    B, P = prompt.shape
    if max_new_tokens <= 0:
        return prompt
    total = P + max_new_tokens
    if rng is None:
        rng = jax.random.key(0)
    keys = jax.random.split(rng, B)

    sv = dict(
        slots=B,
        max_len=max_len or max(total, 1),
        prefill_buckets=(P,),
        max_top_k=max(top_k, max_top_k, DEFAULT_NUCLEUS_K),
    )
    sv.update(serve or {})
    engine = Engine(params, cfg, ServeConfig(**sv))
    prompt_np = np.asarray(prompt)
    ids = [
        engine.submit(Request(
            prompt=prompt_np[i],
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            eos_id=eos_id,
            rng=keys[i],
        ))
        for i in range(B)
    ]
    completions = engine.run()
    rows = []
    for i, rid in enumerate(ids):
        toks = list(completions[rid].tokens)
        if len(toks) < max_new_tokens:  # finished at EOS: stick at it
            toks += [eos_id] * (max_new_tokens - len(toks))
        rows.append(np.concatenate([prompt_np[i], np.asarray(toks, np.int32)]))
    return jnp.asarray(np.stack(rows), jnp.int32)


# --- sampling -----------------------------------------------------------------


def _truncated_logits(logits: jax.Array, top_k: int, top_p: float) -> jax.Array:
    """[B,V] logits -> [B,V] with everything outside the top-k / nucleus set
    at -inf. Sort-free: one ``lax.top_k`` over a bounded slice (k, or
    DEFAULT_NUCLEUS_K when only top-p is set) replaces the full-vocab
    descending sort; the nucleus cumsum runs over that slice only.

    This static-parameter form is the draw-for-draw parity surface against
    the legacy sort-based sampler (tests/test_generate.py); the engine's
    per-row array-parameter twin lives in :func:`sample_tokens` — keep
    their truncation semantics in lockstep."""
    V = logits.shape[-1]
    k = top_k if top_k > 0 else DEFAULT_NUCLEUS_K
    k = min(k, V)
    vals, idx = lax.top_k(logits, k)  # [B,k] descending
    if top_p > 0.0:
        # nucleus: keep the smallest prefix of the sorted slice whose
        # cumulative probability reaches top_p (the top token always stays)
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p
        vals = jnp.where(keep, vals, -jnp.inf)
    out = jnp.full_like(logits, -jnp.inf)
    return out.at[jnp.arange(logits.shape[0])[:, None], idx].set(vals)


def _sample(logits: jax.Array, temperature: float, top_k: int, top_p: float,
            rng: jax.Array) -> jax.Array:
    """logits [B,V] -> token ids [B] (static sampling params)."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0 or top_p > 0.0:
        logits = _truncated_logits(logits, top_k, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_tokens(
    logits: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
    rngs: jax.Array,
    *,
    max_k: int = DEFAULT_NUCLEUS_K,
) -> jax.Array:
    """Per-row sampling for the decode engine: logits [N,V], per-row
    temperature/top_k/top_p arrays [N], per-row rng keys [N] -> tokens [N].

    Rows with temperature <= 0 are greedy; top_k is clamped to the static
    ``max_k`` slice (0 = no top-k: the slice bound still applies when that
    row also sets top_p). Same truncation semantics as :func:`_sample`,
    vectorised over heterogeneous requests sharing one decode step.
    """
    N, V = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    k = min(max_k, V)
    vals, idx = lax.top_k(scaled, k)  # [N,k] descending
    eff_k = jnp.where(top_k > 0, jnp.minimum(top_k, k), k)
    keep = jnp.arange(k)[None, :] < eff_k[:, None]
    vals = jnp.where(keep, vals, -jnp.inf)
    probs = jax.nn.softmax(vals, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = jnp.where(
        top_p[:, None] > 0.0, (cum - probs) < top_p[:, None], True
    )
    vals = jnp.where(keep & keep_p, vals, -jnp.inf)
    truncate = (top_k > 0) | (top_p > 0.0)
    masked = jnp.full_like(scaled, -jnp.inf).at[
        jnp.arange(N)[:, None], idx
    ].set(vals)
    masked = jnp.where(truncate[:, None], masked, scaled)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row)
    )(rngs, masked).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


__all__ = [
    "DEFAULT_NUCLEUS_K", "KVCache", "forward_with_cache", "generate",
    "sample_tokens",
]

"""Checkpoint conversion: HuggingFace/torch Llama weights -> tony-tpu pytree.

The reference orchestrates user scripts and never touches weights; a
migration story needs one. This maps a HuggingFace `LlamaForCausalLM`
state_dict (torch tensors or numpy arrays, e.g. `torch.load`-ed from local
disk — this environment has no network) onto the stacked-per-layer pytree
`tony_tpu.models.llama.init_params` produces, transposing torch's
[out, in] Linear layout to our [in, out] matmul layout. Rotary needs no
re-permutation: our apply_rope uses the same half-split (rotate_half)
convention HF checkpoints are stored in — logits match transformers'
LlamaForCausalLM to float tolerance (tests/test_convert.py).

    state = transformers.LlamaForCausalLM.from_pretrained(path).state_dict()
    params = from_hf_state_dict(state, cfg)   # (or safetensors tensors)
    logits = forward(params, tokens, cfg)

Meta's original `consolidated.*.pth` shards use different key names AND the
interleaved rotary layout — convert those to HF format first (the
`transformers` conversion script); only the HF layout is handled here.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from tony_tpu.models.llama import LlamaConfig, Params


def _to_np(x: Any) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor without importing torch
        x = x.detach().cpu().float().numpy()
    return np.asarray(x)


def from_hf_state_dict(
    state: Mapping[str, Any], cfg: LlamaConfig, *, strict: bool = True
) -> Params:
    """Build the model pytree from a HF `LlamaForCausalLM` state_dict.

    ``strict`` verifies every expected key exists and shapes agree (clear
    errors beat silent garbage weights).
    """
    if cfg.is_moe:
        raise NotImplementedError(
            "HF conversion covers dense Llama configs; MoE trees (router/"
            "per-expert ffn) have no HF Llama layout to map from"
        )
    sd = {k.removeprefix("model."): v for k, v in state.items()}
    L, d = cfg.n_layers, cfg.dim
    dtype = cfg.dtype

    def get(key: str, shape: tuple[int, ...]) -> np.ndarray:
        if key not in sd:
            raise KeyError(f"missing weight {key!r} (have {len(sd)} keys)")
        w = _to_np(sd[key])
        if strict and tuple(w.shape) != shape:
            raise ValueError(f"{key}: expected shape {shape}, got {tuple(w.shape)}")
        return w

    # NOTE on rotary: HF stores q/k projections in its half-split
    # (rotate_half) convention — which is exactly what our apply_rope
    # implements, so q/k need no permutation (only the original Meta
    # release's interleaved-pair layout would).
    def stack(fmt: str, shape: tuple[int, ...], *, transpose: bool = True) -> jnp.ndarray:
        per = []
        for i in range(L):
            w = get(fmt.format(i=i), shape)
            per.append(w.T if transpose else w)  # torch Linear is [out, in]
        return jnp.asarray(np.stack(per), dtype)

    nq = cfg.n_heads * cfg.head_dim
    nkv = cfg.n_kv_heads * cfg.head_dim
    F = cfg.ffn_dim
    emb = get("embed_tokens.weight", (cfg.vocab_size, d))
    params: Params = {
        "tok_emb": jnp.asarray(emb, dtype),
        "layers": {
            "attn_norm": stack(
                "layers.{i}.input_layernorm.weight", (d,), transpose=False
            ),
            "wq": stack("layers.{i}.self_attn.q_proj.weight", (nq, d)),
            "wk": stack("layers.{i}.self_attn.k_proj.weight", (nkv, d)),
            "wv": stack("layers.{i}.self_attn.v_proj.weight", (nkv, d)),
            "wo": stack("layers.{i}.self_attn.o_proj.weight", (d, nq)),
            "ffn_norm": stack(
                "layers.{i}.post_attention_layernorm.weight", (d,),
                transpose=False,
            ),
            "w1": stack("layers.{i}.mlp.gate_proj.weight", (F, d)),
            "w3": stack("layers.{i}.mlp.up_proj.weight", (F, d)),
            "w2": stack("layers.{i}.mlp.down_proj.weight", (d, F)),
        },
        "final_norm": jnp.asarray(get("norm.weight", (d,)), dtype),
        # tie_word_embeddings checkpoints (Llama 3.2 1B/3B, TinyLlama) omit
        # lm_head.weight from the state_dict; the tied head IS the embedding
        "lm_head": jnp.asarray(
            get("lm_head.weight", (cfg.vocab_size, d)).T
            if "lm_head.weight" in sd
            else emb.T,
            dtype,
        ),
    }
    return params


def to_hf_state_dict(params: Params, cfg: LlamaConfig) -> dict[str, np.ndarray]:
    """Inverse mapping (numpy arrays, HF key layout) — lets weights trained
    here be loaded back into `transformers` for eval/serving parity checks."""
    if cfg.is_moe:
        raise NotImplementedError(
            "HF conversion covers dense Llama configs; MoE expert stacks "
            "would silently axis-scramble under this dense mapping"
        )
    out: dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = np.asarray(
        params["tok_emb"], dtype=np.float32
    )
    out["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T
    out["model.norm.weight"] = np.asarray(params["final_norm"], np.float32)
    lp = params["layers"]

    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}"
        get = lambda name: np.asarray(lp[name][i], np.float32)  # noqa: E731
        out[f"{pre}.input_layernorm.weight"] = get("attn_norm")
        out[f"{pre}.post_attention_layernorm.weight"] = get("ffn_norm")
        out[f"{pre}.self_attn.q_proj.weight"] = get("wq").T
        out[f"{pre}.self_attn.k_proj.weight"] = get("wk").T
        out[f"{pre}.self_attn.v_proj.weight"] = get("wv").T
        out[f"{pre}.self_attn.o_proj.weight"] = get("wo").T
        out[f"{pre}.mlp.gate_proj.weight"] = get("w1").T
        out[f"{pre}.mlp.up_proj.weight"] = get("w3").T
        out[f"{pre}.mlp.down_proj.weight"] = get("w2").T
    return out


__all__ = ["from_hf_state_dict", "to_hf_state_dict"]

"""Model families: Llama decoder transformers + KV-cache generation."""

from tony_tpu.models.generate import KVCache, forward_with_cache, generate
from tony_tpu.models.llama import LlamaConfig, forward, init_params, loss_fn

__all__ = [
    "KVCache",
    "LlamaConfig",
    "forward",
    "forward_with_cache",
    "generate",
    "init_params",
    "loss_fn",
]

"""Llama-2-family decoder transformer, TPU-first.

The reference framework contains no models (TonY delegates training code to
user scripts; SURVEY.md section 0). This module is the training-side library
the rebuild adds, designed for the MXU/XLA rather than translated from torch:

- parameters are a plain pytree of stacked per-layer arrays; the layer stack
  runs under ``lax.scan`` (one trace, one compile, pipeline-ready layout);
- compute dtype bfloat16 end-to-end, softmax/norm statistics and the final
  loss in float32;
- optional ``jax.checkpoint`` rematerialisation per layer (HBM for FLOPs);
- every parameter carries logical axis names (see
  tony_tpu.parallel.sharding.DEFAULT_RULES) so the same code runs single-chip,
  FSDP, Megatron-TP, or sequence-parallel purely by mesh choice;
- attention is pluggable: plain fused attention here, Pallas flash attention
  and ring attention (context parallelism) from tony_tpu.ops/.parallel.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

Params = dict[str, Any]
AttnFn = Callable[..., jax.Array]  # (q, k, v, cfg) -> out


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # what the per-layer jax.checkpoint keeps: 'nothing' recomputes the whole
    # layer in bwd (min HBM); 'dots' saves matmul outputs with no batch dims
    # (nothing in practice here — all our dots carry batch); 'checkpoint_dots'
    # saves every matmul output (min recompute, max HBM)
    remat_policy: str = "nothing"
    # 'dot' = fused plain attention; 'flash' = pallas kernel (tony_tpu.ops);
    # 'ring' = sequence-parallel ring attention (tony_tpu.parallel);
    # 'ring_flash' = ring over sp with the pallas kernel per chunk (the
    # long-context production path); 'ulysses' = all-to-all head sharding.
    attention_impl: str = "dot"
    # pallas flash kernel tile sizes (attention_impl='flash'); clipped to S.
    # 1024/1024 measured fastest on v5e at S=2048 (43.7 -> 53.2 TF/s fwd vs
    # the old 512/1024)
    flash_block_q: int = 1024
    flash_block_k: int = 1024
    # lax.scan unroll factor for the layer stack (trades compile time /
    # code size for cross-layer scheduling freedom)
    scan_unroll: int = 1
    # MoE variant (n_experts > 0): every layer's FFN becomes a GShard-style
    # top-k expert block (tony_tpu.parallel.moe) with the expert dim on the
    # mesh's ``ep`` axis; aux load-balancing loss is added to the objective.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # 'grouped' (dropless sorted grouped GEMM — no capacity, no drops; the
    # default since round 20's judged `grouped_vs_gather` bench gate held)
    # | 'gather' / 'einsum' (fixed-capacity slots, overflow tokens dropped
    # — one knob away; see parallel.moe and docs/PERF.md "Grouped MoE")
    moe_dispatch: str = "grouped"
    # moe_dispatch='grouped': row-tile of the grouped GEMM (each expert's
    # ragged token group pads up to a multiple of this)
    moe_group_block: int = 128
    # moe_dispatch='grouped': 'scan' (pure-XLA, runs anywhere — default) |
    # 'pallas' (TPU kernel, tony_tpu.ops.grouped_mm)
    moe_gmm_impl: str = "scan"
    # moe_dispatch='grouped' on an ep mesh: 'off' = single blocking post-
    # FFN combine psum (default); 'scan' | 'pallas' = decomposed per-token-
    # chunk partial combines so expert compute overlaps combine traffic
    # (tony_tpu.ops.moe_overlap, docs/PERF.md "Round 20"). Declines to the
    # single psum wherever the chunk split doesn't apply.
    moe_overlap_impl: str = "off"
    # moe_overlap_impl != 'off': tokens per combine chunk per shard (0 =
    # auto split; size measured captures via moe_overlap.chunk_tokens_from_report)
    moe_overlap_chunk: int = 0
    moe_aux_coef: float = 0.01
    # loss head (tony_tpu.ops.fused_ce): 'scan' = fused chunked CE via
    # lax.scan (default — never materialises [B,S,V] logits, runs anywhere);
    # 'pallas' = fused TPU kernel (VMEM accumulators over the vocab grid);
    # 'dense' = legacy full-logits logsumexp reference.
    ce_impl: str = "scan"
    # vocab columns per chunk for ce_impl='scan' (the forward/backward
    # transient is one [B*S, ce_vocab_chunk] fp32 block)
    ce_vocab_chunk: int = 4096
    # pallas CE kernel tile sizes (rows x vocab); clipped to B*S and V
    ce_block_n: int = 512
    ce_block_v: int = 512
    # comm/compute overlap for the fsdp-sharded trunk matmuls
    # (tony_tpu.ops.overlap): '' = GSPMD's blocking weight all-gathers
    # (default); 'scan' = decomposed ppermute-ring all-gather-matmul,
    # pure-XLA per-chunk inner; 'pallas' = same ring with the TPU tiled
    # matmul kernel per chunk. Falls back to the plain matmul wherever the
    # decomposition doesn't apply (no fsdp ring, manual region, odd shapes).
    overlap_impl: str = ""

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def n_params(self) -> int:
        """Exact parameter count (embeddings included, tied=False)."""
        d, h = self.dim, self.head_dim
        attn = d * self.n_heads * h + 2 * d * self.n_kv_heads * h + self.n_heads * h * d
        if self.is_moe:
            ffn = d * self.n_experts + 3 * self.n_experts * d * self.ffn_dim
        else:
            ffn = 3 * d * self.ffn_dim
        norms = 2 * d
        per_layer = attn + ffn + norms
        return self.vocab_size * d * 2 + self.n_layers * per_layer + d

    @property
    def n_active_params(self) -> int:
        """Parameters touched per token (MoE: only top_k experts fire) —
        the right N for 6*N FLOPs accounting."""
        if not self.is_moe:
            return self.n_params
        inactive = 3 * (self.n_experts - self.moe_top_k) * self.dim * self.ffn_dim
        return self.n_params - self.n_layers * inactive

    # --- presets -----------------------------------------------------------

    @classmethod
    def llama2_7b(cls, **kw: Any) -> "LlamaConfig":
        return cls(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=32,
            ffn_dim=11008, max_seq_len=4096, **kw,
        )

    @classmethod
    def llama2_13b(cls, **kw: Any) -> "LlamaConfig":
        return cls(
            vocab_size=32000, dim=5120, n_layers=40, n_heads=40, n_kv_heads=40,
            ffn_dim=13824, max_seq_len=4096, **kw,
        )

    @classmethod
    def llama3_8b(cls, **kw: Any) -> "LlamaConfig":
        return cls(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
            ffn_dim=14336, max_seq_len=8192, rope_theta=500000.0, **kw,
        )

    @classmethod
    def bench_410m(cls, **kw: Any) -> "LlamaConfig":
        """~410M-param config that trains comfortably on one v5e chip."""
        return cls(
            vocab_size=32000, dim=1024, n_layers=24, n_heads=16, n_kv_heads=16,
            ffn_dim=2816, max_seq_len=2048, **kw,
        )

    @classmethod
    def bench_1b4(cls, **kw: Any) -> "LlamaConfig":
        """~1.35B-param config: the single-chip (v5e 16GB) benchmark model.

        Large enough that the matmuls fill the MXU (52% MFU vs 37% for the
        410M config at the same batch), small enough that params + AdamW
        state + remat activations fit one chip's HBM."""
        return cls(
            vocab_size=32000, dim=2048, n_layers=24, n_heads=16, n_kv_heads=16,
            ffn_dim=5504, max_seq_len=2048, **kw,
        )

    @classmethod
    def tiny(cls, **kw: Any) -> "LlamaConfig":
        """Test-size config (CPU-fast)."""
        kw.setdefault("dtype", jnp.float32)
        kw.setdefault("remat", False)
        return cls(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            ffn_dim=128, max_seq_len=64, **kw,
        )

    @classmethod
    def tiny_moe(cls, **kw: Any) -> "LlamaConfig":
        """Test-size MoE config (CPU-fast, 4 experts top-2)."""
        kw.setdefault("n_experts", 4)
        return cls.tiny(**kw)

    @classmethod
    def bench_moe(cls, **kw: Any) -> "LlamaConfig":
        """Single-chip MoE benchmark: 8 experts top-2 on the 410M trunk
        (~2.1B total params, ~700M active)."""
        kw.setdefault("n_experts", 8)
        return cls.bench_410m(**kw)


# --- parameter tree -----------------------------------------------------------


def logical_axes(cfg: LlamaConfig) -> Params:
    """Pytree (matching init_params) of logical axis-name tuples.

    Sharding follows the Megatron+FSDP recipe: wide dims (heads/ffn/vocab) on
    ``tp``, model dim on ``fsdp``; the leading stacked-layer dim is never
    sharded. tony_tpu.parallel.sharding turns these into NamedShardings.
    """
    if cfg.is_moe:
        ffn_axes = {
            "router": ("layers", "embed", "expert"),
            "w1": ("layers", "expert", "embed", "ffn"),
            "w3": ("layers", "expert", "embed", "ffn"),
            "w2": ("layers", "expert", "ffn", "embed"),
        }
    else:
        ffn_axes = {
            "w1": ("layers", "embed", "ffn"),
            "w3": ("layers", "embed", "ffn"),
            "w2": ("layers", "ffn", "embed"),
        }
    return {
        "tok_emb": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "norm"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "ffn_norm": ("layers", "norm"),
            **ffn_axes,
        },
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialise the parameter pytree (per-layer arrays stacked on axis 0)."""
    d, hd = cfg.dim, cfg.head_dim
    nq, nkv, L = cfg.n_heads * hd, cfg.n_kv_heads * hd, cfg.n_layers
    keys = jax.random.split(rng, 10)

    def dense(key: jax.Array, shape: tuple[int, ...], fan_in: int) -> jax.Array:
        scale = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    F, E = cfg.ffn_dim, cfg.n_experts
    if cfg.is_moe:
        ffn = {
            # routing statistics stay float32 (see parallel.moe)
            "router": dense(keys[5], (L, d, E), d).astype(jnp.float32),
            "w1": dense(keys[6], (L, E, d, F), d),
            "w3": dense(keys[7], (L, E, d, F), d),
            "w2": dense(keys[9], (L, E, F, d), F),
        }
    else:
        ffn = {
            "w1": dense(keys[5], (L, d, F), d),
            "w3": dense(keys[6], (L, d, F), d),
            "w2": dense(keys[7], (L, F, d), F),
        }
    return {
        "tok_emb": dense(keys[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "wq": dense(keys[1], (L, d, nq), d),
            "wk": dense(keys[2], (L, d, nkv), d),
            "wv": dense(keys[3], (L, d, nkv), d),
            "wo": dense(keys[4], (L, nq, d), nq),
            "ffn_norm": jnp.ones((L, d), cfg.dtype),
            **ffn,
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": dense(keys[8], (d, cfg.vocab_size), d),
    }


# --- building blocks ----------------------------------------------------------


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * weight


def rope_freqs(cfg: LlamaConfig) -> jax.Array:
    """Rotary frequency vector [head_dim/2] fp32 — the ONE copy of the
    formula shared by the train table, the prefill path, and the decode
    engine (a scaling scheme added here reaches all three)."""
    half = cfg.head_dim // 2
    return cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)


def rope_table(cfg: LlamaConfig, seq_len: int, offset: int = 0) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables [seq, head_dim/2], float32."""
    freqs = rope_freqs(cfg)
    pos = jnp.arange(offset, offset + seq_len, dtype=jnp.float32)
    angles = pos[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, H, hd] -> rotated, same dtype. Pairs (even, odd) halves."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def dot_attention(q: jax.Array, k: jax.Array, v: jax.Array, cfg: LlamaConfig | None = None) -> jax.Array:
    """Plain causal attention, fp32 softmax. q:[B,S,H,hd] k/v:[B,S,H,hd]."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    S = q.shape[1]
    causal = jnp.tril(jnp.ones((S, S), dtype=bool))
    scores = jnp.where(causal[None, None], scores * scale, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _get_attention(cfg: LlamaConfig) -> AttnFn:
    if cfg.attention_impl == "dot":
        return dot_attention
    try:
        if cfg.attention_impl == "flash":
            from tony_tpu.ops.attention import sharded_flash_attention

            return sharded_flash_attention
        if cfg.attention_impl == "ring":
            from tony_tpu.parallel.ring_attention import ring_attention

            return ring_attention
        if cfg.attention_impl == "ring_flash":
            from tony_tpu.parallel.ring_attention import ring_flash_attention

            return ring_flash_attention
        if cfg.attention_impl == "ulysses":
            from tony_tpu.parallel.ulysses import ulysses_attention

            return ulysses_attention
    except ImportError as e:
        raise NotImplementedError(
            f"attention_impl={cfg.attention_impl!r} backend not available: {e}"
        ) from e
    raise ValueError(f"unknown attention_impl {cfg.attention_impl!r}")


def _proj(x: jax.Array, w: jax.Array, cfg: LlamaConfig,
          axes: tuple[str | None, ...]) -> jax.Array:
    """One trunk projection ``x [B,S,D] @ w``. With ``cfg.overlap_impl``
    set, the fsdp weight all-gather streams per-chunk through the
    decomposed ring matmul (tony_tpu.ops.overlap) instead of blocking up
    front; ``axes`` are the weight's per-layer logical axes — which dim
    rides the ring is read off the sharding rules (parallel.sharding), not
    hardcoded here. Silently the plain matmul wherever the decomposition
    doesn't apply: overlap is an optimisation, never a semantic.
    """
    if cfg.overlap_impl:
        from tony_tpu.ops.overlap import overlap_matmul
        from tony_tpu.parallel.sharding import overlap_gather_dim

        gd = overlap_gather_dim(axes)
        if gd is not None:
            y = overlap_matmul(x, w, gather_dim=gd, impl=cfg.overlap_impl)
            if y is not None:
                return y
    return x @ w


def attention_block(x: jax.Array, lp: Params, cfg: LlamaConfig,
                    cos: jax.Array, sin: jax.Array) -> jax.Array:
    B, S, _ = x.shape
    hd = cfg.head_dim
    from jax.ad_checkpoint import checkpoint_name

    q = _proj(x, lp["wq"], cfg, ("embed", "heads")).reshape(B, S, cfg.n_heads, hd)
    k = _proj(x, lp["wk"], cfg, ("embed", "kv_heads")).reshape(B, S, cfg.n_kv_heads, hd)
    v = _proj(x, lp["wv"], cfg, ("embed", "kv_heads")).reshape(B, S, cfg.n_kv_heads, hd)
    q = checkpoint_name(apply_rope(q, cos, sin), "attn_qkv")
    k = checkpoint_name(apply_rope(k, cos, sin), "attn_qkv")
    v = checkpoint_name(v, "attn_qkv")
    # GQA: the flash kernels read each kv head n_heads/n_kv_heads times via
    # their BlockSpec index maps — no HBM-materialised repeat (and for
    # ring_flash, no repeat riding every ppermute hop). Other impls get the
    # expanded kv tensors.
    if (cfg.n_kv_heads != cfg.n_heads
            and cfg.attention_impl not in ("flash", "ring_flash")):
        rep = cfg.n_heads // cfg.n_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    out = _get_attention(cfg)(q, k, v, cfg)
    # named save point: remat_policy='save_attn' keeps this activation so the
    # bwd recompute skips qkv projections + the attention kernel (~29% of a
    # layer's fwd FLOPs) for ~32MB/layer at bench shapes
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "attn_out")
    return _proj(
        out.reshape(B, S, cfg.n_heads * hd), lp["wo"], cfg, ("heads", "embed")
    )


def ffn_block(x: jax.Array, lp: Params, cfg: LlamaConfig) -> jax.Array:
    from jax.ad_checkpoint import checkpoint_name

    # named save point: remat policies can keep the gate product so the bwd
    # recompute skips the two widest matmuls (w1/w3, ~45% of a layer's fwd)
    gate = checkpoint_name(
        jax.nn.silu(_proj(x, lp["w1"], cfg, ("embed", "ffn")))
        * _proj(x, lp["w3"], cfg, ("embed", "ffn")),
        "ffn_gate",
    )
    return _proj(gate, lp["w2"], cfg, ("ffn", "embed"))


def moe_ffn_block(x: jax.Array, lp: Params, cfg: LlamaConfig):
    """Expert-parallel FFN: (y, aux_loss). See tony_tpu.parallel.moe."""
    from tony_tpu.parallel.moe import MoEConfig, moe_block

    mcfg = MoEConfig(
        dim=cfg.dim, ffn_dim=cfg.ffn_dim, n_experts=cfg.n_experts,
        top_k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
        dispatch=cfg.moe_dispatch, group_block=cfg.moe_group_block,
        gmm_impl=cfg.moe_gmm_impl, overlap_impl=cfg.moe_overlap_impl,
        overlap_chunk=cfg.moe_overlap_chunk,
    )
    return moe_block(
        {"router": lp["router"], "w1": lp["w1"], "w3": lp["w3"], "w2": lp["w2"]},
        x, mcfg,
    )


# --- forward ------------------------------------------------------------------


def transformer_block(x: jax.Array, lp: Params, cfg: LlamaConfig,
                      cos: jax.Array, sin: jax.Array) -> tuple[jax.Array, jax.Array]:
    """One decoder layer: (x, lp) -> (x', aux_loss). aux is 0 for dense."""
    h = x + attention_block(
        rms_norm(x, lp["attn_norm"], cfg.norm_eps), lp, cfg, cos, sin
    )
    normed = rms_norm(h, lp["ffn_norm"], cfg.norm_eps)
    if cfg.is_moe:
        delta, aux = moe_ffn_block(normed, lp, cfg)
    else:
        delta, aux = ffn_block(normed, lp, cfg), jnp.zeros((), jnp.float32)
    return h + delta, aux


def _remat_policy(name: str):
    policies = {
        "nothing": jax.checkpoint_policies.nothing_saveable,
        "save_attn": jax.checkpoint_policies.save_only_these_names("attn_out"),
        "save_gate": jax.checkpoint_policies.save_only_these_names("ffn_gate"),
        "save_attn_gate": jax.checkpoint_policies.save_only_these_names(
            "attn_out", "ffn_gate"
        ),
        # keep the flash kernel's inputs + residuals (q/k/v post-rope, out,
        # lse): the bwd recompute skips the qkv projections, rope, AND the
        # flash fwd kernel — the three hottest recompute items in the trace
        # — for ~3.2GB at bench shapes (B=4, S=2048, 24 layers)
        "save_attn_kernel": jax.checkpoint_policies.save_only_these_names(
            "attn_qkv", "flash_res"
        ),
        "save_attn_kernel_gate": jax.checkpoint_policies.save_only_these_names(
            "attn_qkv", "flash_res", "ffn_gate"
        ),
        # flash residuals + gate but NOT q/k/v: bwd re-runs the (cheap) qkv
        # projections but skips the flash fwd kernel and the two widest FFN
        # matmuls — 0.8GB less HBM than save_attn_kernel_gate
        "save_flash_gate": jax.checkpoint_policies.save_only_these_names(
            "flash_res", "ffn_gate"
        ),
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "checkpoint_dots": jax.checkpoint_policies.checkpoint_dots,
    }
    if name not in policies:
        raise ValueError(f"unknown remat_policy {name!r} (expected {sorted(policies)})")
    return policies[name]


def embed_tokens(params: Params, tokens: jax.Array, act_sharding=None) -> jax.Array:
    """Embedding gather with pinned shardings: gather from an explicitly
    replicated table view, batch/seq-sharded output. The fsdp/tp-sharded
    table would otherwise make the partitioner emit the same all-gather
    *involuntarily* (an embed-sharded gather output it then full-remats to
    the activation layout — "[SPMD] Involuntary full rematerialization" in
    the multichip dryrun log); the constraint's transpose pins the bwd
    cotangents too. The ONE copy both the sequential trunk and the
    trainer's pipeline losses use. ``act_sharding=None`` is a plain gather.
    """
    emb = params["tok_emb"]
    if act_sharding is None:
        return emb[tokens]
    from jax.sharding import NamedSharding, PartitionSpec

    emb = lax.with_sharding_constraint(
        emb, NamedSharding(act_sharding.mesh, PartitionSpec())
    )
    return lax.with_sharding_constraint(emb[tokens], act_sharding)


def hidden_states_with_aux(
    params: Params, tokens: jax.Array, cfg: LlamaConfig,
    act_sharding=None,
) -> tuple[jax.Array, jax.Array]:
    """tokens [B, S] int32 -> (post-final-norm hidden [B, S, D], aux_loss).

    The trunk without the vocab projection: the fused CE head consumes this
    directly so the [B, S, V] logits tensor never exists on the train path.

    ``act_sharding`` (a NamedSharding for [B, S, D] activations, or None)
    pins the embedding output and the returned hidden states: without it
    the partitioner propagates ``tok_emb``'s fsdp/tp weight sharding into
    the gather's embed dim while downstream ops want batch/seq-sharded
    activations, and resolves the conflict with "[SPMD] Involuntary full
    rematerialization" all-gathers in both fwd and bwd (the constraint's
    transpose pins the cotangents too). The trainer passes it whenever the
    mesh has more than one device.
    """
    x = embed_tokens(params, tokens, act_sharding)
    cos, sin = rope_table(cfg, tokens.shape[1])

    def block(carry, lp: Params):
        x, aux_acc = carry
        out, aux = transformer_block(x, lp, cfg, cos, sin)
        return (out, aux_acc + aux), None

    if cfg.remat:
        block = jax.checkpoint(block, policy=_remat_policy(cfg.remat_policy))
    (x, aux), _ = lax.scan(
        block, (x, jnp.zeros((), jnp.float32)), params["layers"],
        unroll=cfg.scan_unroll,
    )
    h = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if act_sharding is not None:
        h = lax.with_sharding_constraint(h, act_sharding)
    return h, aux / cfg.n_layers


def forward_with_aux(
    params: Params, tokens: jax.Array, cfg: LlamaConfig
) -> tuple[jax.Array, jax.Array]:
    """tokens [B, S] int32 -> (logits [B, S, vocab] float32, aux_loss)."""
    x, aux = hidden_states_with_aux(params, tokens, cfg)
    return (x @ params["lm_head"]).astype(jnp.float32), aux


def forward(params: Params, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, vocab] float32."""
    return forward_with_aux(params, tokens, cfg)[0]


def ce_tokens(
    h: jax.Array, lm_head: jax.Array, targets: jax.Array, cfg: LlamaConfig
) -> jax.Array:
    """Per-token CE [B, S] f32 from post-norm hidden states, dispatched on
    ``cfg.ce_impl``. The ONE head every loss path shares (sequential, GPipe,
    1F1B), so schedule-parity tests compare identical math."""
    if cfg.ce_impl == "dense":
        # legacy full-logits path (the fused impls' parity oracle — ONE copy
        # of the math, in ops.fused_ce): the logits and autodiff's dlogits
        # still materialise at [B,S,V]
        from tony_tpu.ops.fused_ce import reference_ce_tokens

        return reference_ce_tokens(h, lm_head, targets)
    from tony_tpu.ops.fused_ce import sharded_fused_ce_tokens

    return sharded_fused_ce_tokens(h, lm_head, targets, cfg)


def loss_from_pairs(
    params: Params, inputs: jax.Array, targets: jax.Array, cfg: LlamaConfig,
    act_sharding=None,
) -> jax.Array:
    """Cross-entropy of predicting targets [B, S] from inputs [B, S].

    Pre-shifted pairs keep the sequence length identical across inputs,
    activations, and targets, so a ``sp``-sharded seq axis stays aligned end
    to end (no off-by-one reshard between forward and loss). The head runs
    through :func:`ce_tokens` (fused chunked CE by default).
    ``act_sharding`` pins [B, S, D] activation shardings at the trunk
    boundaries (see :func:`hidden_states_with_aux`).
    """
    h, aux = hidden_states_with_aux(params, inputs, cfg, act_sharding)
    ce = jnp.mean(ce_tokens(h, params["lm_head"], targets, cfg))
    if cfg.is_moe:
        ce = ce + cfg.moe_aux_coef * aux
    return ce


def loss_fn(params: Params, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """Next-token cross-entropy over tokens [B, S+1] (shifts internally)."""
    return loss_from_pairs(params, tokens[:, :-1], tokens[:, 1:], cfg)


def train_flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs per token: 6*N_active (param matmuls,
    fwd+bwd; MoE counts only the top-k experts that fire per token) plus the
    causal-attention score/value matmuls (12*L*D*S/2)."""
    return 6.0 * cfg.n_active_params + 6.0 * cfg.n_layers * cfg.dim * seq_len


__all__ = [
    "LlamaConfig", "embed_tokens", "init_params", "logical_axes", "forward",
    "forward_with_aux", "hidden_states_with_aux", "ce_tokens",
    "loss_fn", "loss_from_pairs",
    "rms_norm", "rope_freqs", "rope_table", "apply_rope", "dot_attention",
    "transformer_block", "train_flops_per_token",
]

"""Elastic training: survive TPU preemption without a cold restart.

The subsystem behind ``elastic.*`` config (docs/ELASTIC.md): on a lost
training host the AM declares a new cluster generation instead of
gang-restarting; survivors fence on it, reshard the dp axis via the
runtime-swappable :class:`ElasticTopology`, donate state from the
host-RAM :class:`ShadowStore`, skip exactly the dead member's unconsumed
batches (:class:`ElasticBatchStream`), and keep stepping — then grow back
when the lease store re-acquires capacity.

The protocol layer (generation records, controller, journals) is
stdlib-only so the AM and the invariant checker import it without paying
for jax; the device-side pieces (topology/shadow/data) load lazily.
"""

from tony_tpu.elastic.protocol import (
    ENV_ENABLED,
    ENV_MEMBER,
    ENV_MEMBERS,
    ENV_POLL,
    ENV_SHADOW,
    ElasticController,
    ElasticJournal,
    ElasticSettings,
    GenerationRecord,
    active_controller,
    elastic_dir,
    generation_path,
    install,
    install_from_env,
    journal_files,
    journal_path,
    read_generation,
    read_history,
    read_journal,
    uninstall,
    write_generation,
)

_LAZY = {
    "ElasticBatchStream": ("tony_tpu.elastic.data", "ElasticBatchStream"),
    "reference_batches": ("tony_tpu.elastic.data", "reference_batches"),
    "ShadowStore": ("tony_tpu.elastic.shadow", "ShadowStore"),
    "reshard_state": ("tony_tpu.elastic.shadow", "reshard_state"),
    "ElasticTopology": ("tony_tpu.elastic.topology", "ElasticTopology"),
}


def __getattr__(name: str):
    # lazy jax-side exports: the AM/checker import this package for the
    # protocol alone and must not drag jax into a control-plane process
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    return getattr(importlib.import_module(mod_name), attr)


__all__ = [
    "ENV_ENABLED",
    "ENV_MEMBER",
    "ENV_MEMBERS",
    "ENV_POLL",
    "ENV_SHADOW",
    "ElasticBatchStream",
    "ElasticController",
    "ElasticJournal",
    "ElasticSettings",
    "ElasticTopology",
    "GenerationRecord",
    "ShadowStore",
    "active_controller",
    "elastic_dir",
    "generation_path",
    "install",
    "install_from_env",
    "journal_files",
    "journal_path",
    "read_generation",
    "read_history",
    "read_journal",
    "reference_batches",
    "reshard_state",
    "uninstall",
    "write_generation",
]

"""Membership-aware batch streams: skip exactly the dead member's data.

Elastic training changes the *shape* of the global batch at a generation
boundary (dp shrinks with the membership), and the data contract across
that boundary is strict: survivors must neither replay a batch they
already consumed nor skip one of their own — only the dead member's
unconsumed positions may drop out of the stream, and they must be
declared, not silently lost (chaos invariant ``elastic-no-data-loss``;
the health sentinel's repeated-batch fingerprint rule is the runtime twin
for the replay half).

The stream keeps the bookkeeping trivial to audit by construction: every
member draws its per-step shard from its OWN deterministic substream
keyed by ``(seed + member, step)``, so a member's stream position is
always exactly the global step index. Shrink/grow then never moves any
survivor's position — membership just selects which substreams contribute
to the global batch — and the skipped ranges are pure intervals
``[shrink_step, grow_step)`` per dead member.

``prefetch`` composes: the live membership's generator is wrapped in the
standard :class:`~tony_tpu.train.prefetch.PrefetchIterator`; a reshard
closes it and rebuilds from the boundary step. Batches the old prefetcher
had generated but the loop never consumed are regenerated identically by
the new one (same substreams, same positions) — discarding them is a
re-layout, not a skip.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np
from jax.sharding import NamedSharding

from tony_tpu.train.data import Batch, DataConfig, _assemble


class ElasticBatchStream:
    """Synthetic per-member token stream for elastic ``fit()``.

    ``cfg.global_batch`` is the FULL-membership global batch; each member
    contributes ``global_batch / n_members`` rows. ``next()`` yields the
    live membership's assembled (inputs, targets) pair; :meth:`reshard`
    swaps membership + sharding at a step boundary and records what the
    dead members will skip.
    """

    def __init__(self, cfg: DataConfig, n_members: int,
                 members: tuple[int, ...],
                 sharding: NamedSharding | None = None, start_step: int = 0,
                 prefetch: int | None = None):
        if cfg.path:
            raise NotImplementedError(
                "elastic fit currently streams the synthetic pipeline; "
                "token-file streams need per-member shard ownership "
                "(DataConfig.path with elastic_members is not supported yet)"
            )
        if n_members < 1 or cfg.global_batch % n_members:
            raise ValueError(
                f"global batch {cfg.global_batch} not divisible by "
                f"{n_members} members"
            )
        self.cfg = cfg
        self.n_members = n_members
        self.per_member = cfg.global_batch // n_members
        self.members: tuple[int, ...] = tuple(sorted(members))
        self.step = start_step
        self._sharding = sharding
        self._prefetch = cfg.prefetch if prefetch is None else prefetch
        # member -> [from_step, to_step) ranges this stream skipped; an
        # open range (to_step == -1) means the member never came back
        self.skipped: dict[int, list[list[int]]] = {}
        self._cum = self._zipf_table(cfg.vocab_size)
        self._it: Iterator[Batch] | None = None
        self._rebuild()

    @staticmethod
    def _zipf_table(vocab_size: int) -> np.ndarray:
        # same marginals as train.data.synthetic_batches (inverse-CDF over
        # a one-time cumulative table; tail pinned so rounding can't
        # index past vocab_size-1)
        ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
        probs = (1.0 / ranks) / np.sum(1.0 / ranks)
        cum = np.cumsum(probs)
        cum[-1] = 1.0
        return cum

    def member_rows(self, member: int, step: int) -> np.ndarray:
        """Member ``member``'s [per_member, seq+1] token block at ``step``
        — the deterministic substream contract (position == step)."""
        rng = np.random.default_rng((self.cfg.seed + member, step))
        draws = rng.random((self.per_member, self.cfg.seq_len + 1))
        return np.searchsorted(self._cum, draws, side="right").astype(np.int32)

    def _generate(self, members: tuple[int, ...], start: int) -> Iterator[Batch]:
        step = start
        while True:
            tokens = np.concatenate(
                [self.member_rows(m, step) for m in members], axis=0
            )
            step += 1
            yield _assemble(
                np.ascontiguousarray(tokens[:, :-1]),
                np.ascontiguousarray(tokens[:, 1:]),
                self._sharding,
            )

    def _rebuild(self) -> None:
        it: Iterator[Batch] = self._generate(self.members, self.step)
        if self._prefetch > 0:
            from tony_tpu.train.prefetch import PrefetchIterator

            it = PrefetchIterator(it, depth=self._prefetch)
        self._it = it

    def __iter__(self) -> "ElasticBatchStream":
        return self

    def __next__(self) -> Batch:
        batch = next(self._it)
        self.step += 1
        return batch

    @property
    def global_batch(self) -> int:
        """Live global batch rows (shrinks/grows with membership)."""
        return self.per_member * len(self.members)

    def reshard(self, members: tuple[int, ...],
                sharding: NamedSharding | None) -> dict[int, tuple[int, int]]:
        """Swap membership at the current step boundary.

        Returns the skip bookkeeping delta: ``{member: (from, to)}`` —
        a newly-dead member opens ``(step, -1)``; a returning member
        closes its open range at ``(from, step)``. Survivor positions are
        untouched by construction."""
        from tony_tpu.train.prefetch import close_batches

        members = tuple(sorted(members))
        delta: dict[int, tuple[int, int]] = {}
        for m in self.members:
            if m not in members:
                self.skipped.setdefault(m, []).append([self.step, -1])
                delta[m] = (self.step, -1)
        for m in members:
            if m not in self.members:
                ranges = self.skipped.get(m, [])
                if ranges and ranges[-1][1] == -1:
                    ranges[-1][1] = self.step
                    delta[m] = (ranges[-1][0], self.step)
        close_batches(self._it)
        self.members = members
        self._sharding = sharding
        self._rebuild()
        return delta

    def close(self) -> None:
        from tony_tpu.train.prefetch import close_batches

        close_batches(self._it)


def reference_batches(cfg: DataConfig, n_members: int,
                      sharding: NamedSharding | None = None,
                      start_step: int = 0) -> ElasticBatchStream:
    """A full-membership elastic stream — the no-fault reference a
    loss-continuity comparison trains against (same substreams, no
    boundary)."""
    return ElasticBatchStream(
        cfg, n_members, tuple(range(n_members)), sharding, start_step
    )


__all__ = ["ElasticBatchStream", "reference_batches"]

"""ElasticTopology: member-granular, runtime-swappable device meshes.

The repo's meshes were fixed at ``fit()`` entry; elastic training needs the
mesh to be a *function of the current membership*. The unit of elasticity
is a **member** — one gang seat owning an equal slice of the device set
(on real TPU fleets, one host's chips; under the CPU test platform, a
contiguous group of local devices). Members map onto the ``dp`` axis
outermost: parameters and optimizer state are replicated across members
(sharded only over the per-member axes inside a member's devices), which
is exactly what makes survivors *whole* — when a member dies, the
remaining members already hold the complete current state and resharding
is a relayout, not a recovery.

``mesh_for(members)`` builds the mesh for any live subset: ``dp`` shrinks
to the member count, the per-member shape (fsdp/tp/sp within a member's
devices) is preserved, and member device groups stay in member order so
the dp coordinate *is* the member's rank among survivors. The train step
is re-lowered against the result through the existing compile-ahead path
(train/loop.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
from jax.sharding import Mesh

from tony_tpu.parallel.mesh import MESH_AXES, MeshShape


@dataclass
class ElasticTopology:
    """Partition of a device set into ``n_members`` equal groups.

    ``per_member`` is the mesh shape INSIDE one member's device group; its
    ``dp`` must be 1 (the dp axis is the member axis — a per-member dp
    would make the member boundary invisible to the reshard path).
    """

    n_members: int
    per_member: MeshShape | None = None
    devices: list = field(default_factory=list)

    def __post_init__(self) -> None:
        if self.n_members < 2:
            raise ValueError(
                f"elastic topology needs >= 2 members, got {self.n_members}"
            )
        if not self.devices:
            import jax

            self.devices = list(jax.devices())
        if len(self.devices) % self.n_members:
            raise ValueError(
                f"{len(self.devices)} devices not divisible into "
                f"{self.n_members} member groups"
            )
        per = len(self.devices) // self.n_members
        if self.per_member is None:
            # fsdp-first inside the member, mirroring default_shape(): the
            # bandwidth-hungry axis stays on the member's own interconnect
            self.per_member = MeshShape(fsdp=per)
        if self.per_member.dp != 1:
            raise ValueError(
                "per_member.dp must be 1: the dp axis is the member axis "
                f"(got per-member shape {self.per_member.sizes})"
            )
        if self.per_member.n_devices != per:
            raise ValueError(
                f"per-member shape {self.per_member.sizes} needs "
                f"{self.per_member.n_devices} devices but each of the "
                f"{self.n_members} members owns {per}"
            )

    @property
    def devices_per_member(self) -> int:
        return len(self.devices) // self.n_members

    def member_devices(self, member: int) -> list:
        per = self.devices_per_member
        if not 0 <= member < self.n_members:
            raise ValueError(f"member {member} outside 0..{self.n_members - 1}")
        return self.devices[member * per : (member + 1) * per]

    def shape_for(self, members: tuple[int, ...] | list[int]) -> MeshShape:
        pm = self.per_member
        return MeshShape(
            dp=len(members), pp=pm.pp, fsdp=pm.fsdp, ep=pm.ep, tp=pm.tp,
            sp=pm.sp,
        )

    def mesh_for(self, members: tuple[int, ...] | list[int]) -> Mesh:
        """Mesh over the live members' devices, member-major on ``dp``.

        Device order is deliberately member-major raveled (NOT
        ``create_device_mesh``'s topology-optimised order): the dp
        coordinate must identify the member so shrink/grow relayouts move
        whole member groups — and dp is the latency-tolerant outer axis,
        so member order costs nothing (the same reasoning that puts dp on
        DCN in ``build_multislice_mesh``).
        """
        members = tuple(sorted(members))
        if not members:
            raise ValueError("elastic mesh needs at least one live member")
        devs: list = []
        for m in members:
            devs.extend(self.member_devices(m))
        shape = self.shape_for(members)
        dev_array = np.asarray(devs, dtype=object).reshape(shape.sizes)
        return Mesh(dev_array, MESH_AXES)


__all__ = ["ElasticTopology"]

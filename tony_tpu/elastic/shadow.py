"""Checkpoint shadowing: a host-RAM replica of the sharded train state.

The elastic recovery point must not live on disk: a preempted host costs
seconds, a cold orbax restore costs minutes. The :class:`ShadowStore`
keeps a recent full copy of the TrainState in host RAM:

- **async stride shadows** — every ``interval_steps`` the fit loop hands
  the store a reference to the live (device) state; a single bounded
  daemon thread performs the device->host transfer off the step loop
  (exactly the health sentinel's queue discipline: the D2H sync lands on
  the worker thread, never on the dispatch path). If a transfer is still
  in flight the new request is dropped, not queued — the shadow is a
  bounded-lag recovery point, not a log.
- **fence shadows** — at a generation boundary the loop calls
  :meth:`capture_sync` AFTER draining the device: the result is the
  *exact current* state, which is what makes elastic shrink lose zero
  steps (survivors donate from this capture; the periodic shadow is the
  fallback recovery point when a fence cannot complete, and its age
  bounds the lost steps in that path).
- **donation / restore** — :func:`reshard_state` device_puts a host
  shadow onto any new mesh's shardings: the same call serves shrink
  (survivors re-layout onto fewer members), grow-back (the relaunched
  member syncs from survivors' RAM), and the rollback path
  (:meth:`snapshot` + reshard = resume at the shadowed step).

The store never touches disk and holds at most one full host replica plus
one in-flight transfer.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any

log = logging.getLogger(__name__)


class ShadowStore:
    """Bounded background device->host state replica (see module doc)."""

    def __init__(self, interval_steps: int = 16):
        self.interval_steps = max(int(interval_steps), 1)
        self._q: queue.Queue = queue.Queue(maxsize=1)
        self._lock = threading.Lock()  # guards (_step, _host) swaps only
        self._step = -1
        self._host: Any = None
        self._dropped = 0
        self._closed = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="tony-elastic-shadow"
        )
        self._thread.start()

    # --- producer side (fit loop) --------------------------------------------

    def maybe_update(self, step: int, state: Any) -> bool:
        """Stride-gated async shadow request; returns whether one was
        enqueued. Never blocks: a busy worker means the previous shadow is
        still transferring and this stride is skipped (bounded lag =
        at most 2x the interval).

        The enqueued arrays are device-side COPIES, not the live state:
        the train step donates its state argument, so the caller's
        reference is deleted the moment the next step dispatches — a
        worker device_get on it would race the donation and fail. The
        copy dispatches asynchronously (no step-loop stall) at the cost
        of one transient extra state replica on device per shadow; size
        the stride accordingly on HBM-tight configs.
        """
        if step % self.interval_steps:
            return False
        if self._q.full():
            self._dropped += 1
            return False
        import jax

        try:
            copy = jax.tree.map(lambda x: x.copy(), state)
            self._q.put_nowait((step, copy))
            return True
        except queue.Full:
            self._dropped += 1
            return False

    def capture_sync(self, step: int, state: Any) -> Any:
        """Synchronous full device->host capture (the fence-boundary path);
        also installs the result as the current shadow and returns it."""
        import jax

        host = jax.device_get(state)
        with self._lock:
            self._step, self._host = step, host
        return host

    # --- worker ---------------------------------------------------------------

    def _worker(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            step, state = item
            try:
                import jax

                host = jax.device_get(state)
            except Exception:
                # a failed transfer is a MISSED shadow, not a logged
                # curiosity: it must show in `dropped` or a permanently
                # failing path would report a perfect record
                self._dropped += 1
                log.warning("shadow transfer failed at step %d", step,
                            exc_info=True)
                continue
            with self._lock:
                if step > self._step:
                    self._step, self._host = step, host

    # --- consumer side --------------------------------------------------------

    def snapshot(self) -> tuple[int, Any] | None:
        """(step, host state) of the most recent completed shadow, or None
        when nothing has been shadowed yet."""
        with self._lock:
            if self._host is None:
                return None
            return self._step, self._host

    @property
    def dropped(self) -> int:
        return self._dropped

    def drain(self, timeout_s: float = 10.0) -> None:
        """Settle any in-flight transfer (tests / pre-fence)."""
        import time

        deadline = time.monotonic() + timeout_s
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            # worker is mid-transfer; it will pick the sentinel up next
            try:
                self._q.put(None, timeout=1.0)
            except queue.Full:
                pass
        self._thread.join(timeout=5.0)


def reshard_state(host_state: Any, shardings: Any) -> Any:
    """Place a host shadow onto a (new) mesh's shardings leaf by leaf —
    the donation path: shrink, grow-back sync, and shadow rollback all
    reduce to this one device_put."""
    import jax

    return jax.tree.map(lambda x, s: jax.device_put(x, s), host_state, shardings)


__all__ = ["ShadowStore", "reshard_state"]

"""Elastic membership protocol: cluster generations over the shared app dir.

TonY's core mandate is that the AM keeps a gang alive across container loss
(PAPER.md); until now a lost training host meant a full cold restart —
re-schedule, re-compile, re-restore, replayed data. This module is the
control-plane half of the elastic alternative (ROADMAP open item 5):

- the AM is the ONE membership authority. On a lost host it declares a new
  **cluster generation** — a :class:`GenerationRecord` naming the surviving
  members — by atomically writing ``<app_dir>/elastic/generation.json``
  (the same shared-app-dir broadcast channel profile requests and the
  series rollup use) and appending the record to
  ``<app_dir>/elastic/generations.jsonl`` so the whole membership history
  is auditable post-mortem (chaos invariant ``elastic-no-data-loss`` reads
  it).
- survivors **fence on the generation**: the trainer's
  :class:`ElasticController` watches the broadcast file from a daemon
  thread (synchronously once at arm time, so a generation declared while
  the trainer boots is honoured at the first step boundary) and surfaces
  the pending record to ``fit()``, which reshards at the next step
  boundary. Ghost executors of a removed member are fenced by the
  existing heartbeat epoch: the AM bumps the dead task's attempt when it
  detaches the member, so a still-running ghost gets ABORT on its next
  heartbeat — the membership protocol *rides* the heartbeat protocol
  instead of inventing a second liveness channel.
- the trainer journals its side — per-step membership, log-boundary
  losses + batch fingerprints, and every reshard with the exact data
  ranges it skipped — to ``<app_dir>/elastic/journal_m<member>.jsonl``,
  the evidence the ``elastic-loss-continuity`` / ``elastic-no-data-loss``
  invariants and ``tony elastic`` audit.

Nothing here imports jax: the AM and the invariant checker stay pure
control-plane consumers.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any

log = logging.getLogger(__name__)

# env contract (AM -> executor -> user process, next to TONY_TRACE_* /
# TONY_OBS_*): the ElasticRuntime exports these into every member
ENV_ENABLED = "TONY_ELASTIC"                  # "1" arms the trainer
ENV_MEMBERS = "TONY_ELASTIC_MEMBERS"          # gang size at full strength
ENV_MEMBER = "TONY_ELASTIC_MEMBER"            # this process's member id
ENV_POLL = "TONY_ELASTIC_POLL_S"              # generation-file poll cadence
ENV_SHADOW = "TONY_ELASTIC_SHADOW_STEPS"      # checkpoint-shadow stride

GENERATION_FILE = "generation.json"
HISTORY_FILE = "generations.jsonl"

# loss-continuity tolerance the trainer stamps into its journal meta line;
# the invariant checker judges boundary jumps against exactly these numbers
# (a post-mortem must not invent its own thresholds)
DEFAULT_TOLERANCE = {"window": 8, "z": 4.0, "frac": 0.25}


@dataclass(frozen=True)
class GenerationRecord:
    """One membership declaration (the generation.json payload)."""

    generation: int
    members: tuple[int, ...]           # surviving member ids, sorted
    boundary: str = "start"            # start | shrink | grow
    dead: tuple[int, ...] = ()         # members removed at this boundary
    added: tuple[int, ...] = ()        # members restored at this boundary
    reason: str = ""
    ts: float = 0.0
    freed_host: str = ""               # lease handed back on shrink
    granted_host: str = ""             # lease re-acquired on grow

    def to_dict(self) -> dict:
        d = asdict(self)
        for k in ("members", "dead", "added"):
            d[k] = list(d[k])
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "GenerationRecord":
        return cls(
            generation=int(d.get("generation", 0)),
            members=tuple(int(m) for m in d.get("members", ())),
            boundary=str(d.get("boundary", "start")),
            dead=tuple(int(m) for m in d.get("dead", ())),
            added=tuple(int(m) for m in d.get("added", ())),
            reason=str(d.get("reason", "")),
            ts=float(d.get("ts", 0.0) or 0.0),
            freed_host=str(d.get("freed_host", "")),
            granted_host=str(d.get("granted_host", "")),
        )


def elastic_dir(app_dir: str) -> str:
    return os.path.join(app_dir, "elastic")


def generation_path(app_dir: str) -> str:
    return os.path.join(elastic_dir(app_dir), GENERATION_FILE)


def history_path(app_dir: str) -> str:
    return os.path.join(elastic_dir(app_dir), HISTORY_FILE)


def write_generation(app_dir: str, rec: GenerationRecord) -> GenerationRecord:
    """The AM's membership broadcast: atomic latest + append-only history.

    The latest file is what survivors fence on; the history is the
    post-mortem record (``tony elastic``, the elastic chaos invariants).
    The history append lands BEFORE the latest-file replace, so a reader
    that observed generation G in the broadcast always finds G in the
    history too.
    """
    if not rec.ts:
        rec = GenerationRecord(**{**rec.to_dict(), "ts": time.time()})
    d = elastic_dir(app_dir)
    os.makedirs(d, exist_ok=True)
    with open(history_path(app_dir), "a", encoding="utf-8") as f:
        f.write(json.dumps(rec.to_dict(), sort_keys=True) + "\n")
        f.flush()
        os.fsync(f.fileno())
    path = generation_path(app_dir)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rec.to_dict(), f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return rec


def read_generation(app_dir: str) -> GenerationRecord | None:
    try:
        with open(generation_path(app_dir), encoding="utf-8") as f:
            d = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(d, dict) or "generation" not in d:
        return None
    return GenerationRecord.from_dict(d)


def read_history(app_dir: str) -> list[GenerationRecord]:
    """Every declared generation, journal order; torn tails skipped."""
    recs: list[GenerationRecord] = []
    try:
        with open(history_path(app_dir), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    recs.append(GenerationRecord.from_dict(json.loads(line)))
                except (ValueError, TypeError):
                    continue
    except OSError:
        pass
    return recs


# --- trainer-side journal -----------------------------------------------------


class ElasticJournal:
    """Append-only per-member evidence stream under ``<app_dir>/elastic/``.

    Written from the fit loop's thread only (no lock needed); each record
    is one JSON line. ``step`` records are pure host-side bookkeeping
    (membership per step — the no-data-loss evidence); ``loss`` records
    ride the log boundary's already-synced scalars; ``reshard`` records
    carry the exact skipped data ranges. Flushed at reshard boundaries and
    close so a chaos SIGKILL loses at most the buffered tail (the
    invariant checker skips torn lines).
    """

    def __init__(self, path: str, *, member: int, members: int,
                 tolerance: dict | None = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._f = open(path, "a", encoding="utf-8", buffering=1 << 16)
        self._write({
            "type": "meta", "member": member, "members": members,
            "tolerance": dict(tolerance or DEFAULT_TOLERANCE), "ts": time.time(),
        })
        self._f.flush()

    def _write(self, rec: dict) -> None:
        self._f.write(json.dumps(rec, sort_keys=True) + "\n")

    def step(self, step: int, generation: int, members: tuple[int, ...]) -> None:
        self._write({
            "type": "step", "step": step, "gen": generation,
            "members": list(members),
        })

    def loss(self, step: int, generation: int, loss: float,
             fingerprint: int | None = None) -> None:
        rec: dict[str, Any] = {
            "type": "loss", "step": step, "gen": generation, "loss": loss,
        }
        if fingerprint is not None:
            rec["fp"] = int(fingerprint)
        self._write(rec)

    def reshard(self, *, generation: int, at_step: int, boundary: str,
                members: tuple[int, ...], dead: tuple[int, ...] = (),
                added: tuple[int, ...] = (),
                skipped: dict[int, tuple[int, int]] | None = None,
                reshard_s: float = 0.0, lost_steps: int = 0) -> None:
        self._write({
            "type": "reshard", "gen": generation, "at_step": at_step,
            "boundary": boundary, "members": list(members),
            "dead": list(dead), "added": list(added),
            "skipped": {str(m): list(r) for m, r in (skipped or {}).items()},
            "reshard_s": round(reshard_s, 4), "lost_steps": lost_steps,
        })
        self.flush()

    def flush(self) -> None:
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
        except OSError:
            pass

    def close(self) -> None:
        try:
            self._f.flush()
            self._f.close()
        except OSError:
            pass


def journal_path(app_dir: str, member: int) -> str:
    return os.path.join(elastic_dir(app_dir), f"journal_m{member}.jsonl")


def read_journal(path: str) -> list[dict]:
    """One journal's records in order; torn/corrupt lines skipped."""
    recs: list[dict] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    recs.append(rec)
    except OSError:
        pass
    return recs


def journal_files(app_dir: str) -> list[str]:
    d = elastic_dir(app_dir)
    try:
        return sorted(
            os.path.join(d, n) for n in os.listdir(d)
            if n.startswith("journal_m") and n.endswith(".jsonl")
        )
    except OSError:
        return []


# --- trainer-side controller --------------------------------------------------


@dataclass
class ElasticSettings:
    """Everything the trainer needs to arm elastic training."""

    members: int = 0                   # gang size at full strength (0 = off)
    member: int = 0                    # this process's member id
    app_dir: str = ""                  # broadcast + journal root ("" = none)
    poll_interval_s: float = 0.5
    shadow_interval_steps: int = 16

    @classmethod
    def from_env(cls) -> "ElasticSettings | None":
        if os.environ.get(ENV_ENABLED, "") != "1":
            return None
        try:
            members = int(os.environ.get(ENV_MEMBERS, "0"))
        except ValueError:
            members = 0
        if members < 2:
            return None

        def _f(key: str, default: float) -> float:
            try:
                return float(os.environ.get(key, "") or default)
            except ValueError:
                return default

        return cls(
            members=members,
            member=int(_f(ENV_MEMBER, 0)),
            app_dir=os.environ.get("TONY_APP_DIR", ""),
            poll_interval_s=_f(ENV_POLL, 0.5),
            shadow_interval_steps=int(_f(ENV_SHADOW, 16)),
        )


class ElasticController:
    """Per-trainer membership watcher + evidence journal.

    The fit loop calls :meth:`pending` at each step boundary (two
    attribute loads when nothing changed — the same armed-idle budget as
    the profile controller) and :meth:`applied` after it finished
    resharding. Tests and bench drive boundaries in-process through
    :meth:`trigger`, the exact twin of the AM's file broadcast.
    """

    def __init__(self, settings: ElasticSettings, *, watch: bool = True):
        self.settings = settings
        self.members: tuple[int, ...] = tuple(range(settings.members))
        self.generation = 0
        self._pending: GenerationRecord | None = None
        self._last_seen_gen = 0
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self.journal: ElasticJournal | None = None
        if settings.app_dir:
            self.journal = ElasticJournal(
                journal_path(settings.app_dir, settings.member),
                member=settings.member, members=settings.members,
            )
        if settings.app_dir and watch:
            # synchronous first check: a generation declared while the
            # trainer was still compiling is honoured at the first boundary
            self.check()
            self._thread = threading.Thread(
                target=self._watch_loop, daemon=True, name="tony-elastic-watch"
            )
            self._thread.start()

    # --- broadcast watching ---------------------------------------------------

    def _watch_loop(self) -> None:
        while not self._stop_evt.wait(self.settings.poll_interval_s):
            try:
                self.check()
            except Exception:
                log.debug("elastic generation check failed", exc_info=True)

    def check(self) -> None:
        rec = read_generation(self.settings.app_dir)
        if rec is None or rec.generation <= self._last_seen_gen:
            return
        self._last_seen_gen = rec.generation
        if rec.boundary == "start":
            # initial declaration: adopt the number, no boundary
            self.generation = max(self.generation, rec.generation)
            return
        # ALWAYS queue the newest record — never judge "no membership
        # change" here against self.members: that races with a reshard in
        # flight on the fit thread (a grow declared while the shrink is
        # still applying would compare against the PRE-shrink membership
        # and be swallowed as an echo). The fit loop adopts true no-ops
        # at the boundary, where membership is settled.
        self._pending = rec
        log.warning(
            "elastic generation %d pending (%s): members -> %s",
            rec.generation, rec.boundary, list(rec.members),
        )

    def trigger(self, rec: GenerationRecord) -> None:
        """Arm a membership change in-process (tests, bench) — the twin of
        the AM broadcast."""
        self._last_seen_gen = max(self._last_seen_gen, rec.generation)
        self._pending = rec

    # --- fit-loop side --------------------------------------------------------

    def pending(self) -> GenerationRecord | None:
        return self._pending

    def applied(self, rec: GenerationRecord) -> None:
        """The fit loop finished resharding onto ``rec``'s membership."""
        self.members = tuple(sorted(rec.members))
        self.generation = rec.generation
        if self._pending is rec:
            self._pending = None

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self.journal is not None:
            self.journal.close()


# --- process-global arming (fit() entry) -------------------------------------

_controller: ElasticController | None = None


def active_controller() -> ElasticController | None:
    return _controller


def install(controller: ElasticController) -> ElasticController:
    global _controller
    if _controller is not None:
        _controller.close()
    _controller = controller
    return _controller


def uninstall() -> None:
    global _controller
    if _controller is not None:
        _controller.close()
    _controller = None


def install_from_env() -> ElasticController | None:
    """Arm this process from the TONY_ELASTIC* env the ElasticRuntime
    exported (idempotent; returns the active controller). No-op outside an
    elastic job."""
    if _controller is not None:
        return _controller
    settings = ElasticSettings.from_env()
    if settings is None:
        return None
    return install(ElasticController(settings))


__all__ = [
    "DEFAULT_TOLERANCE",
    "ENV_ENABLED",
    "ENV_MEMBER",
    "ENV_MEMBERS",
    "ENV_POLL",
    "ENV_SHADOW",
    "ElasticController",
    "ElasticJournal",
    "ElasticSettings",
    "GenerationRecord",
    "active_controller",
    "elastic_dir",
    "generation_path",
    "history_path",
    "install",
    "install_from_env",
    "journal_files",
    "journal_path",
    "read_generation",
    "read_history",
    "read_journal",
    "uninstall",
    "write_generation",
]

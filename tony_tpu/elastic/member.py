"""Elastic gang member agent: the seat-holder process for non-trainer hosts.

In an elastic training gang the coordinator (member 0) owns the training
loop; every other member's *user process* is this agent. It holds the
member's seat in the gang — the executor wrapping it registers with the
AM and heartbeats, which is the liveness signal the membership protocol
rides — watches the generation broadcast so membership changes land in
its log (and on the merged trace), and exits promptly when fenced.

On a real TPU fleet the agent's host contributes its chips to the shared
mesh; chaos ``kill_container`` aimed at this process IS the preemption
under test: the executor's process group dies, the AM detects the loss,
declares a shrink generation, and the trainer reshards — no agent logic
is on that path, which is the point (a preempted host gets no chance to
run cleanup).

Run as ``python -m tony_tpu.elastic.member`` (the job.<type>.command of
elastic member task types).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import time

from tony_tpu.elastic.protocol import ENV_MEMBER, read_generation
from tony_tpu.obs import trace

log = logging.getLogger(__name__)


def main() -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s MEMBER %(levelname)s %(name)s: %(message)s",
    )
    trace.install_from_env()  # join the job's trace spine (no-op untraced)
    app_dir = os.environ.get("TONY_APP_DIR", "")
    member = int(os.environ.get(ENV_MEMBER, os.environ.get("TONY_PROCESS_ID", "0")))
    stop = {"fenced": False}

    def _term(*_):
        stop["fenced"] = True

    signal.signal(signal.SIGTERM, _term)
    log.info("elastic member %d holding its seat (app_dir=%s)", member, app_dir)
    # membership self-fence patience: a RELAUNCHED agent necessarily boots
    # while the broadcast still shows the shrink generation that removed
    # its seat — the AM declares the grow only after this agent's executor
    # registers. Exclusion is therefore only a fence once it PERSISTS; a
    # genuinely fenced ghost also gets ABORT on its (stale-attempt)
    # heartbeat long before this timer, so the file path is pure backstop.
    fence_after_s = 10.0
    excluded_since: float | None = None
    with trace.span("elastic.member", member=member):
        last_gen = -1
        while not stop["fenced"]:
            rec = read_generation(app_dir) if app_dir else None
            if rec is not None and rec.generation != last_gen:
                last_gen = rec.generation
                log.info(
                    "generation %d (%s): members=%s",
                    rec.generation, rec.boundary, list(rec.members),
                )
                trace.instant(
                    "elastic.member_generation", member=member,
                    generation=rec.generation, boundary=rec.boundary,
                )
            if rec is not None and member not in rec.members:
                if excluded_since is None:
                    excluded_since = time.monotonic()
                elif time.monotonic() - excluded_since > fence_after_s:
                    log.warning(
                        "member %d fenced out of generation %d for %.0fs; "
                        "exiting", member, rec.generation, fence_after_s,
                    )
                    break
            else:
                excluded_since = None
            time.sleep(0.2)
    trace.uninstall()
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""tony-tpu: a TPU-native distributed-training orchestration framework.

A from-scratch rebuild of the capabilities of yuriyao/TonY (LinkedIn's
"TensorFlow on YARN" orchestrator; see SURVEY.md) designed TPU-first:

- The control plane (client -> ApplicationMaster -> TaskExecutor) is a gRPC
  service instead of Hadoop RPC (reference: tony-core/.../rpc/ApplicationRpc,
  per SURVEY.md section 2 -- reference mount was empty, citations are to the
  expected upstream layout).
- The resource substrate is a pluggable ``ClusterBackend`` with a ``tpu``
  resource type (the ``yarn.io/gpu`` analogue) instead of YARN RM/NM.
- Framework runtimes bootstrap ``jax.distributed.initialize`` with an
  AM-assigned coordinator address and process id (``JaxTpuRuntime``), with
  TF_CONFIG / PyTorch env / Horovod-style rendezvous adapters for parity.
- The data plane is compiled XLA collectives over ICI/DCN (psum, ppermute,
  all_gather under pjit/shard_map) -- there is no NCCL/Gloo surface.
- A training-side parallelism library (DP/FSDP/TP/PP/EP + ring-attention
  context parallelism with Pallas kernels) that the reference delegated to
  user frameworks is first-class here.
"""

__version__ = "0.1.0"

__all__ = ["__version__"]

"""Device-mesh construction: the ICI x DCN axis layout.

The reference delegates all parallelism to user frameworks (SURVEY.md section 2
"Parallelism strategies": TonY orchestrates NCCL/Gloo rings via env variables,
implements none itself). Here the mesh is first-class: axes

- ``dp``   -- pure data parallel (params replicated, grads psum'd)
- ``pp``   -- pipeline parallel (layer stages, GPipe microbatch schedule)
- ``fsdp`` -- data parallel with parameter/optimizer sharding (ZeRO-style)
- ``ep``   -- expert parallel (MoE expert dim; doubles as a batch axis)
- ``tp``   -- tensor (Megatron-style) parallel over heads / ffn hidden
- ``sp``   -- sequence/context parallel (ring attention over lax.ppermute)

Collectives over these axes ride ICI within a slice; a multi-slice job maps its
slice-crossing axis (usually ``dp``) onto DCN by putting it outermost, which is
what ``mesh_utils.create_device_mesh`` produces for contiguous device order.
``pp`` sits next (stage hops are one point-to-point ppermute per tick —
latency-tolerant); bandwidth-hungry fsdp/ep/tp/sp stay innermost on the
shortest ICI paths. The GPipe schedule lives in tony_tpu.parallel.pipeline,
the expert dispatch in tony_tpu.parallel.moe; both are reachable from the
trainer via this mesh (LlamaConfig n_experts / FitConfig mesh_shape.pp).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

# Canonical axis order: slice-crossing / outermost first.
MESH_AXES = ("dp", "pp", "fsdp", "ep", "tp", "sp")


@dataclass(frozen=True)
class MeshShape:
    """Per-axis sizes. Product must equal the number of devices used."""

    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def sizes(self) -> tuple[int, ...]:
        return (self.dp, self.pp, self.fsdp, self.ep, self.tp, self.sp)

    @property
    def n_devices(self) -> int:
        return math.prod(self.sizes)

    def __post_init__(self) -> None:
        for name, v in zip(MESH_AXES, self.sizes):
            if v < 1:
                raise ValueError(f"mesh axis {name!r} must be >= 1, got {v}")


def default_shape(n_devices: int, *, tp: int = 1, sp: int = 1) -> MeshShape:
    """FSDP-first default: all non-tp/sp parallelism goes to ``fsdp``.

    FSDP is the right default on TPU (params sharded over ICI, all-gathered
    per-layer: HBM-bound win) the way plain DP was the reference's Horovod
    default.
    """
    if n_devices % (tp * sp):
        raise ValueError(f"{n_devices} devices not divisible by tp*sp={tp * sp}")
    return MeshShape(dp=1, fsdp=n_devices // (tp * sp), tp=tp, sp=sp)


# The mesh model-level hooks (attention_impl='ring'/'flash') resolve against;
# build_mesh registers every mesh it constructs.
_DEFAULT_MESH: Mesh | None = None


def set_default_mesh(mesh: Mesh | None) -> None:
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


def get_default_mesh() -> Mesh | None:
    return _DEFAULT_MESH


def inside_manual_region() -> bool:
    """True when tracing inside a shard_map manual computation (e.g. a pp
    pipeline stage). A merely non-empty abstract mesh is NOT enough: a
    ``jax.sharding.use_mesh`` context also sets one, with Auto/Explicit axis
    types — only Manual axes mean an enclosing shard_map region that shardy
    forbids re-binding collective axes inside."""
    try:
        from jax.sharding import AxisType, get_abstract_mesh
    except ImportError:
        # old jax (no abstract-mesh typing): shard_map binds its manual
        # axes into the tracing axis env, so a non-empty env means we are
        # tracing inside one (also true under pmap/named vmap — both want
        # the region-local path here anyway). The accessor lives in
        # jax._src.core on this line (jax.core only has a deprecation
        # stub for it).
        try:
            from jax._src.core import get_axis_env
        except ImportError:
            return False
        env = get_axis_env()
        return bool(getattr(env, "axis_sizes", None))

    mesh = get_abstract_mesh()
    if mesh is None or not mesh.shape_tuple:
        return False
    return any(t == AxisType.Manual for t in mesh.axis_types)


def build_mesh(shape: MeshShape | None = None, devices: list | None = None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with the canonical axis names.

    ``devices`` defaults to all local devices; shape defaults to
    ``default_shape(len(devices))``. Uses ``mesh_utils.create_device_mesh`` so
    that physically-near devices land on inner (tp/sp) axes -- inner axes carry
    the latency-sensitive collectives and should ride the shortest ICI hops.
    """
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = default_shape(len(devices))
    if shape.n_devices > len(devices):
        raise ValueError(
            f"mesh shape {shape.sizes} needs {shape.n_devices} devices, "
            f"got {len(devices)}"
        )
    if shape.n_devices < len(devices):
        # Truncation is only safe single-process (sub-meshes of one host's
        # devices, mostly tests): multi-host, the first-N global devices can
        # exclude every device of some process, which then fails far from the
        # config mistake. Loud warning either way — idle chips are a bug.
        import logging

        if jax.process_count() > 1:
            raise ValueError(
                f"mesh shape {shape.sizes} uses {shape.n_devices} of "
                f"{len(devices)} devices; undersized meshes are not allowed "
                "multi-host (some processes would own no mesh device)"
            )
        logging.getLogger(__name__).warning(
            "mesh shape %s uses only %d of %d devices; %d idle",
            shape.sizes, shape.n_devices, len(devices), len(devices) - shape.n_devices,
        )
    devices = list(devices)[: shape.n_devices]
    try:
        dev_array = mesh_utils.create_device_mesh(shape.sizes, devices=devices)
    except (ValueError, AssertionError):
        # Virtual/CPU device sets lack topology metadata; fall back to raveled order.
        dev_array = np.asarray(devices).reshape(shape.sizes)
    return Mesh(dev_array, MESH_AXES)


def build_multislice_mesh(
    per_slice: MeshShape,
    n_slices: int,
    devices: list | None = None,
) -> Mesh:
    """ICI x DCN hybrid mesh for multi-slice jobs.

    The slice-crossing axis is ``dp`` (gradient all-reduce tolerates DCN
    latency; everything bandwidth-hungry — fsdp/tp/sp — stays inside a
    slice's ICI). The resulting mesh has the same four canonical axes, with
    dp = n_slices * per_slice.dp; on real multi-slice TPU metadata,
    mesh_utils.create_hybrid_device_mesh lays devices out so the outer dp
    factor crosses DCN. SURVEY.md section 2 "Distributed communication
    backend": multi-slice via a dcn-parallel outer axis.
    """
    if devices is None:
        devices = jax.devices()
    total = per_slice.n_devices * n_slices
    if total != len(devices):
        raise ValueError(
            f"{n_slices} slices x {per_slice.sizes} = {total} devices, "
            f"got {len(devices)}"
        )
    ici_shape = per_slice.sizes
    dcn_shape = (n_slices,) + (1,) * (len(MESH_AXES) - 1)
    try:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices
        )
    except (ValueError, AssertionError, KeyError, AttributeError):
        # No slice metadata (CPU/virtual devices): raveled fallback keeps the
        # same logical shape so sharding code still compiles.
        dev_array = np.asarray(devices).reshape(
            tuple(i * d for i, d in zip(ici_shape, dcn_shape))
        )
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh() -> Mesh:
    """A 1x1x1x1 mesh over one device -- lets single-chip code share the
    sharded code path (all PartitionSpecs collapse to replication)."""
    return build_mesh(MeshShape(), devices=jax.devices()[:1])

"""Ring attention: sequence/context parallelism over an ICI ring.

Absent from the reference in any form (SURVEY.md section 5 "Long-context":
TonY never touches sequence length); required here as a first-class library
layer. Design follows the blockwise/ring-attention pattern (Liu et al.,
arXiv:2310.01889) expressed the TPU way: the sequence axis is sharded over
the ``sp`` mesh axis, K/V chunks rotate around the ring with
``lax.ppermute`` (one ICI hop per step), and each device folds incoming
chunks into an online-softmax accumulator — peak memory per device is
O(S/n), compute overlaps with the permute because XLA pipelines the loop.

Numerics: scores and the softmax accumulator are float32 regardless of input
dtype; masked positions use a large-negative filler instead of -inf so fully
masked chunks stay NaN-free (the j=0 diagonal chunk always has unmasked
entries, which seeds the running max with a finite value).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _chunk_scores(q, k, scale, q_start, k_start, causal):
    """fp32 scores [B,H,Sq,Sk] with causal mask at global positions."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        q_pos = q_start + jnp.arange(q.shape[1])
        k_pos = k_start + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    return s


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Per-device ring attention; call inside shard_map.

    q, k, v: [B, S_local, H, head_dim] — this device's contiguous sequence
    chunk (chunk index == its position along ``axis_name``). Returns the
    attention output for the local queries, exact (not approximate).
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Derive the accumulators from q (not jnp.zeros) so they carry the same
    # varying-manual-axes type as the loop outputs (jax>=0.9 shard_map typing).
    zero = jnp.swapaxes(q.astype(jnp.float32).sum(-1), 1, 2) * 0.0  # [B,H,S]
    o0 = jnp.broadcast_to(zero[..., None], (B, H, S, D))
    m0 = zero + _NEG
    l0 = zero

    def body(j, carry):
        k_cur, v_cur, o, m, l = carry
        kv_idx = (my - j) % n  # which chunk this device holds at step j
        s = _chunk_scores(q, k_cur, scale, my * S, kv_idx * S, causal)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        # rotate K/V one step around the ring (ICI-neighbour hop)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, o, m_new, l

    _, _, o, _, l = lax.fori_loop(0, n, body, (k, v, o0, m0, l0))
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, *, axis_name: str = "sp", causal: bool = True
):
    """AttnFn closure: full arrays in, shard_map over the mesh inside.

    Batch goes over dp/fsdp, sequence over ``axis_name``, heads over tp (all
    only if present in the mesh); the ring collective runs over ``axis_name``.
    Plugs into llama.LlamaConfig(attention_impl='ring') via set_default_mesh.
    """
    from tony_tpu.parallel.mesh import inside_manual_region
    from tony_tpu.parallel.sharding import attn_spec

    spec = attn_spec(mesh, seq_axis=axis_name)
    inner = partial(ring_attention_local, axis_name=axis_name, causal=causal)

    def attn(q, k, v, cfg=None):
        if inside_manual_region():
            # shardy cannot re-bind collective axes inside a parent manual
            # computation (tested: both full-manual and sp-only nesting are
            # rejected by the sdy verifier) — pp_loss_from_pairs raises
            # before reaching here; this guards direct shard_map users
            raise NotImplementedError(
                "ring attention cannot run inside another shard_map region "
                "(e.g. a pp pipeline stage); use attention_impl='flash' or "
                "'dot' with pp, or drop pp and shard the sequence with sp"
            )
        return jax.shard_map(
            lambda a, b, c: inner(a, b, c),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)

    return attn


def ring_attention(q, k, v, cfg=None):
    """Model hook (AttnFn signature): uses the registered default mesh."""
    from tony_tpu.parallel.mesh import get_default_mesh

    mesh = get_default_mesh()
    if mesh is None:
        raise RuntimeError(
            "ring attention needs a mesh: call "
            "tony_tpu.parallel.set_default_mesh(mesh) (fit() does this "
            "automatically for its training mesh)"
        )
    return make_ring_attention(mesh)(q, k, v, cfg)


__all__ = [
    "make_ring_attention",
    "ring_attention",
    "ring_attention_local",
]

"""Ring attention: sequence/context parallelism over an ICI ring.

Absent from the reference in any form (SURVEY.md section 5 "Long-context":
TonY never touches sequence length); required here as a first-class library
layer. Design follows the blockwise/ring-attention pattern (Liu et al.,
arXiv:2310.01889) expressed the TPU way: the sequence axis is sharded over
the ``sp`` mesh axis, K/V chunks rotate around the ring with
``lax.ppermute`` (one ICI hop per step), and each device folds incoming
chunks into an online-softmax accumulator — peak memory per device is
O(S/n), compute overlaps with the permute because XLA pipelines the loop.

Numerics: scores and the softmax accumulator are float32 regardless of input
dtype; masked positions use a large-negative filler instead of -inf so fully
masked chunks stay NaN-free (the j=0 diagonal chunk always has unmasked
entries, which seeds the running max with a finite value).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.ops.compat import axis_size as _axis_size, shard_map_compat as _shard_map

_NEG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _chunk_scores(q, k, scale, q_start, k_start, causal):
    """fp32 scores [B,H,Sq,Sk] with causal mask at global positions."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        q_pos = q_start + jnp.arange(q.shape[1])
        k_pos = k_start + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    return s


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Per-device ring attention; call inside shard_map.

    q, k, v: [B, S_local, H, head_dim] — this device's contiguous sequence
    chunk (chunk index == its position along ``axis_name``). Returns the
    attention output for the local queries, exact (not approximate).
    """
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Derive the accumulators from q (not jnp.zeros) so they carry the same
    # varying-manual-axes type as the loop outputs (jax>=0.9 shard_map typing).
    zero = jnp.swapaxes(q.astype(jnp.float32).sum(-1), 1, 2) * 0.0  # [B,H,S]
    o0 = jnp.broadcast_to(zero[..., None], (B, H, S, D))
    m0 = zero + _NEG
    l0 = zero

    def body(j, carry):
        k_cur, v_cur, o, m, l = carry
        kv_idx = (my - j) % n  # which chunk this device holds at step j
        s = _chunk_scores(q, k_cur, scale, my * S, kv_idx * S, causal)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        # rotate K/V one step around the ring (ICI-neighbour hop)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, o, m_new, l

    _, _, o, _, l = lax.fori_loop(0, n, body, (k, v, o0, m0, l0))
    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# --- ring flash: the Pallas kernel as the per-chunk inner -------------------


def _fold(x):
    """[B,S,H,D] -> [B*H,S,D] (the flash kernels' layout)."""
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _unfold(x, B, H):
    BH, S, D = x.shape
    return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention_local(q, k, v, axis_name="sp", blk_q=512, blk_k=512):
    """Ring attention whose per-chunk inner is the Pallas flash kernel.

    The dense ring inner materialises [S_local, S_local] fp32 scores per
    step; this one streams them through VMEM, so the per-device sequence
    chunk can itself be long (the production long-context configuration:
    ring over ``sp`` × flash within the chunk). Causal, exact; same
    [B, S_local, H, D] contract as ring_attention_local. The backward is
    the blockwise decomposition: each chunk's dq/dk/dv kernels run against
    the GLOBAL logsumexp, with dk/dv accumulators riding the ring.
    """
    out, _ = _ring_flash_fwd_local(q, k, v, axis_name, blk_q, blk_k)
    return out


def _chunk_rel(my, kv_idx):
    """0 = fully visible (kv before q), 1 = diagonal (causal), 2 = skip."""
    return jnp.where(kv_idx < my, 0, jnp.where(kv_idx == my, 1, 2))


def _check_blocks(S: int, blk_q: int, blk_k: int) -> tuple[int, int]:
    bq, bk = min(blk_q, S), min(blk_k, S)
    if S % bq or S % bk:
        raise ValueError(
            f"per-device seq chunk {S} must be a multiple of the flash "
            f"block sizes ({bq}, {bk}); adjust flash_block_q/k or sp"
        )
    return bq, bk


def _ring_flash_fwd_local(q, k, v, axis_name, blk_q, blk_k):
    from tony_tpu.ops.attention import flash_fwd_pass

    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    scale = 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qf = _fold(q)
    bq, bk = _check_blocks(S, blk_q, blk_k)

    def run(causal):
        def f(k_cur, v_cur):
            return flash_fwd_pass(
                qf, _fold(k_cur), _fold(v_cur), scale=scale,
                blk_q=bq, blk_k=bk, causal=causal,
                heads=H, kv_heads=Hkv,
            )
        return f

    def skip(k_cur, v_cur):
        zero_o = jnp.zeros_like(qf)
        # derive from q so the branch output carries the varying-axes type
        neg_lse = (qf.astype(jnp.float32).sum() * 0.0) + jnp.full(
            (B * H, 1, S), _NEG, jnp.float32
        )
        return zero_o, neg_lse

    # accumulators derived from q so they carry the varying-axes type
    o0 = qf.astype(jnp.float32) * 0.0
    lse0 = jnp.full((B * H, 1, S), _NEG, jnp.float32) + (
        qf.astype(jnp.float32).sum() * 0.0
    )

    def body(j, carry):
        k_cur, v_cur, o_num, lse = carry
        kv_idx = (my - j) % n
        out_c, lse_c = lax.switch(
            _chunk_rel(my, kv_idx),
            [run(False), run(True), skip],
            k_cur, v_cur,
        )
        new_lse = jnp.logaddexp(lse, lse_c)
        w_old = jnp.exp(lse - new_lse)[:, 0, :, None]     # [BH,S,1]
        w_new = jnp.exp(lse_c - new_lse)[:, 0, :, None]
        o_num = o_num * w_old + out_c.astype(jnp.float32) * w_new
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return k_next, v_next, o_num, new_lse

    _, _, o_num, lse = lax.fori_loop(0, n, body, (k, v, o0, lse0))
    out = _unfold(o_num.astype(q.dtype), B, H)
    return out, lse


def _ring_flash_fwd_rule(q, k, v, axis_name, blk_q, blk_k):
    out, lse = _ring_flash_fwd_local(q, k, v, axis_name, blk_q, blk_k)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd_rule(axis_name, blk_q, blk_k, res, g):
    from tony_tpu.ops.attention import flash_dq_pass, flash_dkv_pass

    q, k, v, out, lse = res
    n = _axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    scale = 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qf, dof = _fold(q), _fold(g)
    delta = jnp.sum(
        dof.astype(jnp.float32) * _fold(out).astype(jnp.float32), axis=-1
    )[:, None, :]
    bq, bk = _check_blocks(S, blk_q, blk_k)
    kw = dict(scale=scale, blk_q=bq, blk_k=bk, heads=H, kv_heads=Hkv)

    def run(causal):
        def f(kf, vf):
            dq_c = flash_dq_pass(qf, kf, vf, dof, lse, delta,
                                 causal=causal, **kw)
            dk_c, dv_c = flash_dkv_pass(qf, kf, vf, dof, lse, delta,
                                        causal=causal, **kw)
            return dq_c, dk_c, dv_c
        return f

    def skip(kf, vf):
        return jnp.zeros_like(qf), jnp.zeros_like(kf), jnp.zeros_like(vf)

    def body(j, carry):
        k_cur, v_cur, dk_cur, dv_cur, dq_acc = carry
        kv_idx = (my - j) % n
        dq_c, dk_c, dv_c = lax.switch(
            _chunk_rel(my, kv_idx),
            [run(False), run(True), skip],
            _fold(k_cur), _fold(v_cur),
        )
        dq_acc = dq_acc + dq_c.astype(jnp.float32)
        dk_cur = dk_cur + _unfold(dk_c, B, Hkv).astype(jnp.float32)
        dv_cur = dv_cur + _unfold(dv_c, B, Hkv).astype(jnp.float32)
        # the grad accumulators ride the ring WITH their chunk: after n
        # rotations each chunk's dk/dv arrive back at its owner having
        # collected every device's contribution
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        dk_next = lax.ppermute(dk_cur, axis_name, perm)
        dv_next = lax.ppermute(dv_cur, axis_name, perm)
        return k_next, v_next, dk_next, dv_next, dq_acc

    dk0 = k.astype(jnp.float32) * 0.0
    dv0 = v.astype(jnp.float32) * 0.0
    dq0 = qf.astype(jnp.float32) * 0.0
    _, _, dk, dv, dqf = lax.fori_loop(0, n, body, (k, v, dk0, dv0, dq0))
    return (_unfold(dqf, B, H).astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


ring_flash_attention_local.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def make_ring_attention(
    mesh: Mesh, *, axis_name: str = "sp", causal: bool = True
):
    """AttnFn closure: full arrays in, shard_map over the mesh inside.

    Batch goes over dp/fsdp, sequence over ``axis_name``, heads over tp (all
    only if present in the mesh); the ring collective runs over ``axis_name``.
    Plugs into llama.LlamaConfig(attention_impl='ring') via set_default_mesh.
    """
    from tony_tpu.parallel.mesh import inside_manual_region
    from tony_tpu.parallel.sharding import attn_spec

    spec = attn_spec(mesh, seq_axis=axis_name)
    inner = partial(ring_attention_local, axis_name=axis_name, causal=causal)

    def attn(q, k, v, cfg=None):
        if inside_manual_region():
            # shardy cannot re-bind collective axes inside a parent manual
            # computation (tested: both full-manual and sp-only nesting are
            # rejected by the sdy verifier) — pp_loss_from_pairs raises
            # before reaching here; this guards direct shard_map users
            raise NotImplementedError(
                "ring attention cannot run inside another shard_map region "
                "(e.g. a pp pipeline stage); use attention_impl='flash' or "
                "'dot' with pp, or drop pp and shard the sequence with sp"
            )
        return _shard_map(
            lambda a, b, c: inner(a, b, c),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)

    return attn


def ring_attention(q, k, v, cfg=None):
    """Model hook (AttnFn signature): uses the registered default mesh."""
    from tony_tpu.parallel.mesh import get_default_mesh

    mesh = get_default_mesh()
    if mesh is None:
        raise RuntimeError(
            "ring attention needs a mesh: call "
            "tony_tpu.parallel.set_default_mesh(mesh) (fit() does this "
            "automatically for its training mesh)"
        )
    return make_ring_attention(mesh)(q, k, v, cfg)


def make_ring_flash_attention(mesh: Mesh, *, axis_name: str = "sp"):
    """AttnFn closure for ring × flash: sequence over the ring, the Pallas
    kernel within each chunk — the production long-context configuration."""
    from tony_tpu.parallel.mesh import inside_manual_region
    from tony_tpu.parallel.sharding import attn_spec

    spec = attn_spec(mesh, seq_axis=axis_name)

    def attn(q, k, v, cfg=None):
        if inside_manual_region():
            raise NotImplementedError(
                "ring-flash attention cannot run inside another shard_map "
                "region (e.g. a pp pipeline stage)"
            )
        # same defaults as flash_attention (1024/1024 measured fastest on
        # v5e) so the two entries to the identical kernel never diverge
        blk_q = getattr(cfg, "flash_block_q", None) or 1024
        blk_k = getattr(cfg, "flash_block_k", None) or 1024
        # GQA under tp: kv heads must divide tp or fall back to expansion
        # (mirrors sharded_flash_attention)
        tp = int(mesh.shape.get("tp", 1))
        if k.shape[2] % tp:
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        # Interpreter-mode pallas (CPU tests) trips jax's varying-axes
        # checker on the kernel's internal dynamic_slice with unvarying
        # grid indices, so the checker is off ONLY there; on real TPU it
        # stays on — same vma discipline as the dense ring path.
        from tony_tpu.ops.attention import _use_interpret

        return _shard_map(
            lambda a, b, c: ring_flash_attention_local(
                a, b, c, axis_name, blk_q, blk_k
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=not _use_interpret(),
        )(q, k, v)

    return attn


def ring_flash_attention(q, k, v, cfg=None):
    """Model hook (AttnFn signature): uses the registered default mesh."""
    from tony_tpu.parallel.mesh import get_default_mesh

    mesh = get_default_mesh()
    if mesh is None:
        raise RuntimeError(
            "ring-flash attention needs a mesh: call "
            "tony_tpu.parallel.set_default_mesh(mesh) first"
        )
    return make_ring_flash_attention(mesh)(q, k, v, cfg)


__all__ = [
    "make_ring_attention",
    "make_ring_flash_attention",
    "ring_attention",
    "ring_attention_local",
    "ring_flash_attention",
    "ring_flash_attention_local",
]

"""Ulysses-style sequence parallelism: all-to-all head sharding.

The alternative context-parallel scheme (DeepSpeed-Ulysses, arXiv:2309.14509)
kept for comparison with ring attention: instead of rotating K/V, one
all-to-all re-shards activations from sequence-sharded to head-sharded, full
(exact) attention runs locally over the whole sequence, and a second
all-to-all restores sequence sharding. Cheaper in collective volume than a
full all-gather (each device ends with S x H/n), but requires
n_heads % axis_size == 0 and peak activation memory O(S) per device —
ring attention wins for the longest sequences, Ulysses for head-rich models
on small rings. Exposed through the same AttnFn contract.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh

from tony_tpu.models.llama import dot_attention as _causal_attention
from tony_tpu.ops.compat import axis_size as _axis_size, shard_map_compat as _shard_map


def ulysses_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    attn=_causal_attention,
) -> jax.Array:
    """Per-device Ulysses attention; call inside shard_map.

    q/k/v: [B, S_local, H, D] sequence-sharded chunks. Internally re-shards
    to [B, S, H_local, D] (full sequence, heads split), runs exact attention,
    and re-shards back. ``attn(q, k, v)`` is the local attention function.
    """
    n = _axis_size(axis_name)
    H = q.shape[2]
    if H % n:
        raise ValueError(f"n_heads={H} not divisible by {axis_name} size {n}")

    # seq-sharded -> head-sharded: split heads (axis 2), gather seq (axis 1)
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    # head-sharded -> seq-sharded: split seq, gather heads
    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    return to_seq(attn(to_heads(q), to_heads(k), to_heads(v)))


def make_ulysses_attention(mesh: Mesh, *, axis_name: str = "sp"):
    """AttnFn closure over full arrays (mirror of make_ring_attention)."""
    from tony_tpu.parallel.mesh import inside_manual_region
    from tony_tpu.parallel.sharding import attn_spec

    spec = attn_spec(mesh, seq_axis=axis_name)
    inner = partial(ulysses_attention_local, axis_name=axis_name)

    def attn(q, k, v, cfg=None):
        if inside_manual_region():
            raise NotImplementedError(
                "ulysses attention cannot run inside another shard_map "
                "region (e.g. a pp pipeline stage); use attention_impl="
                "'flash' or 'dot' with pp, or drop pp"
            )
        return _shard_map(
            lambda a, b, c: inner(a, b, c),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )(q, k, v)

    return attn


def ulysses_attention(q, k, v, cfg=None):
    """Model hook (AttnFn signature): uses the registered default mesh."""
    from tony_tpu.parallel.mesh import get_default_mesh

    mesh = get_default_mesh()
    if mesh is None:
        raise RuntimeError(
            "ulysses attention needs a mesh: call "
            "tony_tpu.parallel.set_default_mesh(mesh) (fit() does this "
            "automatically for its training mesh)"
        )
    return make_ulysses_attention(mesh)(q, k, v, cfg)


__all__ = ["make_ulysses_attention", "ulysses_attention", "ulysses_attention_local"]

"""Mixture-of-Experts with expert parallelism.

Absent from the reference (SURVEY.md section 2 parallelism table: EP "—").
Three interchangeable dispatch implementations behind ``MoEConfig.dispatch``:

- ``'einsum'`` — GShard/Switch (arXiv:2006.16668) dense one-hot
  dispatch/combine einsums over fixed ``[E, C]`` capacity slots. MXU-friendly
  and the parity reference, but the routing einsums cost ~2x the expert FFN
  at bench shapes and overflow tokens are dropped.
- ``'gather'`` — scatter/gather into the same capacity slots: zero routing
  matmul FLOPs, identical drop semantics (docs/PERF.md round 4).
- ``'grouped'`` — dropless sorted grouped GEMM (MegaBlocks,
  arXiv:2211.15841): routes are sorted by expert into ragged contiguous
  groups and the expert FFN runs as a grouped matmul over block-aligned row
  tiles (tony_tpu.ops.grouped_mm — a lax.scan fallback anywhere, a Pallas
  kernel on TPU via ``gmm_impl``). No capacity: nothing padded beyond one
  row tile per expert, nothing dropped.

All routing statistics (softmax, gates, aux loss) are float32 regardless of
activation dtype; expert FFN compute follows the input dtype (bf16 on TPU).
The expert dim is a logical axis ("expert") the sharding rules map onto the
mesh's ``ep`` axis; the grouped path additionally ships an explicit
shard_map-over-ep formulation (each shard runs the grouped FFN for its local
experts only and the combine is a psum) used automatically when a default
mesh with ``ep > 1`` is registered. ``MoEConfig.overlap_impl`` decomposes
that combine into per-token-chunk partial psums so expert compute overlaps
combine traffic (tony_tpu.ops.moe_overlap, docs/PERF.md round 20).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MoEConfig:
    dim: int
    ffn_dim: int
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    # 'grouped' (dropless sorted grouped GEMM — no capacity slots at all;
    # the DEFAULT since round 20, when its PR-4 bench gate "grouped beats
    # gather tokens/s" was measured to hold and `grouped_vs_gather` became
    # a perf-diff-judged ratio, docs/PERF.md), 'gather' (scatter/gather
    # capacity dispatch, O(T*D) data movement — one knob away), or
    # 'einsum' (dense one-hot dispatch, O(T*E*C*D) matmul FLOPs — the
    # reference implementation the others are parity-tested against).
    dispatch: str = "grouped"
    # dispatch='grouped': row-tile size of the grouped GEMM; each expert's
    # ragged group is padded up to a multiple of this (keep it a multiple
    # of 16 so bf16 sublane tiling is happy on TPU)
    group_block: int = 128
    # dispatch='grouped': 'scan' (pure-XLA lax.scan over row tiles — CPU,
    # shard_map and ep-mesh safe, the default) | 'pallas' (TPU kernel with
    # scalar-prefetched tile->expert map; interpret mode on CPU)
    gmm_impl: str = "scan"
    # dispatch='grouped' on an ep mesh: 'off' keeps the single blocking
    # post-FFN psum; 'scan' | 'pallas' decompose it into per-token-chunk
    # partial combines so later chunks' expert FFN overlaps earlier chunks'
    # combine traffic (tony_tpu.ops.moe_overlap, docs/PERF.md round 20).
    # The impl names the chunk FFN's grouped-GEMM kernel; the schedule is
    # identical. Declines cleanly (single psum) when the chunk split
    # doesn't divide, and rides the ep path's own fallbacks otherwise.
    overlap_impl: str = "off"
    # overlap_impl != 'off': tokens per combine chunk, per shard (0 auto-
    # picks the largest clean split in {4,3,2} chunks; a measured value
    # comes from ops.moe_overlap.chunk_tokens_from_report). Must divide
    # the per-shard token count or the overlap declines to the single psum.
    overlap_chunk: int = 0

    def capacity(self, n_tokens: int) -> int:
        """Per-expert token slots; static given the (padded) token count.

        Rounded up to a multiple of 8 so the [E, C, D] dispatch buffers tile
        cleanly on the TPU sublane dim (fp32 min tile is 8 rows)."""
        cap = max(
            1,
            int(math.ceil(self.capacity_factor * self.top_k * n_tokens / self.n_experts)),
        )
        return -(-cap // 8) * 8


def logical_axes() -> dict[str, tuple[str | None, ...]]:
    """Sharding names; "expert" maps to a mesh axis via the rules table."""
    return {
        "router": ("embed", "expert"),
        "w1": ("expert", "embed", "ffn"),
        "w3": ("expert", "embed", "ffn"),
        "w2": ("expert", "ffn", "embed"),
    }


def init_moe_params(rng: jax.Array, cfg: MoEConfig, dtype=jnp.bfloat16) -> dict[str, Any]:
    k0, k1, k2, k3 = jax.random.split(rng, 4)
    d, f, e = cfg.dim, cfg.ffn_dim, cfg.n_experts

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": dense(k0, (d, e), d).astype(jnp.float32),  # routing in fp32
        "w1": dense(k1, (e, d, f), d),
        "w3": dense(k2, (e, d, f), d),
        "w2": dense(k3, (e, f, d), f),
    }


def _top_k_select(probs: jax.Array, cfg: MoEConfig):
    """One vectorized top-k routing pass shared by every dispatch impl.

    probs: [T, E]. Returns ``(experts [T, k] int32, gates [T, k] f32,
    pos [T, k] int32, aux f32 scalar)`` — each token's chosen experts, their
    router probabilities, and the token's position in each chosen expert's
    queue. Selection and position semantics are identical to the k-round
    argmax-and-mask loop this replaces: ``lax.top_k`` breaks ties toward the
    lower expert index (as repeated argmax did), and queue positions are
    assigned in round-major order (every token's round-0 pick queues before
    any round-1 pick) via a single cumsum over the [k*T, E] route sequence.
    All statistics are float32 regardless of the input dtype.
    """
    T, E = probs.shape
    k = cfg.top_k
    p32 = probs.astype(jnp.float32)
    gates, sel = jax.lax.top_k(p32, k)                        # [T, k] each
    sel = sel.astype(jnp.int32)
    onehot = jax.nn.one_hot(sel, E, dtype=jnp.float32)        # [T, k, E]
    rm = jnp.swapaxes(onehot, 0, 1).reshape(k * T, E)         # round-major
    pos_rm = jnp.cumsum(rm, axis=0) - rm                      # [k*T, E]
    pos = jnp.sum(
        jnp.swapaxes(pos_rm.reshape(k, T, E), 0, 1) * onehot, axis=-1
    ).astype(jnp.int32)                                       # [T, k]
    # load-balancing aux loss (Switch eq. 4): E * sum(frac_routed * mean_prob)
    importance = jnp.sum(jnp.mean(onehot, axis=0), axis=0)    # [E]
    aux = cfg.n_experts * jnp.sum(importance / k * jnp.mean(p32, axis=0))
    return sel, gates, pos, aux


def routing_stats(probs: jax.Array, cfg: MoEConfig) -> dict[str, float]:
    """Routing health under the *capacity* semantics: the route fraction the
    fixed [E, C] slots would drop, and the expert load imbalance (max/mean
    assigned routes). The grouped dispatch drops nothing — these numbers
    quantify exactly what dropless recovers."""
    T = probs.shape[0]
    sel, _, pos, _ = _top_k_select(probs, cfg)
    cap = cfg.capacity(T)
    kept = jnp.mean((pos < cap).astype(jnp.float32))
    counts = jnp.bincount(sel.reshape(-1), length=cfg.n_experts)
    imb = counts.max() / jnp.maximum(jnp.mean(counts.astype(jnp.float32)), 1.0)
    return {
        "dropped_frac": round(float(1.0 - kept), 4),
        "load_imbalance": round(float(imb), 3),
        "capacity": int(cap),
        "capacity_factor": cfg.capacity_factor,
    }


def _top_k_dispatch(probs: jax.Array, cfg: MoEConfig, capacity: int):
    """Build dispatch/combine tensors from router probabilities.

    probs: [T, E] float32. Returns (dispatch [T,E,C] in {0,1}, combine
    [T,E,C] fp32 gates, aux_loss scalar). Tokens beyond an expert's capacity
    are dropped (their combine weight is zero), the Switch/GShard contract.
    """
    E = probs.shape[1]
    sel, gates, pos, aux = _top_k_select(probs, cfg)
    within = (pos < capacity).astype(jnp.float32)             # [T, k]
    oh_e = jax.nn.one_hot(sel, E, dtype=jnp.float32)          # [T, k, E]
    oh_c = jax.nn.one_hot(
        jnp.clip(pos, 0, capacity - 1), capacity, dtype=jnp.float32
    )                                                         # [T, k, C]
    dispatch = jnp.einsum("tke,tkc->tec", oh_e * within[..., None], oh_c)
    combine = jnp.einsum(
        "tke,tkc->tec", oh_e * (gates * within)[..., None], oh_c
    )
    # renormalise combine weights over the selected (and kept) experts
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux


def _moe_gather(params: dict[str, Any], flat: jax.Array, cfg: MoEConfig,
                capacity: int, probs: jax.Array):
    """Scatter/gather dispatch: build the slot->token index map (one scatter
    of int32), gather tokens into [E,C,D], run the expert FFN, and gather
    each token's expert outputs back with gate weighting. Data movement is
    O(E*C*D + k*T*D) with ZERO routing matmul FLOPs — vs the one-hot
    einsums' 2*T*E*C*D FLOPs each way (the measured reason behind the
    round-3 22% MoE MFU; docs/PERF.md). Same capacity/drop semantics as the
    einsum reference."""
    T, D = flat.shape
    E, k = cfg.n_experts, cfg.top_k
    sel, gates, pos, aux = _top_k_select(probs, cfg)
    valid = pos < capacity                                    # [T, k]
    flat_slot = (sel * capacity + jnp.clip(pos, 0, capacity - 1)).reshape(T * k)
    tok = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)

    # slot -> token map; sentinel T points at a zero pad row (empty slots);
    # kept slots are unique (pos is the global occupancy rank), so one
    # scatter covers all k rounds
    target = jnp.where(valid.reshape(T * k), flat_slot, E * capacity)
    slot_token = (
        jnp.full((E * capacity,), T, jnp.int32).at[target].set(tok, mode="drop")
    )

    padded = jnp.concatenate([flat, jnp.zeros((1, D), flat.dtype)], axis=0)
    expert_in = padded[slot_token].reshape(E, capacity, D)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"]))
    h = h * jnp.einsum("ecd,edf->ecf", expert_in, params["w3"])
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w2"])

    # combine: each token gathers its (<= k) expert outputs, gate-weighted
    # and renormalised over the experts that actually kept it
    denom = jnp.maximum(jnp.sum(gates * valid, axis=1), 1e-9)  # [T]
    out_flat = expert_out.reshape(E * capacity, D)
    tok_out = out_flat[jnp.where(valid.reshape(T * k), flat_slot, 0)]
    w = ((gates * valid) / denom[:, None]).reshape(T * k).astype(flat.dtype)
    y = jnp.zeros((T, D), flat.dtype).at[tok].add(w[:, None] * tok_out)
    return y, aux


# --- grouped (dropless) dispatch ----------------------------------------------


def _grouped_ffn(params: dict[str, Any], flat: jax.Array, tok: jax.Array,
                 group: jax.Array, weight: jax.Array, n_groups: int,
                 cfg: MoEConfig) -> jax.Array:
    """Sorted grouped-GEMM expert FFN over a flat route list.

    ``tok``/``group``/``weight``: [R] routes — the token row each route
    reads, its expert group in [0, n_groups), and its final combine weight
    (gate/denom, already zeroed for routes this shard doesn't own). Sorts
    routes by group (stable), scatters token rows into a block-aligned
    padded buffer (tony_tpu.ops.grouped_mm.grouped_layout), runs the SwiGLU
    FFN as three grouped matmuls, and scatter-adds the weighted outputs back
    per token. Returns [T, D]."""
    from tony_tpu.ops.grouped_mm import grouped_layout, grouped_matmul

    T, D = flat.shape
    R = tok.shape[0]
    block = cfg.group_block
    order = jnp.argsort(group, stable=True)
    g_s, tok_s, w_s = group[order], tok[order], weight[order]
    sizes = jnp.bincount(group, length=n_groups)
    n_tiles = -(-R // block) + n_groups  # static bound: 1 part tile/group
    starts, tile_group = grouped_layout(sizes, block, n_tiles)
    compact_start = jnp.cumsum(sizes) - sizes
    dst = starts[g_s] + (jnp.arange(R, dtype=jnp.int32) - compact_start[g_s])

    x_pad = (
        jnp.zeros((n_tiles * block, D), flat.dtype).at[dst].set(flat[tok_s])
    )
    gmm = partial(grouped_matmul, tile_group=tile_group, impl=cfg.gmm_impl)
    h = jax.nn.silu(gmm(x_pad, params["w1"])) * gmm(x_pad, params["w3"])
    y_pad = gmm(h, params["w2"])
    contrib = w_s.astype(flat.dtype)[:, None] * y_pad[dst]
    return jnp.zeros((T, D), flat.dtype).at[tok_s].add(contrib)


def _moe_grouped(params: dict[str, Any], flat: jax.Array, cfg: MoEConfig,
                 probs: jax.Array):
    """Dropless grouped dispatch: every route is served (no capacity), the
    combine weight is the gate renormalised over all k selections."""
    T, _ = flat.shape
    k = cfg.top_k
    sel, gates, _, aux = _top_k_select(probs, cfg)  # pos unused: dropless
    denom = jnp.maximum(jnp.sum(gates, axis=1), 1e-9)
    tok = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
    weight = (gates / denom[:, None]).reshape(T * k)
    y = _grouped_ffn(params, flat, tok, sel.reshape(T * k), weight,
                     cfg.n_experts, cfg)
    return y, aux


def _chunk_ffn(w1, w3, w2, flat_, sel_, weight_, *, cfg: MoEConfig,
               e_local: int):
    """Shard-local grouped FFN over one token chunk's routes — the body of
    ``_moe_grouped_ep.local`` restricted to a row slice, shared with the
    overlapped combine so both schedules run the identical math. Masks the
    chunk's routes by expert ownership (this shard's contiguous e_local
    experts, located by ``axis_index("ep")``) and returns the LOCAL partial
    [t_chunk, D]; the combine psum stays with the caller so forward and
    backward issue matching (single or decomposed) collectives."""
    t, k = flat_.shape[0], cfg.top_k
    off = jax.lax.axis_index("ep") * e_local
    rel = sel_ - off
    mine = (rel >= 0) & (rel < e_local)
    grp = jnp.where(mine, rel, 0).reshape(t * k)
    wgt = jnp.where(mine, weight_, 0.0).reshape(t * k)
    tok = jnp.repeat(jnp.arange(t, dtype=jnp.int32), k)
    return _grouped_ffn({"w1": w1, "w3": w3, "w2": w2}, flat_, tok, grp,
                        wgt, e_local, cfg)


def _moe_grouped_ep(params: dict[str, Any], flat: jax.Array, cfg: MoEConfig,
                    probs: jax.Array, mesh):
    """Expert-parallel grouped dispatch: shard_map where each ``ep`` shard
    runs the grouped FFN for its E/ep local experts only and the
    token-indexed combine is a psum over ``ep``. The token dim stays sharded
    over the data axes (the ``sharded_fused_ce_tokens`` pattern — only ep is
    gathered), so per-shard work scales with the LOCAL batch. Expert-weight
    streaming — the measured round-4 MoE bottleneck — shards by ep; per-
    shard row work stays worst-case-bounded at T_local*k (routes to remote
    experts ride along with zero combine weight — the static-shape cost of
    dropless EP, since routing counts are data-dependent). Routing (fp32)
    and the aux loss stay outside the manual region."""
    from dataclasses import replace

    from jax.sharding import PartitionSpec as P

    from tony_tpu.ops.compat import shard_map_compat
    from tony_tpu.ops.moe_overlap import overlap_chunks, overlapped_combine

    ep = int(mesh.shape["ep"])
    e_local = cfg.n_experts // ep
    sel, gates, _, aux = _top_k_select(probs, cfg)
    denom = jnp.maximum(jnp.sum(gates, axis=1), 1e-9)
    weight = gates / denom[:, None]                           # [T, k]

    axes = set(mesh.axis_names)
    batch = tuple(a for a in ("dp", "fsdp") if a in axes) or None
    n_batch = 1
    for a in batch or ():
        n_batch *= int(mesh.shape[a])

    n_chunks = None
    if cfg.overlap_impl and cfg.overlap_impl != "off":
        # remaining decline leg of the overlap triad (no-ep-axis and
        # already-manual-region decline the whole ep path upstream): a
        # chunk size that doesn't divide the per-shard token rows keeps
        # the single blocking psum below
        n_chunks = overlap_chunks(flat.shape[0] // n_batch, cfg.overlap_chunk)

    def local(w1, w3, w2, flat_, sel_, weight_):
        if n_chunks is not None:
            # the overlap impl names the chunk FFN's grouped-GEMM kernel
            ffn = partial(_chunk_ffn,
                          cfg=replace(cfg, gmm_impl=cfg.overlap_impl),
                          e_local=e_local)
            return overlapped_combine(ffn, "ep", n_chunks, w1, w3, w2,
                                      flat_, sel_, weight_)
        y = _chunk_ffn(w1, w3, w2, flat_, sel_, weight_, cfg=cfg,
                       e_local=e_local)
        return jax.lax.psum(y, "ep")
    wspec = P("ep", None, None)
    bspec = P(batch, None)
    y = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(wspec, wspec, wspec, bspec, bspec, bspec),
        out_specs=bspec,
    )(params["w1"], params["w3"], params["w2"], flat, sel, weight)
    return y, aux


def _moe_grouped_entry(params, flat, cfg, probs):
    from tony_tpu.parallel.mesh import get_default_mesh, inside_manual_region

    mesh = get_default_mesh()
    if (
        mesh is not None
        and int(mesh.shape.get("ep", 1)) > 1
        # the manual region is ep-only: a tp-sharded ffn dim would be
        # all-gathered into every shard inside it (4x weight HBM on tp=4 —
        # exactly the streaming this path exists to shrink), so ep x tp
        # meshes stay on the plain GSPMD path, which partitions the ffn
        # einsums itself
        and int(mesh.shape.get("tp", 1)) == 1
        and cfg.n_experts % int(mesh.shape["ep"]) == 0
        # the ep shard_map keeps tokens sharded over the data axes, which
        # needs an even split; odd batches take the plain GSPMD path
        and flat.shape[0]
        % (int(mesh.shape.get("dp", 1)) * int(mesh.shape.get("fsdp", 1)))
        == 0
        and not inside_manual_region()
    ):
        return _moe_grouped_ep(params, flat, cfg, probs, mesh)
    return _moe_grouped(params, flat, cfg, probs)


def moe_block(params: dict[str, Any], x: jax.Array, cfg: MoEConfig):
    """MoE SwiGLU FFN. x: [B, S, D] -> (y [B, S, D], aux_loss scalar).

    Capacity dispatches ('gather'/'einsum'): dropped (over-capacity) tokens
    pass through with a zero FFN delta, so the residual connection outside
    this block keeps their representation. 'grouped' is dropless — every
    routed token is served.
    """
    B, S, D = x.shape
    T = B * S
    flat = x.reshape(T, D)

    # router math is ALWAYS fp32: a bf16 softmax loses ~2 decimal digits and
    # the aux loss is a mean of small per-expert fractions
    logits = flat.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)

    if cfg.dispatch == "grouped":
        if cfg.overlap_impl not in ("", "off", "scan", "pallas"):
            raise ValueError(
                f"unknown MoE overlap impl {cfg.overlap_impl!r}; expected "
                "'off' | 'scan' | 'pallas'"
            )
        y, aux = _moe_grouped_entry(params, flat, cfg, probs)
        return y.reshape(B, S, D), aux
    capacity = cfg.capacity(T)
    if cfg.dispatch == "gather":
        y, aux = _moe_gather(params, flat, cfg, capacity, probs)
        return y.reshape(B, S, D), aux
    if cfg.dispatch != "einsum":
        raise ValueError(f"unknown MoE dispatch {cfg.dispatch!r}")

    dispatch, combine, aux = _top_k_dispatch(probs, cfg, capacity)
    # [T,E,C]x[T,D] -> [E,C,D]: the EP all-to-all happens inside this einsum
    # when "expert" is mesh-sharded.
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), flat)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"]))
    h = h * jnp.einsum("ecd,edf->ecf", expert_in, params["w3"])
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w2"])
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return y.reshape(B, S, D), aux


__all__ = [
    "MoEConfig", "init_moe_params", "logical_axes", "moe_block",
    "routing_stats",
]

"""Mixture-of-Experts with expert parallelism.

Absent from the reference (SURVEY.md section 2 parallelism table: EP "—").
TPU-native formulation (GShard/Switch style, arXiv:2006.16668): routing is
expressed as dense one-hot dispatch/combine einsums — MXU-friendly, static
shapes (fixed expert capacity, overflow tokens dropped) — and the expert dim
is a logical axis ("expert") that the sharding rules map onto a mesh axis.
With expert weights sharded over that axis, XLA lowers the dispatch/combine
einsums into the all-to-all exchange that dedicated EP backends hand-write.

All routing statistics are float32; expert FFN compute follows the input
dtype (bf16 on TPU).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MoEConfig:
    dim: int
    ffn_dim: int
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    # 'gather' (scatter/gather dispatch, O(T*D) data movement) or 'einsum'
    # (dense one-hot dispatch, O(T*E*C*D) matmul FLOPs — at bench shapes
    # those einsums cost ~2x the expert FFN itself; kept as the reference
    # implementation the gather path is parity-tested against). Measured
    # single-chip: gather is +51% tokens/s (docs/PERF.md). On large ep
    # meshes the einsum path's all-to-all lowering may reshard better than
    # the gather's all-gather — both stay selectable per config.
    dispatch: str = "gather"

    def capacity(self, n_tokens: int) -> int:
        """Per-expert token slots; static given the (padded) token count."""
        return max(
            1,
            int(math.ceil(self.capacity_factor * self.top_k * n_tokens / self.n_experts)),
        )


def logical_axes() -> dict[str, tuple[str | None, ...]]:
    """Sharding names; "expert" maps to a mesh axis via the rules table."""
    return {
        "router": ("embed", "expert"),
        "w1": ("expert", "embed", "ffn"),
        "w3": ("expert", "embed", "ffn"),
        "w2": ("expert", "ffn", "embed"),
    }


def init_moe_params(rng: jax.Array, cfg: MoEConfig, dtype=jnp.bfloat16) -> dict[str, Any]:
    k0, k1, k2, k3 = jax.random.split(rng, 4)
    d, f, e = cfg.dim, cfg.ffn_dim, cfg.n_experts

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(dtype)

    return {
        "router": dense(k0, (d, e), d).astype(jnp.float32),  # routing in fp32
        "w1": dense(k1, (e, d, f), d),
        "w3": dense(k2, (e, d, f), d),
        "w2": dense(k3, (e, f, d), f),
    }


def _top_k_dispatch(probs: jax.Array, cfg: MoEConfig, capacity: int):
    """Build dispatch/combine tensors from router probabilities.

    probs: [T, E] float32. Returns (dispatch [T,E,C] in {0,1}, combine
    [T,E,C] fp32 gates, aux_loss scalar). Tokens beyond an expert's capacity
    are dropped (their combine weight is zero), the Switch/GShard contract.
    """
    T, E = probs.shape
    remaining = probs
    # occupancy count per expert, accumulated across the k rounds
    occupancy = jnp.zeros((E,), jnp.int32)
    dispatch = jnp.zeros((T, E, capacity), probs.dtype)
    combine = jnp.zeros((T, E, capacity), probs.dtype)
    importance = jnp.zeros((E,), probs.dtype)  # fraction routed, for aux loss

    for _ in range(cfg.top_k):
        idx = jnp.argmax(remaining, axis=-1)                      # [T]
        gate = jnp.take_along_axis(remaining, idx[:, None], -1)[:, 0]
        onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)        # [T,E]
        # position of each token in its expert's queue this round, offset by
        # seats taken in earlier rounds
        pos_in_round = jnp.cumsum(onehot, axis=0) - onehot        # [T,E]
        pos = pos_in_round + occupancy[None, :]
        within = (pos < capacity) & (onehot > 0)
        pos_clipped = jnp.clip(pos.astype(jnp.int32), 0, capacity - 1)
        slot = jax.nn.one_hot(pos_clipped, capacity, dtype=probs.dtype)  # [T,E,C]
        sel = (within.astype(probs.dtype))[..., None] * slot
        dispatch = dispatch + sel
        combine = combine + gate[:, None, None] * sel
        occupancy = occupancy + jnp.sum(onehot, axis=0).astype(jnp.int32)
        importance = importance + jnp.mean(onehot, axis=0)
        remaining = remaining * (1.0 - onehot)                    # mask chosen

    # load-balancing aux loss (Switch eq. 4): E * sum(frac_routed * mean_prob)
    aux = cfg.n_experts * jnp.sum(importance / cfg.top_k * jnp.mean(probs, axis=0))
    # renormalise combine weights over the selected experts
    denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    return dispatch, combine, aux


def _top_k_routes(probs: jax.Array, cfg: MoEConfig, capacity: int):
    """Per-round routing decisions without materialising [T,E,C] tensors.

    probs: [T, E] float32. Returns (rounds, aux) where rounds is a list of
    ``(idx [T] int32, gate [T] fp32, pos [T] int32, valid [T] bool)`` — the
    chosen expert, its gate value, the token's position in that expert's
    queue, and whether it is within capacity. Identical selection/drop
    semantics to the one-hot reference path (same argmax order, same
    occupancy-offset positions)."""
    T, E = probs.shape
    remaining = probs
    occupancy = jnp.zeros((E,), jnp.int32)
    importance = jnp.zeros((E,), probs.dtype)
    rounds = []
    for _ in range(cfg.top_k):
        idx = jnp.argmax(remaining, axis=-1)                      # [T]
        gate = jnp.take_along_axis(remaining, idx[:, None], -1)[:, 0]
        onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)        # [T,E]
        pos_in_round = (jnp.cumsum(onehot, axis=0) - onehot).astype(jnp.int32)
        pos = (
            jnp.take_along_axis(pos_in_round, idx[:, None], -1)[:, 0]
            + occupancy[idx]
        )
        valid = pos < capacity
        rounds.append((idx.astype(jnp.int32), gate, pos, valid))
        occupancy = occupancy + jnp.sum(onehot, axis=0).astype(jnp.int32)
        importance = importance + jnp.mean(onehot, axis=0)
        remaining = remaining * (1.0 - onehot)
    aux = cfg.n_experts * jnp.sum(importance / cfg.top_k * jnp.mean(probs, axis=0))
    return rounds, aux


def _moe_gather(params: dict[str, Any], flat: jax.Array, cfg: MoEConfig,
                capacity: int, probs: jax.Array):
    """Scatter/gather dispatch: build the slot->token index map (one scatter
    of int32), gather tokens into [E,C,D], run the expert FFN, and gather
    each token's expert outputs back with gate weighting. Data movement is
    O(E*C*D + k*T*D) with ZERO routing matmul FLOPs — vs the one-hot
    einsums' 2*T*E*C*D FLOPs each way, which at bench shapes (T=8192, E=4,
    C=5120, D=1024) cost ~2x the expert FFN itself (the measured reason
    behind the round-3 22% MoE MFU; docs/PERF.md)."""
    T, D = flat.shape
    E = cfg.n_experts
    rounds, aux = _top_k_routes(probs, cfg, capacity)

    # slot -> token map; sentinel T points at a zero pad row (empty slots)
    slot_token = jnp.full((E * capacity,), T, jnp.int32)
    arange_t = jnp.arange(T, dtype=jnp.int32)
    for idx, _, pos, valid in rounds:
        flat_slot = idx * capacity + jnp.clip(pos, 0, capacity - 1)
        target = jnp.where(valid, flat_slot, E * capacity)  # OOB -> dropped
        slot_token = slot_token.at[target].set(arange_t, mode="drop")

    padded = jnp.concatenate([flat, jnp.zeros((1, D), flat.dtype)], axis=0)
    expert_in = padded[slot_token].reshape(E, capacity, D)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"]))
    h = h * jnp.einsum("ecd,edf->ecf", expert_in, params["w3"])
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w2"])

    # combine: each token gathers its (<= k) expert outputs, gate-weighted
    # and renormalised over the experts that actually kept it
    denom = sum(
        gate * valid.astype(gate.dtype) for _, gate, _, valid in rounds
    )
    denom = jnp.maximum(denom, 1e-9)
    out_flat = expert_out.reshape(E * capacity, D)
    y = jnp.zeros((T, D), flat.dtype)
    for idx, gate, pos, valid in rounds:
        flat_slot = idx * capacity + jnp.clip(pos, 0, capacity - 1)
        tok_out = out_flat[jnp.where(valid, flat_slot, 0)]
        w = (gate * valid.astype(gate.dtype) / denom).astype(flat.dtype)
        y = y + w[:, None] * tok_out
    return y, aux


def moe_block(params: dict[str, Any], x: jax.Array, cfg: MoEConfig):
    """MoE SwiGLU FFN. x: [B, S, D] -> (y [B, S, D], aux_loss scalar).

    Dropped (over-capacity) tokens pass through with a zero FFN delta, so the
    residual connection outside this block keeps their representation.
    """
    B, S, D = x.shape
    T = B * S
    flat = x.reshape(T, D)
    capacity = cfg.capacity(T)

    logits = flat.astype(jnp.float32) @ params["router"]
    probs = jax.nn.softmax(logits, axis=-1)

    if cfg.dispatch == "gather":
        y, aux = _moe_gather(params, flat, cfg, capacity, probs)
        return y.reshape(B, S, D), aux
    if cfg.dispatch != "einsum":
        raise ValueError(f"unknown MoE dispatch {cfg.dispatch!r}")

    dispatch, combine, aux = _top_k_dispatch(probs, cfg, capacity)
    # [T,E,C]x[T,D] -> [E,C,D]: the EP all-to-all happens inside this einsum
    # when "expert" is mesh-sharded.
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), flat)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"]))
    h = h * jnp.einsum("ecd,edf->ecf", expert_in, params["w3"])
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w2"])
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return y.reshape(B, S, D), aux


__all__ = ["MoEConfig", "init_moe_params", "logical_axes", "moe_block"]
